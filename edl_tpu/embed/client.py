"""Client side of the embedding plane: dedup, hot cache, overlap.

:class:`EmbedPlaneClient` is the trainer-facing surface. One
``lookup(table, keys)`` is, on the optimized path:

1. **dedup** — ``np.unique`` collapses the batch's duplicate keys (the
   zipf head makes this a large factor) and yields the inverse map for
   the final scatter back to slot order;
2. **cache** — the :class:`~edl_tpu.embed.cache.HotKeyCache` absorbs
   unique keys it holds; only true misses cross the wire;
3. **hot tier** — misses in the advertised hot set route to their
   capacity-weighted consistent-hash replica (``embed.hot_lookup``,
   version-checked; a stale or dead replica falls back to the owner);
4. **coalesce** — the remaining misses, already sorted, partition into
   per-owner contiguous runs and leave as ONE pipelined batched-gather
   RPC per owner (``ClientPool.call_async``), all in flight at once;
5. **fence** — each owner's response carries its table version and the
   keys OTHER writers touched since this client's watermark; any such
   key that was served from cache in this same batch is invalidated
   and refetched before the batch is returned (counted as a
   ``stale_refetch`` — a fenced row is never served), and the
   watermark advances;
6. **scatter** — rows land in unique order and ``inverse`` scatters
   them to slot order. A short or missing response is a typed
   :class:`~edl_tpu.utils.errors.EmbedLookupError`, never silent
   zeros.

``writeback(table, keys, grads, lr)`` accumulates duplicate-slot
gradients per unique key (``np.add.at``), ships one fused
``rows -= lr * acc`` per owner, and **write-through** applies the same
float32 subtract to the cached copies — so cached bytes equal served
bytes with no refetch.

Failed coalesced RPCs are requeued under a
:class:`~edl_tpu.robustness.policy.RetryPolicy` (chaos points
``embed.lookup`` / ``embed.writeback`` fire INSIDE the retried
closure, so an armed ``error_once`` proves fail→requeue→exact-result);
retries are counted exactly (``edl_embed_*_retries_total``).

Consistency model: a single writer sees its own writes exactly
(write-through + fencing); concurrent writers are fenced on every
owner round-trip. Hot-tier serves are additionally marked cache-served
so an owner response in the same batch fences them too; a batch served
ENTIRELY by replicas is bounded-stale by one advertisement period (the
Kraken trade).

:class:`EmbedPrefetcher` is the overlap half: a worker thread runs
batch i+1's ``lookup`` while the training thread computes batch i;
``wait()`` charges only the residual join to the new ``embed_wait``
TimeLedger state. The worker must NOT touch the process ledger —
background concurrency is not the training thread's lost time.
Prefetched rows reflect the table before the overlapped step's
writeback lands (bounded staleness 1, the async parameter-server
regime); the cache's version guard keeps a late prefetch from rolling
cached rows back.
"""

import queue
import threading
import time
from collections import deque

import numpy as np

from edl_tpu.distill.consistent_hash import ConsistentHash
from edl_tpu.embed import sharding
from edl_tpu.embed.cache import HotKeyCache, HotSetTracker
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs.ledger import LEDGER
from edl_tpu.robustness import faults
from edl_tpu.robustness.policy import RetryPolicy
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger

LOOKUP_MS = obs_metrics.histogram(
    "edl_embed_lookup_ms", "wall time of one batch embedding lookup "
    "(dedup + cache + gather + scatter)")
WRITEBACK_MS = obs_metrics.histogram(
    "edl_embed_writeback_ms", "wall time of one batch sparse "
    "optimizer write-back")
UNIQUE_FRAC = obs_metrics.gauge(
    "edl_embed_unique_key_frac", "unique/total key fraction of the "
    "last looked-up batch (zipf head collapse)")
LOOKUP_RETRIES = obs_metrics.counter(
    "edl_embed_lookup_retries_total", "coalesced gather RPCs requeued "
    "after a failure")
WRITEBACK_RETRIES = obs_metrics.counter(
    "edl_embed_writeback_retries_total", "write-back RPCs requeued "
    "after a failure")
HOT_HITS = obs_metrics.counter(
    "edl_embed_hot_tier_hits_total", "lookups served by a replicated "
    "hot-tier node instead of the owner")


class EmbedPlaneClient(object):
    """One trainer's handle on the sharded tables (module docstring).

    ``endpoints`` maps member id -> RPC endpoint (the owner set);
    ``pool`` is the shared :class:`~edl_tpu.rpc.pool.ClientPool`. The
    table map (vocab, dim per table) comes from ``embed.manifest`` of
    any member. ``cache_entries=0`` disables the cache tier;
    ``dedup=False`` is the NAIVE arc: one RPC per key, no dedup, no
    cache — kept as a first-class mode so rec_bench's baseline is the
    real code path, not a simulation."""

    def __init__(self, pool, endpoints, client_id="trainer-0",
                 cache_entries=0, dedup=True, capacities=None,
                 retry=None, decay_every=64):
        self._pool = pool
        self._client_id = str(client_id)
        self._dedup = bool(dedup)
        self._lock = threading.Lock()
        self._retry = retry if retry is not None else RetryPolicy(
            max_attempts=4, base_delay=0.02, max_delay=0.5, seed=0)
        self._cache = (HotKeyCache(cache_entries) if cache_entries
                       else None)
        self._tracker = HotSetTracker(decay_every=decay_every)
        self._capacities = dict(capacities or {})
        self._hot_ring = ConsistentHash()
        self._hot_keys = {}    # table -> set of advertised hot keys
        self._since = {}       # (table, member) -> watermark version
        self._lookups = 0
        self._keys_total = 0
        self._unique_total = 0
        self._writebacks = 0
        self._retries = 0
        self._adopt(dict(endpoints))
        self._tables = self._load_manifest()

    # -- membership --------------------------------------------------------

    def _adopt(self, endpoints):
        self._endpoints = {str(m): e for m, e in endpoints.items()}
        self._members = sorted(self._endpoints)
        self._hot_ring.update(self._members, weights=self._capacities)

    def _load_manifest(self):
        man = self._pool.call(self._endpoints[self._members[0]],
                              "embed.manifest")
        if sorted(man["members"]) != self._members:
            raise errors.StaleStateError(
                "embed manifest members %r != client view %r"
                % (sorted(man["members"]), self._members))
        return {name: (int(t["vocab"]), int(t["dim"]))
                for name, t in man["tables"].items()}

    def resize(self, endpoints):
        """Adopt a post-reshard member view. Rows changed owners, so
        everything keyed on the old layout goes: watermarks reset (the
        servers raised their log floors anyway), the cache drops
        wholesale, and the hot set must be re-advertised against the
        new ring."""
        with self._lock:
            self._adopt(dict(endpoints))
            self._since.clear()
            self._hot_keys.clear()
        if self._cache is not None:
            self._cache.invalidate()
        self._tables = self._load_manifest()
        logger.info("embed client %s: adopted %d-member layout",
                    self._client_id, len(self._members))

    def tables(self):
        return dict(self._tables)

    # -- plumbing ----------------------------------------------------------

    def _watermark(self, table, owner):
        with self._lock:
            return self._since.get((table, owner), 0)

    def _advance(self, table, owner, version):
        with self._lock:
            key = (table, owner)
            if version > self._since.get(key, 0):
                self._since[key] = version

    def _attempt(self, method, table, owner, args):
        """One attempt of one coalesced RPC: the chaos point fires
        before the request leaves (INSIDE the retried path), then the
        call goes out synchronously."""
        if faults.PLANE is not None:
            faults.PLANE.fire(method, table=table, member=owner,
                              endpoint=self._endpoints[owner])
        return self._pool.call(self._endpoints[owner], method, table,
                               *args)

    def _requeue(self, method, table, owner, args, first_err, err_cls,
                 counter):
        """A failed coalesced RPC is requeued under the retry policy;
        every extra attempt is counted exactly. Exhausting the budget
        raises the typed error — the step fails loudly, rows are never
        fabricated."""
        def note(_attempt, _exc):
            with self._lock:
                self._retries += 1
            counter.inc()
        with self._lock:
            self._retries += 1
        counter.inc()
        try:
            return self._retry.call(
                lambda: self._attempt(method, table, owner, args),
                on_retry=note)
        except errors.EdlError as e:
            raise err_cls(
                "%s to %s failed after retries: %r (first: %r)"
                % (method, owner, e, first_err)) from e

    def _gather_round(self, method, table, parts, extra_of, err_cls,
                      counter):
        """Issue one pipelined RPC per owner (all in flight at once),
        then collect — failures drop to the requeue path. Yields
        ``(owner, keys, result)`` in owner order."""
        pending = []
        for owner, kslice in parts:
            args = (kslice,) + tuple(extra_of(owner, kslice))
            fut = err = None
            try:
                if faults.PLANE is not None:
                    faults.PLANE.fire(method, table=table, member=owner,
                                      endpoint=self._endpoints[owner])
                fut = self._pool.call_async(self._endpoints[owner],
                                            method, table, *args)
            except errors.EdlError as e:
                err = e
            pending.append((owner, kslice, args, fut, err))
        out = []
        for owner, kslice, args, fut, err in pending:
            res = None
            if fut is not None:
                try:
                    res = fut.result()
                except errors.EdlError as e:
                    err = e
            if res is None:
                res = self._requeue(method, table, owner, args, err,
                                    err_cls, counter)
            out.append((owner, kslice, res))
        return out

    @staticmethod
    def _check_rows(table, owner, keys, rows, dim):
        rows = np.asarray(rows, np.float32)
        if rows.shape != (keys.size, dim):
            raise errors.EmbedLookupError(
                "embed.lookup %s from %s: got %s rows for %d keys — "
                "refusing to zero-fill" % (table, owner,
                                           rows.shape, keys.size))
        return rows

    # -- lookup ------------------------------------------------------------

    def lookup(self, table, keys):
        """Rows for ``keys`` in slot order, ``[len(keys), dim]``."""
        keys = np.asarray(keys, np.int64).reshape(-1)
        vocab, dim = self._tables[table]
        if keys.size == 0:
            return np.empty((0, dim), np.float32)
        with LOOKUP_MS.time_ms():
            if not self._dedup:
                return self._lookup_naive(table, keys, vocab, dim)
            return self._lookup_fast(table, keys, vocab, dim)

    def _lookup_naive(self, table, keys, vocab, dim):
        """The baseline arc: one RPC per SLOT (duplicates and all) —
        pipelined so it measures per-request overhead, not client
        serialization."""
        n = len(self._members)
        out = np.empty((keys.size, dim), np.float32)
        pending = []
        for i, k in enumerate(keys):
            owner = self._members[int(
                sharding.owner_index(int(k), vocab, n))]
            one = np.array([k], np.int64)
            fut = err = None
            try:
                if faults.PLANE is not None:
                    faults.PLANE.fire("embed.lookup", table=table,
                                      member=owner,
                                      endpoint=self._endpoints[owner])
                fut = self._pool.call_async(
                    self._endpoints[owner], "embed.lookup", table, one,
                    self._watermark(table, owner), self._client_id)
            except errors.EdlError as e:
                err = e
            pending.append((i, owner, one, fut, err))
        for i, owner, one, fut, err in pending:
            res = None
            if fut is not None:
                try:
                    res = fut.result()
                except errors.EdlError as e:
                    err = e
            if res is None:
                res = self._requeue(
                    "embed.lookup", table, owner,
                    (one, self._watermark(table, owner),
                     self._client_id), err, errors.EmbedLookupError,
                    LOOKUP_RETRIES)
            out[i] = self._check_rows(table, owner, one, res["rows"],
                                      dim)[0]
            self._advance(table, owner, int(res["version"]))
        with self._lock:
            self._lookups += 1
            self._keys_total += keys.size
            self._unique_total += keys.size
        UNIQUE_FRAC.set(1.0)
        return out

    def _lookup_fast(self, table, keys, vocab, dim):
        uniq, inv, counts = np.unique(keys, return_inverse=True,
                                      return_counts=True)
        with self._lock:
            self._lookups += 1
            self._keys_total += keys.size
            self._unique_total += uniq.size
        UNIQUE_FRAC.set(uniq.size / keys.size)
        self._tracker.observe(uniq, counts)
        urows = np.empty((uniq.size, dim), np.float32)
        filled = np.zeros(uniq.size, bool)
        cache_served = np.zeros(uniq.size, bool)
        if self._cache is not None:
            hits, miss = self._cache.get_many(table, uniq)
            for pos in np.flatnonzero(~miss):
                urows[pos] = hits[int(uniq[pos])]
            filled[~miss] = True
            cache_served[~miss] = True
        need_pos = np.flatnonzero(~filled)
        # hot-tier routing for advertised keys among the misses
        need_pos = self._hot_round(table, uniq, need_pos, urows,
                                   filled, cache_served, vocab, dim)
        # coalesced owner gathers for what remains
        touched_all = set()
        wholesale = False
        contacted = set()
        if need_pos.size:
            need = uniq[need_pos]
            parts = sharding.partition_by_owner(need, vocab,
                                                self._members)
            results = self._gather_round(
                "embed.lookup", table, parts,
                lambda owner, ks: (self._watermark(table, owner),
                                   self._client_id),
                errors.EmbedLookupError, LOOKUP_RETRIES)
            for owner, kslice, res in results:
                contacted.add(owner)
                rows = self._check_rows(table, owner, kslice,
                                        res["rows"], dim)
                version = int(res["version"])
                pos = np.searchsorted(uniq, kslice)
                urows[pos] = rows
                filled[pos] = True
                if self._cache is not None:
                    self._cache.put_many(table, kslice, rows, version)
                t = res.get("touched")
                if t is None:
                    wholesale = True
                else:
                    touched_all.update(
                        int(x) for x in np.asarray(t).reshape(-1))
                self._advance(table, owner, version)
        # An owner whose keys were ALL served locally was never
        # contacted, so its touch log could not reach us. Probe it with
        # an empty gather (one tiny RPC per such owner, pipelined like
        # any part) so the fence below sees every writer — exactness
        # must not depend on this batch happening to miss.
        if cache_served.any():
            n = len(self._members)
            served_owners = {
                self._members[int(i)] for i in np.atleast_1d(
                    sharding.owner_index(uniq[cache_served], vocab, n))}
            probes = [(owner, np.empty(0, np.int64))
                      for owner in sorted(served_owners - contacted)]
            if probes:
                for owner, _, res in self._gather_round(
                        "embed.lookup", table, probes,
                        lambda owner, ks: (self._watermark(table, owner),
                                           self._client_id),
                        errors.EmbedLookupError, LOOKUP_RETRIES):
                    t = res.get("touched")
                    if t is None:
                        wholesale = True
                    else:
                        touched_all.update(
                            int(x) for x in np.asarray(t).reshape(-1))
                    self._advance(table, owner, int(res["version"]))
        if not filled.all():
            raise errors.EmbedLookupError(
                "embed %s: %d unique keys unserved — refusing to "
                "zero-fill" % (table, int((~filled).sum())))
        # version fence: cache-served keys a concurrent writer touched
        # are refetched IN THIS BATCH — a fenced row is never returned
        self._fence_round(table, uniq, urows, cache_served,
                          touched_all, wholesale, vocab, dim)
        return urows[inv]

    def _hot_round(self, table, uniq, need_pos, urows, filled,
                   cache_served, vocab, dim):
        """Serve advertised hot keys from their consistent-hash
        replicas. Partial and best-effort by contract: anything a
        replica cannot answer at the fenced version (or a dead replica
        entirely) stays in the miss set and rides the owner path."""
        hot = self._hot_keys.get(table)
        if not hot or need_pos.size == 0:
            return need_pos
        n = len(self._members)
        groups = {}  # replica -> (positions list, min_version)
        for pos in need_pos:
            k = int(uniq[pos])
            if k not in hot:
                continue
            replica, _ = self._hot_ring.get_node(
                "hot:%s:%d" % (table, k))
            owner = self._members[int(sharding.owner_index(k, vocab, n))]
            if replica is None or replica == owner:
                continue
            plist, minv = groups.setdefault(replica, ([], 0))
            plist.append(pos)
            groups[replica] = (plist, max(minv, self._watermark(
                table, owner)))
        for replica, (plist, minv) in groups.items():
            ks = np.array([int(uniq[p]) for p in plist], np.int64)
            try:
                res = self._pool.call(self._endpoints[replica],
                                      "embed.hot_lookup", table, ks,
                                      minv)
            except errors.EdlError:
                continue  # dead replica: the owner path covers it
            found = np.asarray(res["found"], bool)
            rows = np.asarray(res["rows"], np.float32)
            got = 0
            for j, p in enumerate(plist):
                if not found[j]:
                    continue
                urows[p] = rows[got]
                filled[p] = True
                # replica serves ride the same fence as cache serves
                cache_served[p] = True
                got += 1
            if got:
                HOT_HITS.inc(got)
                if self._cache is not None:
                    self._cache.put_many(table, ks[found],
                                         rows[:got], minv)
        return np.flatnonzero(~filled)

    def _fence_round(self, table, uniq, urows, cache_served,
                     touched_all, wholesale, vocab, dim):
        if self._cache is None and not wholesale:
            return
        if wholesale:
            suspect = np.flatnonzero(cache_served)
            if self._cache is not None:
                # the log no longer covers our watermark (truncation or
                # reshard): nothing cached is provably fresh
                self._cache.invalidate(table)
        else:
            if not touched_all:
                return
            suspect = np.flatnonzero(
                cache_served
                & np.isin(uniq, np.fromiter(touched_all, np.int64)))
        if suspect.size == 0:
            return
        stale_keys = uniq[suspect]
        if self._cache is not None and not wholesale:
            self._cache.invalidate(table, keys=stale_keys, stale=True)
        parts = sharding.partition_by_owner(stale_keys, vocab,
                                            self._members)
        results = self._gather_round(
            "embed.lookup", table, parts,
            lambda owner, ks: (self._watermark(table, owner),
                               self._client_id),
            errors.EmbedLookupError, LOOKUP_RETRIES)
        for owner, kslice, res in results:
            rows = self._check_rows(table, owner, kslice, res["rows"],
                                    dim)
            version = int(res["version"])
            pos = np.searchsorted(uniq, kslice)
            urows[pos] = rows
            if self._cache is not None:
                self._cache.put_many(table, kslice, rows, version)
            self._advance(table, owner, version)

    # -- write-back --------------------------------------------------------

    def writeback(self, table, keys, grads, lr):
        """Sparse optimizer step: ``row[k] -= lr * sum(grads at k)``.

        Duplicate-slot gradients are accumulated per unique key HERE
        (``np.add.at``), so the owner applies one fused subtract per
        key — the exact float math of a single-host reference step —
        and the write-through to the cache repeats the identical
        subtract, keeping cached bytes equal to served bytes."""
        keys = np.asarray(keys, np.int64).reshape(-1)
        vocab, dim = self._tables[table]
        grads = np.asarray(grads, np.float32).reshape(keys.size, dim)
        if keys.size == 0:
            return
        with WRITEBACK_MS.time_ms():
            uniq, inv = np.unique(keys, return_inverse=True)
            acc = np.zeros((uniq.size, dim), np.float32)
            np.add.at(acc, inv, grads)
            parts = sharding.partition_by_owner(uniq, vocab,
                                                self._members)
            results = self._gather_round(
                "embed.writeback", table, parts,
                lambda owner, ks: (
                    acc[np.searchsorted(uniq, ks)], np.float32(lr),
                    self._watermark(table, owner), self._client_id),
                errors.EmbedWritebackError, WRITEBACK_RETRIES)
            touched_all = set()
            wholesale = False
            for owner, kslice, res in results:
                version = int(res["version"])
                if self._cache is not None:
                    deltas = (np.float32(lr)
                              * acc[np.searchsorted(uniq, kslice)])
                    self._cache.apply_update(table, kslice, deltas,
                                             version)
                t = res.get("touched")
                if t is None:
                    wholesale = True
                else:
                    touched_all.update(
                        int(x) for x in np.asarray(t).reshape(-1))
                self._advance(table, owner, version)
            with self._lock:
                self._writebacks += 1
            if self._cache is not None:
                if wholesale:
                    self._cache.invalidate(table)
                elif touched_all:
                    # other writers' keys: drop, the next lookup
                    # refetches them fresh
                    self._cache.invalidate(
                        table,
                        keys=np.fromiter(touched_all, np.int64))

    # -- hot-set advertisement ---------------------------------------------

    def push_hot(self, table, n):
        """Advertise the measured hot set: fetch the ``n`` hottest
        rows fresh from their owners (stamped with the owner version)
        and push each to its capacity-weighted consistent-hash replica
        (``embed.hot_put``; keys whose replica IS the owner are
        skipped — the owner already serves them). Returns the number
        of keys now advertised. Call periodically (the bench does it
        every resync period); between calls the tier is bounded-stale
        by the owner-version check on every hot_lookup."""
        vocab, dim = self._tables[table]
        top = np.array(sorted(int(k) for k in self._tracker.top(n)),
                       np.int64)
        if top.size == 0:
            return 0
        nmem = len(self._members)
        results = self._gather_round(
            "embed.lookup", table,
            sharding.partition_by_owner(top, vocab, self._members),
            lambda owner, ks: (self._watermark(table, owner),
                               self._client_id),
            errors.EmbedLookupError, LOOKUP_RETRIES)
        advertised = set()
        for owner, kslice, res in results:
            rows = self._check_rows(table, owner, kslice, res["rows"],
                                    dim)
            version = int(res["version"])
            self._advance(table, owner, version)
            if self._cache is not None:
                self._cache.put_many(table, kslice, rows, version)
            groups = {}
            for j, k in enumerate(kslice):
                replica, _ = self._hot_ring.get_node(
                    "hot:%s:%d" % (table, int(k)))
                if replica is None or replica == owner:
                    advertised.add(int(k))
                    continue
                groups.setdefault(replica, []).append(j)
            for replica, idxs in groups.items():
                try:
                    self._pool.call(
                        self._endpoints[replica], "embed.hot_put",
                        table, kslice[idxs], rows[idxs], version)
                    advertised.update(int(kslice[j]) for j in idxs)
                except errors.EdlError as e:
                    logger.warning("embed hot_put to %s failed: %r "
                                   "(keys stay owner-served)",
                                   replica, e)
        with self._lock:
            self._hot_keys[table] = advertised
        return len(advertised)

    # -- introspection -----------------------------------------------------

    def stats(self):
        with self._lock:
            stats = {
                "lookups": self._lookups,
                "writebacks": self._writebacks,
                "keys_total": self._keys_total,
                "unique_total": self._unique_total,
                "unique_key_frac": (self._unique_total
                                    / self._keys_total
                                    if self._keys_total else None),
                "retries": self._retries,
                "members": len(self._members),
                "hot_advertised": sum(len(s) for s
                                      in self._hot_keys.values()),
            }
        p99 = LOOKUP_MS.percentile(0.99)
        if p99 is not None:
            stats["lookup_p99_ms"] = p99
        if self._cache is not None:
            for k, v in self._cache.stats().items():
                stats["cache_%s" % k] = v
        stats = {k: v for k, v in stats.items() if v is not None}
        return obs_metrics.mirror_stats("edl_embed", stats)

    def cache(self):
        return self._cache

    def tracker(self):
        return self._tracker


class EmbedPrefetcher(object):
    """Double-buffered lookup–compute overlap (module docstring).

    ``submit(keys)`` hands batch i+1's lookup to the worker thread;
    ``wait()`` (training thread only) joins the oldest outstanding
    lookup, charging the residual to the ``embed_wait`` ledger state —
    with the pipeline warm that residual is near zero, which is
    exactly what rec_bench's overlap arc gates."""

    def __init__(self, client, table):
        self._client = client
        self._table = table
        self._q = queue.Queue()
        self._pending = deque()
        self._lock = threading.Lock()
        self.waits = 0
        self.wait_s = 0.0
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="embed-prefetch")
        self._worker.start()

    def _run(self):
        # NOTE: no LEDGER marks here — the ledger models the TRAINING
        # thread's wall clock; this thread's time is the overlap win.
        while True:
            item = self._q.get()
            if item is None:
                return
            keys, ticket = item
            try:
                ticket[0] = self._client.lookup(self._table, keys)
            except BaseException as e:  # noqa: BLE001 — surfaced at wait()
                ticket[1] = e
            ticket[2].set()

    def submit(self, keys):
        """Queue one batch's lookup; FIFO with :meth:`wait`."""
        ticket = [None, None, threading.Event()]
        self._pending.append(ticket)
        self._q.put((np.asarray(keys, np.int64).reshape(-1), ticket))

    def depth(self):
        return len(self._pending)

    def wait(self):
        """Rows of the oldest submitted batch; the join (and only the
        join) is accounted as ``embed_wait``."""
        if not self._pending:
            raise errors.StatusError("EmbedPrefetcher.wait with no "
                                     "submitted batch")
        ticket = self._pending.popleft()
        t0 = time.perf_counter()
        with LEDGER.state("embed_wait"):
            ticket[2].wait()
        with self._lock:
            self.waits += 1
            self.wait_s += time.perf_counter() - t0
        if ticket[1] is not None:
            raise ticket[1]
        return ticket[0]

    def stats(self):
        with self._lock:
            return {"waits": self.waits, "wait_s": self.wait_s,
                    "outstanding": len(self._pending)}

    def close(self):
        self._q.put(None)
        self._worker.join(timeout=10)
