"""Elastic sharded embedding plane for skewed CTR traffic.

Parameter-server-style embedding tables (Li et al., OSDI'14) on the
in-tree planes: table rows live host-side across pods, sharded by row
span via the same ``costmodel.device_spans`` machinery the state plane
uses, and served over the v2 tensor-frame RPC substrate. Three stacked
perf optimisations, each proven by a ``rec_bench/v1`` arc
(:mod:`edl_tpu.tools.rec_bench`):

- **dedup + coalesce** — per-batch unique-key extraction and sort, ONE
  pipelined batched-gather RPC per owner pod (ClientPool,
  ``call_async``), scatter back to slot order;
- **hot-key cache tier** — a client LRU for the zipf head with
  write-through updates and version fencing, plus a replicated hot
  tier for the hottest keys routed by a capacity-weighted consistent
  hash (à la Kraken, ISCA'22);
- **lookup–compute overlap** — double-buffered prefetch issuing batch
  i+1's gathers while batch i's dense step runs, accounted as the
  ``embed_wait`` TimeLedger state.

Tables are *elastic*: a resize reshards row spans through span-overlap
paste + peer range-reads, byte-identical to stop-resume (bench-gated).

See docs/recommender.md for the design and runbook.
"""

from edl_tpu.embed.cache import HotKeyCache, HotSetTracker  # noqa: F401
from edl_tpu.embed.client import (EmbedPlaneClient,  # noqa: F401
                                  EmbedPrefetcher)
from edl_tpu.embed.sharding import (owner_index,  # noqa: F401
                                    partition_by_owner, reshard_moves,
                                    row_spans)
from edl_tpu.embed.table import EmbedShardServer, TableSpec  # noqa: F401
