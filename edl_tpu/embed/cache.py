"""Hot-key cache tier: client LRU with write-through + hot-set tracking.

The zipf head is the whole game for CTR lookup traffic (Li et al.,
OSDI'14 measured >90% of accesses hitting <10% of keys): a small LRU
over (table, key) -> row absorbs the head so only the tail crosses the
wire. Two coherence rules keep a cached row from ever being SERVED
stale:

- **write-through**: the client applies its own optimizer deltas to
  the cached copies of the keys it wrote back — the exact same
  ``row -= lr * grad`` the owner applies, so the cached bytes equal
  the served bytes without a refetch;
- **version fencing**: every entry is stamped with the owner's table
  version at fetch/update time, and the lookup protocol returns the
  keys OTHER writers touched since the client's watermark
  (:meth:`EmbedPlaneClient.lookup` refetches any of those it served
  from cache in the same batch — see client.py). ``put`` never lets an
  older fetch overwrite a newer stamp, so a slow prefetch landing
  after a write-through cannot roll a row back.

:class:`HotSetTracker` measures the head empirically (decayed access
counts) — its top-k is what the owner pushes to the replicated hot
tier and what ``rec_bench`` compares against the predicted head mass.
"""

import heapq
import threading
from collections import OrderedDict

import numpy as np

from edl_tpu.obs import metrics as obs_metrics

CACHE_HITS = obs_metrics.counter(
    "edl_embed_cache_hits_total", "embedding lookups served from the "
    "hot-key cache")
CACHE_MISSES = obs_metrics.counter(
    "edl_embed_cache_misses_total", "embedding lookups that crossed "
    "the wire")
CACHE_EVICTIONS = obs_metrics.counter(
    "edl_embed_cache_evictions_total", "hot-key cache LRU evictions")
CACHE_STALE = obs_metrics.counter(
    "edl_embed_cache_stale_refetch_total", "cache entries version-"
    "fenced stale by a concurrent writer and refetched")


class HotKeyCache(object):
    """Thread-safe LRU over ``(table, key) -> (row, version)``.

    ``capacity`` counts entries (a row is one fixed-size ndarray; the
    byte budget is ``capacity * dim * 4`` and the caller sizes it).
    Thread safety matters because the overlap prefetcher's worker
    thread fills the cache while the training thread write-throughs."""

    def __init__(self, capacity):
        self._capacity = int(capacity)
        self._lock = threading.Lock()
        self._entries = OrderedDict()  # (table, key) -> [row, version]
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.stale_refetches = 0

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def get_many(self, table, keys):
        """Partition sorted-unique ``keys``: ``(hit_rows, miss_mask)``
        where ``hit_rows`` maps key -> row COPY (the caller scatters it
        into a batch buffer; a copy keeps a concurrent write-through
        from mutating a row mid-scatter) and ``miss_mask`` is a bool
        array over ``keys`` marking the ones that must cross the wire."""
        hits = {}
        miss = np.ones(len(keys), bool)
        with self._lock:
            for i, k in enumerate(keys):
                ent = self._entries.get((table, int(k)))
                if ent is None:
                    continue
                self._entries.move_to_end((table, int(k)))
                hits[int(k)] = ent[0].copy()
                miss[i] = False
            self.hits += len(hits)
            self.misses += int(miss.sum())
        CACHE_HITS.inc(len(hits))
        CACHE_MISSES.inc(int(miss.sum()))
        return hits, miss

    def put_many(self, table, keys, rows, version):
        """Insert fetched rows stamped with the owner ``version``. An
        existing entry with a NEWER stamp wins (a prefetch that raced a
        write-through must not resurrect the pre-update row)."""
        evicted = 0
        with self._lock:
            for k, row in zip(keys, rows):
                tk = (table, int(k))
                ent = self._entries.get(tk)
                if ent is not None and ent[1] > version:
                    continue
                self._entries[tk] = [np.array(row, copy=True), version]
                self._entries.move_to_end(tk)
            while len(self._entries) > self._capacity:
                self._entries.popitem(last=False)
                evicted += 1
            self.evictions += evicted
        if evicted:
            CACHE_EVICTIONS.inc(evicted)

    def apply_update(self, table, keys, deltas, version):
        """Write-through: ``row -= delta`` on the cached copies of
        ``keys`` (missing keys are skipped — absence is a miss, never
        an error), restamped to the post-writeback ``version``."""
        with self._lock:
            for k, delta in zip(keys, deltas):
                ent = self._entries.get((table, int(k)))
                if ent is not None:
                    ent[0] -= delta
                    ent[1] = version

    def invalidate(self, table=None, keys=None, stale=False):
        """Drop entries: everything, one table, or specific keys.
        ``stale=True`` counts the drops as version-fence refetches
        (the caller is about to fetch them fresh)."""
        dropped = 0
        with self._lock:
            if table is None:
                dropped = len(self._entries)
                self._entries.clear()
            elif keys is None:
                for tk in [tk for tk in self._entries
                           if tk[0] == table]:
                    del self._entries[tk]
                    dropped += 1
            else:
                for k in keys:
                    if self._entries.pop((table, int(k)),
                                         None) is not None:
                        dropped += 1
            if stale:
                self.stale_refetches += dropped
        if stale and dropped:
            CACHE_STALE.inc(dropped)
        return dropped

    def stats(self):
        with self._lock:
            looked = self.hits + self.misses
            return {"entries": len(self._entries),
                    "capacity": self._capacity,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "stale_refetches": self.stale_refetches,
                    "hit_rate": (self.hits / looked) if looked else None}


class HotSetTracker(object):
    """Decayed access counts -> the measured hot set.

    ``observe(keys, counts)`` folds one deduped batch in;  every
    ``decay_every`` batches all counts are halved, so the top-k tracks
    the RECENT head (a key that went cold decays out in
    ``O(log count)`` windows instead of squatting forever)."""

    def __init__(self, decay_every=64):
        self._lock = threading.Lock()
        self._counts = {}  # key -> decayed count
        self._decay_every = int(decay_every)
        self._batches = 0

    def observe(self, keys, counts=None):
        with self._lock:
            if counts is None:
                counts = np.ones(len(keys))
            for k, c in zip(keys, counts):
                k = int(k)
                self._counts[k] = self._counts.get(k, 0.0) + float(c)
            self._batches += 1
            if self._batches % self._decay_every == 0:
                self._counts = {k: c / 2.0
                                for k, c in self._counts.items()
                                if c >= 1.0}

    def top(self, n):
        """The ``n`` hottest keys, hottest first (ties by key for
        determinism)."""
        with self._lock:
            best = heapq.nlargest(
                int(n), ((c, -k) for k, c in self._counts.items()))
        return [-nk for _, nk in best]

    def count(self, key):
        with self._lock:
            return self._counts.get(int(key), 0.0)
