"""Robustness layer for the elastic control plane.

Two halves, one contract:

- :mod:`edl_tpu.robustness.faults` — a deterministic, seeded
  fault-injection registry (the "chaos plane"). Named fault points are
  threaded through the RPC transport, the coordination store, and the
  distill discovery layer; tests (or an operator via
  ``EDL_TPU_FAULT_SPEC``) arm faults against those points and the
  schedule is reproducible from the seed.
- :mod:`edl_tpu.robustness.policy` — the unified failure-handling
  vocabulary every control-plane subsystem uses instead of hand-rolled
  sleep loops: :class:`RetryPolicy` (jittered exponential backoff),
  :class:`Deadline` (one budget propagated through nested calls), and
  :class:`CircuitBreaker` (per-endpoint open/half-open/closed).

``tools/check_no_ad_hoc_retries.py`` enforces adoption: control-plane
modules may not grow new raw ``time.sleep`` retry loops.
"""

from edl_tpu.robustness.faults import FaultPlane, plane_from_spec
from edl_tpu.robustness.policy import CircuitBreaker, Deadline, RetryPolicy

__all__ = ["FaultPlane", "plane_from_spec", "CircuitBreaker", "Deadline",
           "RetryPolicy"]
