"""Deterministic fault injection for the elastic control plane.

The chaos plane: named **fault points** are compiled into the RPC
transport, the coordination store, and the distill discovery layer.
When no plane is installed every hook site reduces to a single module
-attribute load and ``is None`` test (``faults.PLANE is None``) — no
allocation, no locking, no measurable cost on the tensor-frame hot
path. When a plane IS installed, armed faults fire deterministically:
each fault draws from its own :class:`random.Random` seeded from
``(plane seed, point, kind)``, so the same seed always produces the
same fault schedule regardless of thread interleaving or how many
other faults are armed.

Fault points (the catalog; see docs/fault_tolerance.md):

======================== ===============================================
point                    fired
======================== ===============================================
rpc.frame.write          before a frame is written (framing.write_frame)
rpc.frame.read           before a frame is read (framing.read_frame)
rpc.client.connect       before a client dials (ctx: endpoint)
rpc.client.call          before a request is sent (ctx: endpoint, method)
rpc.server.conn          when the server accepts a connection
rpc.server.request       before a request dispatches (ctx: method)
store.lease.grant        before a lease is granted (ctx: ttl)
store.lease.refresh      before a lease refresh (ctx: lease_id)
store.lease.expire       after the sweeper expired leases (ctx: lease_ids)
store.watch.deliver      before wait_events blocks (ctx: prefix)
distill.discovery        when a discovery client lists teachers
standby.witness.probe    before the standby asks a witness (ctx: endpoint)
peer_restore.connect     before a restorer dials a peer StateServer
                         (ctx: endpoint, rank)
peer_restore.read        before each peer span fetch (ctx: endpoint,
                         key)
data.assign              before a consumer asks the data leader for an
                         assignment (ctx: pod, endpoint)
data.fetch               before a batch fetch is issued to a producer
                         (ctx: pod, endpoint, batch)
data.fetch.delay         producer-side, inside get_batch/get_batches
                         before the cache is read (ctx: pod, batch) —
                         the latency twin of data.fetch: an armed delay
                         extends the RPC wall time and lands inside the
                         consumer's measured fetch window, so a slow
                         data plane is seeded-reproducible
store.repl.propose       before a leader logs a client op (ctx: kind)
store.repl.append        before a follower handles repl_append (ctx:
                         term, leader, n)
store.repl.vote          before a replica handles a vote request (ctx:
                         term, candidate)
store.repl.snapshot      before a follower installs a leader snapshot
                         (ctx: term, index)
store.repl.apply         before a committed entry is applied (ctx:
                         index, kind)
resize.live.drain        in live_resize before the save-engine drain
                         (ctx: from_devices, to_devices) — a failure
                         here rolls back before anything moved
resize.live.reshard      in live_resize after the new mesh is built,
                         before any state is resharded (ctx:
                         from_devices, to_devices) — the mid-reshard
                         crash drill; rollback must leave the old mesh
                         byte-identical and the 2PC must abort to
                         stop-resume
autopilot.apply          before an autopilot action's actuator runs
                         (ctx: action, pod) — fired INSIDE the retried
                         apply step, so ``error_once`` proves the
                         failed→retried→never-double-applied contract
                         and ``error`` proves a persistent failure is
                         journaled ``outcome: failed``
serve.admit              before the teacher admission controller decides
                         (ctx: rows, pending) — an armed ``error`` turns
                         every predict into a typed shed; ``delay``
                         inflates queue wait so the SLO projection trips
serve.drain              when a teacher starts draining (ctx: endpoint,
                         pending) — arm ``delay`` to hold the drain
                         window open or ``error`` to drill a teacher
                         dying mid-decommission
serve.decode.step        before each fused decode step of the
                         continuous-batching engine (ctx: active,
                         step) — an armed ``error`` fails ONLY the
                         sequences active in that step (typed
                         DecodeStepError, slots freed) and the device
                         loop keeps serving; ``delay`` inflates the
                         inter-token latency so the per-phase ``itl``
                         shed trips
serve.decode.prefix_lookup  before the prefix-cache trie lookup that
                         starts a prefill (ctx: seq, prompt_len) — an
                         armed ``error`` makes the lookup LOSSLESS-fail:
                         the sequence cold-prefills its full prompt
                         (counted as a miss, never a wrong token), so
                         the drill proves reuse is an optimization, not
                         a correctness dependency
relay.attach             child side, when a relay attachment adopts a
                         candidate endpoint (ctx: endpoint, pod) — an
                         armed ``error`` skips the candidate, driving
                         the fall-through to the grandparent / direct
                         store path
relay.forward            relay side, before a child's wait_events
                         long-poll is served from the cache (ctx:
                         prefix, child) — ``drop`` mimics a timed-out
                         poll (delay, never loss), ``error`` forces
                         the child through the since_rev-lossless
                         reattach path
redundancy.encode        push path, before the committed snapshot is
                         erasure-coded (ctx: owner, version) — an
                         armed ``error`` means this version gets no
                         parity cover; the restore ladder must stay
                         lossless via peers/FS
redundancy.push          before each shard is sent to a ring partner
                         (ctx: endpoint, owner, shard) — per-shard
                         failures shrink the rebuild margin, never
                         the commit
redundancy.rebuild       rebuild side, before a dead owner's shards
                         are fetched and decoded (ctx: owner,
                         version) — an armed ``error`` is THE
                         fallback drill: the restore must degrade to
                         the FS rung byte-identically and emit a
                         redundancy.fallback event (reason: fault)
embed.lookup             client side, before a coalesced embedding
                         gather leaves (ctx: table, member, endpoint)
                         — fired INSIDE the retried closure, so
                         ``error_once`` proves fail → requeue → the
                         exact rows (retries counted, no silently-
                         zero rows); a persistent ``error`` surfaces
                         as a typed EmbedLookupError
embed.writeback          client side, before a sparse optimizer
                         write-back leaves (ctx: table, member,
                         endpoint) — same requeue contract; a
                         persistent ``error`` is EmbedWritebackError
                         and the step fails rather than letting table
                         and cache diverge
======================== ===============================================

Fault kinds:

- ``delay``      sleep ``seconds`` (default 0.05), then continue.
- ``error``      raise ``error`` (an EdlError subclass name, or
                 ``ConnectionError``/``OSError``/``timeout``).
- ``error_once`` same, but ``times`` defaults to 1.
- ``partition``  raise ConnectError — arm with an ``endpoint=`` filter
                 to cut specific links.
- ``drop``       site-handled: the frame/request/refresh/event/teacher
                 list silently vanishes (write appears to succeed, the
                 server never answers, the refresh reports the lease
                 gone, the watch delivers nothing, discovery returns no
                 teachers).
- ``corrupt``    site-handled: a garbage header goes on the wire so the
                 peer sees a FramingError.
- ``half_close`` site-handled: the writer shuts down its send side.

Matching: any parameter that is not an action parameter (``seconds``,
``error``) is a **filter** matched as a substring against the fired
context, e.g. ``method="barrier"`` or ``endpoint="127.0.0.1:7021"``.
Scheduling parameters: ``after=K`` skips the first K matches,
``times=N`` fires at most N times, ``prob=p`` fires each match with
probability p from the fault's seeded RNG.

``EDL_TPU_FAULT_SPEC`` grammar (parsed once at import, so any process
— including subprocesses spawned by integration tests — can be placed
under chaos from the environment)::

    SPEC  := [ "seed=" INT ";" ] FAULT { ";" FAULT }
    FAULT := POINT ":" KIND [ "(" k "=" v { "," k "=" v } ")" ]

    EDL_TPU_FAULT_SPEC="seed=7;rpc.server.request:drop(method=barrier,times=2);store.lease.refresh:drop(times=3)"
"""

import os
import threading
import time
import zlib

from edl_tpu.obs import events as obs_events
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger

# THE hot-path gate. None == disabled: hook sites are
# ``if faults.PLANE is not None: ...`` and nothing else.
PLANE = None

_ACTION_PARAMS = frozenset(("seconds", "error"))
SITE_KINDS = frozenset(("drop", "corrupt", "half_close"))
GENERIC_KINDS = frozenset(("delay", "error", "error_once", "partition"))
KINDS = SITE_KINDS | GENERIC_KINDS


class FaultSpecError(Exception):
    """EDL_TPU_FAULT_SPEC (or a programmatic inject) is malformed."""


def _resolve_error(name):
    """Error class for the ``error`` kind: the EdlError taxonomy by
    class name, plus the transport-level builtins a socket can raise."""
    builtin = {"ConnectionError": ConnectionError, "OSError": OSError,
               "timeout": TimeoutError}
    cls = errors._name_to_cls().get(name) or builtin.get(name)
    if cls is None:
        raise FaultSpecError("unknown error class %r" % name)
    return cls


class Fault(object):
    """One armed fault at one point. Thread-safe via the plane's lock
    (all counter mutation happens inside FaultPlane.fire)."""

    __slots__ = ("point", "kind", "params", "filters", "times", "after",
                 "prob", "matched", "fired", "_rng")

    def __init__(self, point, kind, seed=0, times=None, after=0, prob=1.0,
                 **params):
        if kind not in KINDS:
            raise FaultSpecError("unknown fault kind %r (want one of %s)"
                                 % (kind, sorted(KINDS)))
        if kind == "error_once" and times is None:
            times = 1
        self.point = point
        self.kind = kind
        self.params = {k: v for k, v in params.items()
                       if k in _ACTION_PARAMS}
        self.filters = {k: v for k, v in params.items()
                        if k not in _ACTION_PARAMS}
        self.times = times
        self.after = int(after)
        self.prob = float(prob)
        self.matched = 0
        self.fired = 0
        # per-fault stream: independent of arming order and of every
        # other fault's draws — the determinism contract
        import random
        self._rng = random.Random(
            (int(seed) << 32) ^ zlib.crc32(("%s:%s" % (point, kind))
                                           .encode("utf-8")))

    def _matches(self, ctx):
        for key, want in self.filters.items():
            if str(want) not in str(ctx.get(key, "")):
                return False
        return True

    def _decide(self, ctx):
        """Counter/RNG advance; call only under the plane lock."""
        if not self._matches(ctx):
            return False
        self.matched += 1
        if self.matched <= self.after:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        if self._rng.random() >= self.prob:
            return False
        self.fired += 1
        return True

    def make_error(self):
        cls = _resolve_error(self.params.get("error", "ConnectError"))
        return cls("fault injected at %s" % self.point)

    def __repr__(self):
        return "Fault(%s:%s times=%r after=%d prob=%g fired=%d)" % (
            self.point, self.kind, self.times, self.after, self.prob,
            self.fired)


class FaultPlane(object):
    """Registry of armed faults + the fire() entry point hook sites call.

    ``log`` records every firing as ``(point, kind)`` in order — the
    observable fault schedule; two planes with equal seeds driven
    through equal match sequences produce equal logs.
    """

    def __init__(self, seed=0):
        self.seed = int(seed)
        self.log = []
        self._faults = {}  # point -> [Fault]
        self._lock = threading.Lock()

    # -- arming ------------------------------------------------------------

    def inject(self, point, kind, **params):
        """Arm ``kind`` at ``point``; returns the Fault (counters are
        inspectable: ``f.fired``)."""
        f = Fault(point, kind, seed=self.seed, **params)
        with self._lock:
            self._faults.setdefault(point, []).append(f)
        return f

    def clear(self, point=None):
        with self._lock:
            if point is None:
                self._faults.clear()
            else:
                self._faults.pop(point, None)

    def install(self):
        """Make this plane THE process-global plane."""
        global PLANE
        PLANE = self
        return self

    def uninstall(self):
        global PLANE
        if PLANE is self:
            PLANE = None

    # -- firing ------------------------------------------------------------

    def fire(self, point, **ctx):
        """Evaluate the point. Generic kinds act here (delay sleeps,
        error/partition raise); site-handled kinds (drop / corrupt /
        half_close) are returned for the hook site to apply. At most one
        site-handled fault is returned per firing (the first armed)."""
        with self._lock:
            flist = self._faults.get(point)
            if not flist:
                return None
            hits = [f for f in flist if f._decide(ctx)]
            for f in hits:
                self.log.append((point, f.kind))
        out = None
        for f in hits:
            logger.warning("fault fired: %s:%s %r", point, f.kind, ctx)
            # the injection lands on the elastic-event timeline, so a
            # chaos drill's observed recovery is causally attributable
            obs_events.emit("fault.fired", point=point, fault=f.kind,
                            ctx={k: str(v) for k, v in ctx.items()})
            if f.kind == "delay":
                time.sleep(float(f.params.get("seconds", 0.05)))
            elif f.kind in ("error", "error_once"):
                raise f.make_error()
            elif f.kind == "partition":
                raise errors.ConnectError(
                    "fault: partition at %s %r" % (point, ctx))
            elif out is None:
                out = f
        return out


def plane_from_spec(spec, seed=0):
    """Build a FaultPlane from the EDL_TPU_FAULT_SPEC grammar (module
    docstring). Does NOT install it."""
    plane = None
    entries = [e.strip() for e in spec.split(";") if e.strip()]
    if not entries:
        raise FaultSpecError("empty fault spec")
    if entries[0].startswith("seed="):
        seed = int(entries.pop(0)[len("seed="):])
    plane = FaultPlane(seed=seed)
    for entry in entries:
        if ":" not in entry:
            raise FaultSpecError("bad fault entry %r (want point:kind)"
                                 % entry)
        point, _, action = entry.partition(":")
        kind, params = action, {}
        if "(" in action:
            if not action.endswith(")"):
                raise FaultSpecError("unbalanced parens in %r" % entry)
            kind, _, arglist = action[:-1].partition("(")
            for pair in arglist.split(","):
                if not pair.strip():
                    continue
                if "=" not in pair:
                    raise FaultSpecError("bad param %r in %r"
                                         % (pair, entry))
                k, _, v = pair.partition("=")
                params[k.strip()] = _coerce(v.strip())
        plane.inject(point.strip(), kind.strip(), **params)
    return plane


def _coerce(value):
    for cast in (int, float):
        try:
            return cast(value)
        except ValueError:
            pass
    return value


# Opt-in environment activation: any process started with a spec is
# under chaos from its first import. A malformed spec fails loudly —
# silently ignoring it would report a chaos run as green without ever
# injecting anything.
_env_spec = os.environ.get("EDL_TPU_FAULT_SPEC")
if _env_spec:
    plane_from_spec(_env_spec).install()
    logger.warning("fault plane installed from EDL_TPU_FAULT_SPEC=%r",
                   _env_spec)
