"""Unified retry / deadline / circuit-breaker policy.

The three primitives every control-plane subsystem composes instead of
hand-rolling failure handling (the pre-existing idioms they replace:
``RpcClient.call`` failing fast, CoordClient's inline rotation-with-
grace loop, DistillReader's ``_recent_failures`` timestamp map, and
liveft's bare fixed-interval polls):

- :class:`Deadline` — a time **budget** created once at the outermost
  caller and passed down through nested calls, so a 60s caller budget
  caps every inner RPC and backoff sleep instead of each layer starting
  its own fresh timer (the classic unbounded-total-latency bug).
- :class:`RetryPolicy` — jittered exponential backoff with retryable
  -error classification and optional max attempts. Deterministic under
  test via ``seed``.
- :class:`CircuitBreaker` — per-key (endpoint) open / half-open /
  closed, so a flapping peer is probed at a bounded rate instead of
  hammered by every caller.
"""

import random
import threading
import time

from edl_tpu.obs import events as obs_events
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils import errors

_BREAKER_TRIPS = obs_metrics.counter(
    "edl_breaker_trips_total", "circuit-breaker closed/half-open -> "
    "open transitions")


class Deadline(object):
    """An absolute point in time shared by a whole call tree.

    ``Deadline(None)`` is the unbounded deadline: ``remaining()`` is
    None, ``expired()`` is False, ``sleep`` always sleeps fully.
    """

    __slots__ = ("_at",)

    def __init__(self, seconds=None):
        self._at = None if seconds is None else time.monotonic() + seconds

    @classmethod
    def after(cls, seconds):
        return cls(seconds)

    def remaining(self, cap=None):
        """Seconds left (None = unbounded), optionally capped — the
        shape RPC ``timeout=`` parameters want: never longer than the
        layer's own default, never longer than the caller's budget."""
        if self._at is None:
            return cap
        rem = self._at - time.monotonic()
        return rem if cap is None else min(rem, cap)

    def expired(self):
        return self._at is not None and time.monotonic() >= self._at

    def check(self, what=""):
        if self.expired():
            raise errors.DeadlineExceededError(
                "deadline exceeded%s" % (": " + what if what else ""))

    def sleep(self, seconds):
        """Sleep up to ``seconds`` but never past the deadline; returns
        False iff the deadline is exhausted (before or by the sleep)."""
        rem = self.remaining()
        if rem is not None and rem <= 0:
            return False
        time.sleep(seconds if rem is None else min(seconds, rem))
        return not self.expired()

    def union(self, other):
        """The earlier of two deadlines (budget intersection)."""
        if other is None or other._at is None:
            return self
        if self._at is None:
            return other
        return self if self._at <= other._at else other

    def __repr__(self):
        if self._at is None:
            return "Deadline(unbounded)"
        return "Deadline(%.3fs left)" % (self._at - time.monotonic())


FOREVER = Deadline(None)


class RetryPolicy(object):
    """Jittered exponential backoff + retryable-error classification.

    delay(attempt) = min(max_delay, base_delay * multiplier**(attempt-1))
                     scaled by uniform(1-jitter, 1+jitter)

    ``max_attempts=None`` retries until the deadline (callers without a
    deadline and without max_attempts retry forever — by design for
    supervision loops; everything user-facing passes one or both).
    ``seed`` pins the jitter stream for deterministic tests.
    """

    def __init__(self, max_attempts=None, base_delay=0.1, max_delay=5.0,
                 multiplier=2.0, jitter=0.5, retry_on=(errors.EdlError,),
                 give_up_on=(errors.StopError,), seed=None):
        self.max_attempts = max_attempts
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.retry_on = tuple(retry_on)
        self.give_up_on = tuple(give_up_on)
        self._rng = random.Random(seed) if seed is not None else random
        self._lock = threading.Lock()

    def delay(self, attempt):
        """Backoff before attempt ``attempt + 1`` (attempt counts from 1)."""
        d = min(self.max_delay,
                self.base_delay * self.multiplier ** max(0, attempt - 1))
        if self.jitter:
            with self._lock:  # Random isn't thread-safe for our seeded use
                u = self._rng.uniform(1.0 - self.jitter, 1.0 + self.jitter)
            d *= u
        return max(0.0, d)

    def sleep(self, attempt, deadline=None):
        """Back off after failed attempt ``attempt``. Returns False iff
        retrying is pointless: attempts exhausted or deadline spent."""
        if self.max_attempts is not None and attempt >= self.max_attempts:
            return False
        d = self.delay(attempt)
        if deadline is None:
            time.sleep(d)
            return True
        return deadline.sleep(d)

    def call(self, fn, *args, **kwargs):
        """Run ``fn(*args, **kwargs)`` under this policy. Keyword-only:
        ``deadline`` (a :class:`Deadline`) and ``on_retry(attempt, exc)``.

        Raises the last error when attempts run out; raises
        DeadlineExceededError (carrying the last error as ``__cause__``)
        when the budget runs out.
        """
        deadline = kwargs.pop("deadline", None)
        on_retry = kwargs.pop("on_retry", None)
        attempt = 0
        while True:
            attempt += 1
            if deadline is not None:
                deadline.check(getattr(fn, "__name__", "call"))
            try:
                return fn(*args, **kwargs)
            except self.give_up_on:
                raise
            except self.retry_on as e:
                if not self.sleep(attempt, deadline):
                    if (deadline is not None and deadline.expired()
                            and (self.max_attempts is None
                                 or attempt < self.max_attempts)):
                        raise errors.DeadlineExceededError(
                            "%s: deadline exceeded after %d attempts; "
                            "last error: %r"
                            % (getattr(fn, "__name__", "call"), attempt,
                               e)) from e
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)


class CircuitBreaker(object):
    """Per-key circuit breaker (key = endpoint, typically).

    closed → (``failure_threshold`` consecutive failures) → open →
    (``reset_timeout`` elapses) → half-open: up to ``half_open_max``
    concurrent probes allowed; one success closes, one failure re-opens
    (and restarts the reset clock).

    State is bounded: :meth:`prune` drops keys outside the live set, so
    endpoint churn (teachers coming and going for days) cannot grow the
    map without bound — the regression the old ``_recent_failures``
    timestamp map had.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_threshold=3, reset_timeout=5.0,
                 half_open_max=1, clock=time.monotonic):
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout = float(reset_timeout)
        self.half_open_max = int(half_open_max)
        self._clock = clock
        self._lock = threading.Lock()
        self._s = {}  # key -> [state, consecutive_failures, opened_at, probes]

    def _cell(self, key):
        cell = self._s.get(key)
        if cell is None:
            cell = self._s[key] = [self.CLOSED, 0, 0.0, 0]
        return cell

    def allow(self, key):
        """May a call to ``key`` proceed right now? An allowed call in
        half-open counts as a probe until success/failure is recorded."""
        with self._lock:
            cell = self._cell(key)
            if cell[0] == self.CLOSED:
                return True
            if cell[0] == self.OPEN:
                if self._clock() - cell[2] < self.reset_timeout:
                    return False
                cell[0] = self.HALF_OPEN
                cell[3] = 0
            if cell[3] >= self.half_open_max:
                return False
            cell[3] += 1
            return True

    def record_success(self, key):
        with self._lock:
            self._s[key] = [self.CLOSED, 0, 0.0, 0]

    def record_failure(self, key):
        with self._lock:
            cell = self._cell(key)
            cell[1] += 1
            tripped = cell[0] == self.HALF_OPEN \
                or cell[1] >= self.failure_threshold
            if tripped:
                reopened = cell[0] == self.HALF_OPEN
                self._s[key] = [self.OPEN, 0, self._clock(), 0]
        if tripped:
            # outside the lock: the timeline write takes its own lock
            _BREAKER_TRIPS.inc()
            obs_events.emit("breaker.open", key=str(key),
                            reopened=reopened)

    def state(self, key):
        with self._lock:
            cell = self._s.get(key)
            return self.CLOSED if cell is None else cell[0]

    def keys(self):
        with self._lock:
            return list(self._s)

    def prune(self, keep):
        """Forget every key not in ``keep`` — bounds state to the live
        endpoint set."""
        keep = set(keep)
        with self._lock:
            for key in [k for k in self._s if k not in keep]:
                del self._s[key]
