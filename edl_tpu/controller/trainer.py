"""Trainer model: one training process on a pod.

Reference parity: edl/utils/trainer.py (uuid, rank_in_pod, device slice,
endpoint, global_rank). On TPU a trainer is a JAX host process owning a set
of local chips — usually all of them (one process per host).
"""

from edl_tpu.utils import unique_name
from edl_tpu.utils.json_serializable import Serializable


class Trainer(Serializable):
    def __init__(self):
        self.id = None
        self.rank_in_pod = None
        self.devices = []       # local chip indices owned by this process
        self.endpoint = None    # host:port for jax.distributed / data plane
        self.global_rank = None

    @staticmethod
    def make(rank_in_pod, devices, endpoint):
        t = Trainer()
        t.id = unique_name.uid()
        t.rank_in_pod = rank_in_pod
        t.devices = list(devices)
        t.endpoint = endpoint
        t.global_rank = None
        return t
