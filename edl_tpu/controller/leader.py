"""Leader election: seize a TTL-leased key, keep it refreshed, run the
cluster generator while leading.

Reference parity: edl/utils/leader_pod.py (_seize_leader:57-88 put-if-absent
with TTL lease; losers retry every 3s :104-119; winner starts the generator).
Improvement over the reference: a leader that loses its lease stops its
generator and rejoins the election instead of going silent.
"""

import threading

from edl_tpu.controller import constants
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger


class LeaderElector(object):
    def __init__(self, coord, pod_id, on_elected=None, on_lost=None,
                 ttl=constants.ETCD_TTL):
        self._coord = coord
        self._pod_id = pod_id
        self._ttl = ttl
        self._on_elected = on_elected
        self._on_lost = on_lost
        self._is_leader = threading.Event()
        self._stop = threading.Event()
        self._broken = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="leader-elector")

    def start(self):
        self._thread.start()
        return self

    def _run(self):
        lease_id = None
        while not self._stop.is_set():
            try:
                if lease_id is None:
                    lease_id = self._coord.set_server_not_exists(
                        constants.SERVICE_LEADER, constants.LEADER_SERVER,
                        self._pod_id, self._ttl)
                    if lease_id is not None:
                        logger.info("pod %s became leader", self._pod_id)
                        self._is_leader.set()
                        if self._on_elected:
                            self._on_elected()
                    self._stop.wait(1.0)
                else:
                    if not self._coord.lease_refresh(lease_id):
                        raise errors.LeaseExpiredError("leader lease expired")
                    self._stop.wait(self._ttl / 3.0)
            except errors.EdlError as e:
                if self._is_leader.is_set():
                    logger.error("pod %s lost leadership: %r", self._pod_id,
                                 e)
                    self._is_leader.clear()
                    if self._on_lost:
                        self._on_lost()
                lease_id = None
                self._stop.wait(1.0)

    def is_leader(self):
        return self._is_leader.is_set()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=self._ttl)
        if self._is_leader.is_set():
            try:
                # guarded: only delete the key if WE still hold it — if the
                # lease silently expired (e.g. a pause longer than the TTL)
                # and a successor already seized leadership, an unguarded
                # delete would evict the successor and churn the election
                key = self._coord.server_key(constants.SERVICE_LEADER,
                                             constants.LEADER_SERVER)
                self._coord.txn([(key, "value_eq", self._pod_id)],
                                [("delete", key)])
            except errors.EdlError:
                pass
            self._is_leader.clear()
            if self._on_lost:
                self._on_lost()


def get_leader_id(coord):
    return coord.get_value(constants.SERVICE_LEADER, constants.LEADER_SERVER)
