"""The per-host launcher daemon: register → elect → barrier → spawn →
supervise → stop-resume on membership change.

Reference parity: edl/utils/launcher.py (init:58, _barrier:69, _launch:160,
supervision loop :202-246, _exit:99-130). The launch call stack is
SURVEY.md §3.1. The TPU difference: one trainer process per host owning all
local chips; gradient communication is XLA collectives inside the trainer,
so the launcher only manages membership + barrier + processes.
"""

import json
import time

from edl_tpu.controller import barrier as barrier_mod
from edl_tpu.controller import cluster as cluster_mod
from edl_tpu.controller import constants, status, train_process
from edl_tpu.controller.cluster_generator import Generator
from edl_tpu.controller.cluster_watcher import ClusterWatcher
from edl_tpu.controller.leader import LeaderElector
from edl_tpu.controller.resource_pods import ResourceRegister
from edl_tpu.obs import autopilot as autopilot_mod
from edl_tpu.obs import flight as obs_flight
from edl_tpu.obs.health import HealthMonitor
from edl_tpu.rpc.client import RpcClient
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger

# _join_cluster verdicts: admitted to a cluster including this pod /
# never needed (clean surplus exit) / the job failed while waiting
_JOIN_ADMITTED = "admitted"
_JOIN_SURPLUS = "surplus"
_JOIN_FAILED = "failed"


class Launcher(object):
    def __init__(self, job_env, pod, coord, training_script, script_args=(),
                 topology_valid=None):
        self._job_env = job_env
        self._pod = pod
        self._coord = coord
        self._script = training_script
        self._script_args = list(script_args)
        self._topology_valid = topology_valid

        self._pod_server = None
        self._resource_register = None
        self._elector = None
        self._generator = None
        self._health = None
        self._autopilot = None
        self._watcher = None
        self._relay = None
        self._procs = []
        self._cluster = None
        # live-resize intents this launcher already adopted (ids); a
        # committed intent stays in the store until the next one
        self._live_done = set()

    # -- lifecycle -----------------------------------------------------------

    def init(self):
        status.save_pod_status(self._coord, self._pod.id,
                               status.Status.INITIAL)

        def stats():
            return {
                "trainers": [
                    {"rank": tp.trainer.global_rank, "pid": tp.proc.pid,
                     "alive": tp.proc.poll() is None}
                    for tp in self._procs],
            }

        self._pod_server = barrier_mod.PodServer(
            self._coord, self._pod, stats_fn=stats).start()
        # the launcher's black box: SIGTERM-era pod deaths and observed
        # trainer failures leave a blackbox/v1 behind for --postmortem
        obs_flight.install(self._pod.id, coord=self._coord,
                           sigterm=True)
        logger.info("pod %s serving barrier on port %d", self._pod.id,
                    self._pod.port)
        return self

    def launch(self):
        """Run the job to completion; returns True on success."""
        try:
            return self._launch()
        finally:
            self._cleanup()

    # -- internals -----------------------------------------------------------

    def _launch(self):
        je = self._job_env
        self._resource_register = ResourceRegister(self._coord, self._pod)
        # the health monitor is leader-hosted alongside the generator:
        # its verdicts advise the generator's scale-in victim choice,
        # and exactly one monitor writes the fleet's health_report/v1.
        # The autopilot (opt-in via EDL_TPU_AUTOPILOT) rides the
        # monitor tick and turns verdicts into journaled actions; it
        # has no thread of its own, so elections start/stop nothing
        # extra — no leader means no monitor tick means no actions.
        mode = autopilot_mod.mode_from_env()
        if mode != autopilot_mod.MODE_OFF:
            self._autopilot = autopilot_mod.Autopilot(
                self._coord, self._pod.id, mode=mode,
                evict_fn=lambda pod: self._generator.direct_evict(pod),
                knobs_fn=self._broadcast_knobs,
                hold_fn=self._failover_hold)
        self._health = HealthMonitor(
            self._coord, self._pod.id,
            on_report=(self._autopilot.on_report
                       if self._autopilot else None))
        self._generator = Generator(
            self._coord, self._pod.id, je.min_nodes, je.max_nodes,
            topology_valid=self._topology_valid,
            preferred_victims=self._health.preferred_victims,
            scale_out_gate=(self._autopilot.scale_out_allowed
                            if self._autopilot else None))
        self._elector = LeaderElector(
            self._coord, self._pod.id,
            on_elected=lambda: (self._generator.start(),
                                self._health.start()),
            on_lost=lambda: (self._generator.stop(),
                             self._health.stop())).start()

        verdict = self._join_cluster()
        if verdict is _JOIN_FAILED:
            # the job died while this pod waited at the barrier — e.g. its
            # peer was killed below min_nodes before the first barrier
            # completed; the launcher exit code must reflect the verdict
            # (carried from the barrier's own observation, NOT re-read:
            # a concurrent retry may already have reset the status key)
            logger.error("job FAILED before pod %s was admitted; exiting "
                         "with failure", self._pod.id)
            return False
        if verdict is _JOIN_SURPLUS:
            logger.info("pod %s never admitted to the cluster; exiting as "
                        "surplus", self._pod.id)
            return True
        status.save_pod_status(self._coord, self._pod.id,
                               status.Status.RUNNING)
        # host + attach this pod's watch relay BEFORE the watcher
        # starts, so the cluster watch long-poll rides the tree from
        # its first poll (EDL_TPU_RELAY=0 keeps everything flat)
        self._start_relay()
        self._watcher = ClusterWatcher(self._coord, self._cluster)
        self._procs = train_process.start_trainers(
            je, self._pod, self._cluster, self._script, self._script_args,
            je.log_dir)
        return self._supervise()

    # -- watch relay tree ----------------------------------------------------

    def _start_relay(self):
        """Host this pod's WatchRelay and attach the shared coord
        client to it: long-polls, keepalive beats, and obs publishes
        ride the deterministic B-ary fan-out tree computed from the
        cluster map, falling through to direct store calls whenever no
        relay answers. Strictly best-effort — a pod that cannot host or
        attach simply stays on the flat direct path."""
        from edl_tpu.coordination import relay as relay_mod
        if not relay_mod.enabled() or self._relay is not None:
            return
        try:
            self._relay = relay_mod.WatchRelay(
                self._coord, self._pod.id,
                service=constants.SERVICE_RELAY,
                register_ttl=constants.ETCD_TTL)
            self._relay.update_tree(self._cluster.pod_ids())
            self._relay.start(register=True)
            self._coord.attach_relay(relay_mod.RelayAttachment(
                self._relay.attachment_candidates,
                pod_id=self._pod.id))
            logger.info("pod %s relaying on %s (tree over %d pods)",
                        self._pod.id, self._relay.endpoint,
                        self._cluster.world_size())
        except Exception:
            logger.exception("watch relay unavailable on pod %s; "
                             "staying on direct store path",
                             self._pod.id)
            self._stop_relay()

    def _update_relay_tree(self):
        """Recompute the relay tree from the post-resize cluster map
        and drop sticky endpoints so attachments re-resolve parents."""
        if self._relay is None:
            return
        try:
            self._relay.update_tree(self._cluster.pod_ids())
            att = self._coord.relay_attachment
            if att is not None:
                att.invalidate()
        except Exception:
            logger.exception("relay tree update failed on pod %s",
                             self._pod.id)

    def _stop_relay(self):
        att = None
        try:
            att = self._coord.detach_relay()
        except AttributeError:
            pass
        if att is not None:
            att.close()
        relay, self._relay = self._relay, None
        if relay is not None:
            try:
                relay.stop()
            except Exception:
                logger.exception("relay stop failed for %r", relay)

    def _join_cluster(self):
        """Barrier until a cluster that *includes this pod* is agreed;
        returns a _JOIN_* verdict.

        A pod not in the current map is a late joiner waiting for the
        generator to scale it in (reference: INITIAL pods appended while
        below max_nodes, cluster_generator.py:136-153) — it stays PENDING
        and re-barriers rather than exiting."""
        deadline = time.monotonic() + constants.BARRIER_TIMEOUT
        pending = False
        while time.monotonic() < deadline:
            try:
                self._cluster = self._barrier_sliced(deadline)
            except errors.TimeoutError_:
                break
            except errors.JobFailedError:
                # _launch logs the verdict and maps it to a failure exit
                return _JOIN_FAILED
            if self._update_local_pod():
                return _JOIN_ADMITTED
            job = status.load_job_status(self._coord)
            if job == status.Status.FAILED:
                return _JOIN_FAILED
            if job == status.Status.SUCCEED:
                return _JOIN_SURPLUS
            if not pending:
                status.save_pod_status(self._coord, self._pod.id,
                                       status.Status.PENDING)
                pending = True
                logger.info("pod %s waiting to be scaled in", self._pod.id)
            time.sleep(constants.GENERATE_INTERVAL)
        return _JOIN_SURPLUS

    def _barrier_sliced(self, deadline, poll=0.5, check_every=5.0):
        """Abortable barrier: one cached session retried every ``poll``
        seconds, checking the job verdict every ``check_every`` — a pod
        parked at a barrier that will never form (e.g. its peer died
        below min_nodes before checking in) must not sit out the full
        barrier timeout (VERDICT r1 weak #2 family)."""
        session = barrier_mod.BarrierSession(self._coord, self._pod.id)
        last_check = time.monotonic()
        try:
            while True:
                try:
                    return session.attempt()
                except errors.EdlError:
                    pass
                now = time.monotonic()
                if now >= deadline:
                    raise errors.TimeoutError_("barrier deadline exceeded")
                if now - last_check >= check_every:
                    last_check = now
                    if status.load_job_status(self._coord) \
                            == status.Status.FAILED:
                        raise errors.JobFailedError(
                            "job failed while waiting at the barrier")
                time.sleep(poll)
        finally:
            session.close()

    def _update_local_pod(self):
        """Adopt rank/trainer-rank assignments from the agreed cluster;
        False if this pod was evicted (reference: launcher.py:142-158)."""
        mine = self._cluster.get_pod(self._pod.id)
        if mine is None:
            return False
        mine.addr, mine.port = self._pod.addr, self._pod.port
        self._pod = mine
        return True

    def _supervise(self):
        awaiting_since = None  # set when trainers exited PREEMPTED (101)
        # a real pod eviction needs lease expiry + (possibly)
        # re-election + generator publish + watcher poll to surface;
        # respawning against the stale cluster before that wastes a
        # restart cycle on a dead coordinator
        respawn_wait = max(constants.PREEMPT_RESPAWN_WAIT,
                           2 * constants.ETCD_TTL + 5)
        while True:
            time.sleep(constants.SUPERVISE_INTERVAL)

            if self._procs:
                done, failed = train_process.watch_trainers(self._procs)
                if failed:
                    codes = {tp.proc.returncode for tp in self._procs
                             if tp.proc.poll() not in (None, 0)}
                    if codes == {constants.PREEMPT_EXIT_CODE}:
                        # preempted, not failed: an emergency checkpoint
                        # was written (or the epoch one stands); await
                        # the membership change that usually caused this
                        logger.info("trainers preempted (exit %d) on pod "
                                    "%s; awaiting resize",
                                    constants.PREEMPT_EXIT_CODE,
                                    self._pod.id)
                        train_process.terminate_trainers(self._procs)
                        self._procs = []
                        awaiting_since = time.monotonic()
                    else:
                        logger.error("a trainer failed on pod %s",
                                     self._pod.id)
                        # the child died without its own exit path (kill
                        # -9, OOM): the launcher's observation is the
                        # last evidence standing
                        obs_flight.dump("trainer_exit")
                        return self._exit(False)
                elif done:
                    logger.info("all trainers on pod %s finished",
                                self._pod.id)
                    return self._exit(True)

            if self._resource_register.is_broken():
                logger.error("resource registration lost; killing trainers")
                return self._exit(False)

            if status.load_job_status(self._coord) == status.Status.FAILED:
                logger.error("job marked FAILED; exiting")
                return self._exit(False)

            if self._watcher.changed():
                try:
                    if not self._resize():
                        logger.info("pod %s evicted during resize; clean "
                                    "exit", self._pod.id)
                        return True
                    awaiting_since = None
                except errors.EdlError as e:
                    logger.error("resize failed on pod %s: %r", self._pod.id,
                                 e)
                    return self._exit(False)
            elif awaiting_since is not None and (
                    time.monotonic() - awaiting_since > respawn_wait):
                # the preemption was trainer-only (no pod left the
                # cluster): respawn in place; trainers resume from the
                # emergency checkpoint
                logger.info("no resize followed the preemption; "
                            "respawning trainers in place on pod %s",
                            self._pod.id)
                self._clear_preempt_keys()
                self._procs = train_process.start_trainers(
                    self._job_env, self._pod, self._cluster, self._script,
                    self._script_args, self._job_env.log_dir)
                awaiting_since = None

    def _clear_preempt_keys(self):
        """Retire STALE preempt:<stage>/* keys before a respawn that
        REUSES the cluster stage: within the keys' TTL a stale stop_at
        could make the respawned incarnation immediately re-preempt
        itself when it resumes from an older checkpoint (min_step below
        the stale stop), costing an extra restart cycle.

        Staleness criterion (same one the trainer uses): a key's step
        value at or below the store-published resumed global step is a
        leftover — the emergency save published that step, so trainers
        resume there, and a LIVE preemption on another pod always has
        req/stop values ahead of every live rank's counter, which is
        ahead of the last checkpoint. A blanket delete would tear down
        an in-flight preemption's agreed stop_at mid-protocol and split
        the stop step across ranks. With no published step yet there is
        nothing to compare — keep everything and rely on the trainer's
        min_step filter."""
        from edl_tpu.runtime import state as state_mod
        service = "preempt:%s" % (self._cluster.stage or "default")
        try:
            st = state_mod.load_from_store(self._coord)
            floor = None if st is None else int(st.global_step)
        except Exception:
            floor = None
        if floor is None:
            return
        try:
            for name, value in self._coord.get_service(service):
                try:
                    if isinstance(value, bytes):
                        value = value.decode("utf-8", "replace")
                    if int(value) <= floor:
                        self._coord.remove_server(service, name)
                except (TypeError, ValueError):
                    pass
                except Exception:
                    pass
        except Exception:
            logger.exception("clearing preemption keys failed "
                             "(stage %s)", self._cluster.stage)

    def _live_intent_for_pod(self):
        """The committed live-resize intent this pod should adopt, or
        None (→ stop-resume). Requires: phase ``commit``, this pod in
        the survivor set, an ok ack from this pod's trainer (the
        trainer already drained + resharded in place), and an intent id
        not yet consumed."""
        from edl_tpu.runtime import live_resize as live_mod
        try:
            intent = live_mod.read_intent(self._coord)
        except errors.EdlError:
            return None
        if (not intent or intent.get("phase") != live_mod.COMMIT
                or intent.get("id") in self._live_done
                or self._pod.id not in (intent.get("survivors") or ())):
            return None
        try:
            ack = live_mod.read_acks(self._coord,
                                     intent["id"]).get(self._pod.id)
        except errors.EdlError:
            return None
        if not ack or not ack.get("ok"):
            return None
        return intent

    def _resize_live(self, intent):
        """Adopt a committed live resize: the trainers are ALIVE and
        already resharded — no kill, no barrier, no respawn. Just load
        the atomically-installed cluster map, take the new rank
        assignment, and rearm the watcher. Returns False if the new map
        somehow excludes this pod (then the stop-resume eviction path
        has already decided)."""
        t0 = time.monotonic()
        self._live_done.add(intent.get("id"))
        cluster = cluster_mod.load_from_store(self._coord)
        if cluster is None:
            return None  # caller falls back to stop-resume
        self._cluster = cluster
        if not self._update_local_pod():
            return False
        self._update_relay_tree()
        self._watcher.stop()
        self._watcher = ClusterWatcher(self._coord, self._cluster)
        recovery_s = time.monotonic() - t0
        logger.info("live resize adopted on pod %s: world=%d stage=%s "
                    "(%.3fs, trainers kept alive)", self._pod.id,
                    self._cluster.world_size(), self._cluster.stage,
                    recovery_s)
        self._record_resize_metric(recovery_s, mode="live")
        return True

    def _resize(self):
        """Membership changed. A committed live-resize intent covering
        this pod means the trainer already resharded in place — adopt
        the map without touching the processes. Otherwise stop-resume
        (reference: launcher.py:221-244): kill trainers, re-barrier on
        the new cluster, respawn. Returns False if this pod was evicted
        by the new cluster map."""
        intent = self._live_intent_for_pod()
        if intent is not None:
            adopted = self._resize_live(intent)
            if adopted is not None:
                return adopted
        logger.info("membership changed; stop-resume resize on pod %s",
                    self._pod.id)
        t0 = time.monotonic()
        train_process.terminate_trainers(self._procs)
        self._procs = []
        self._watcher.stop()

        try:
            self._cluster = self._barrier_sliced(
                time.monotonic() + constants.RESIZE_BARRIER_TIMEOUT)
        except errors.TimeoutError_:
            logger.error("resize barrier timed out on pod %s", self._pod.id)
            raise errors.BarrierError("resize barrier timed out")
        except errors.JobFailedError:
            raise errors.BarrierError("job failed during resize barrier")
        if not self._update_local_pod():
            return False
        self._update_relay_tree()
        self._watcher = ClusterWatcher(self._coord, self._cluster)
        self._procs = train_process.start_trainers(
            self._job_env, self._pod, self._cluster, self._script,
            self._script_args, self._job_env.log_dir)
        recovery_s = time.monotonic() - t0
        logger.info("resize complete: world=%d stage=%s (%.2fs)",
                    self._cluster.world_size(), self._cluster.stage,
                    recovery_s)
        self._record_resize_metric(recovery_s)
        return True

    def _record_resize_metric(self, recovery_s, mode="stop_resume"):
        """Per-pod resize history under the metrics service, scrapeable by
        drivers/operators (per-pod keys, so no cross-pod write races)."""
        try:
            raw = self._coord.get_value(constants.SERVICE_METRICS,
                                        self._pod.id) or "[]"
            history = json.loads(raw)[-19:]
            history.append({
                "stage": self._cluster.stage,
                "world": self._cluster.world_size(),
                "recovery_s": round(recovery_s, 2),
                "mode": mode,
                "ts": round(time.time(), 1),
            })
            self._coord.set_server_permanent(constants.SERVICE_METRICS,
                                             self._pod.id,
                                             json.dumps(history))
        except Exception:
            logger.exception("resize metric write failed")

    def _failover_hold(self):
        """The autopilot's hold probe: True while the post-failover
        settle window is open (see standby.failover_guard_active)."""
        try:
            from edl_tpu.coordination.standby import failover_guard_active
            return failover_guard_active(self._coord)
        except Exception:  # noqa: BLE001 — fail open, like the guard
            return False

    def _broadcast_knobs(self, knobs):
        """The autopilot's tune_knobs actuator: fan ``set_knobs`` out
        to every reader's DataPlaneServer. Discovery is the data
        leader's ``ds_stats`` (its ``endpoints`` map — registered
        readers and where they serve). Per-pod failures are reported,
        not raised: tuning the survivors beats tuning no one. Raises
        only when there is no data leader to discover through (the
        action is then journaled ``failed``)."""
        leader_ep = self._coord.get_value(constants.SERVICE_READER,
                                          "reader")
        if not leader_ep:
            raise errors.NotFoundError(
                "no data leader registered; cannot broadcast knobs")
        client = RpcClient(leader_ep, timeout=5.0)
        try:
            stats = client.call("ds_stats")
        finally:
            client.close()
        out = {}
        for pod, ep in sorted((stats.get("endpoints") or {}).items()):
            c = RpcClient(ep, timeout=5.0)
            try:
                out[pod] = c.call("set_knobs", knobs)
            except Exception as e:  # noqa: BLE001 — tune the survivors
                out[pod] = {"error": repr(e)}
            finally:
                c.close()
        return out

    def _exit(self, ok):
        """Write the pod flag; the leader aggregates all flags into the job
        status (reference: launcher.py:99-130)."""
        status.save_pod_status(
            self._coord, self._pod.id,
            status.Status.SUCCEED if ok else status.Status.FAILED)
        status.save_job_flag(self._coord, self._pod.id, ok)
        if not ok:
            # NOT a global job failure: the generator removes this pod and
            # the survivors resize; the job only fails below min_nodes.
            return False
        if self._elector is not None and self._elector.is_leader():
            self._leader_wait_and_finalize()
        return ok

    def _leader_wait_and_finalize(self):
        """Leader waits for every cluster pod's flag, then writes the job
        status. Pods that died (lease gone) fail the job."""
        deadline = time.monotonic() + constants.FLAG_WAIT_TIMEOUT
        want = set(self._cluster.pod_ids()) if self._cluster else set()
        while time.monotonic() < deadline:
            flags = status.load_job_flags(self._coord)
            # only flags of *current* cluster members matter — pods resized
            # away earlier may have left FAILED flags behind
            if any(flags.get(pid) == status.Status.FAILED for pid in want):
                status.save_job_status(self._coord, status.Status.FAILED)
                return
            if want.issubset(flags.keys()):
                status.save_job_status(self._coord, status.Status.SUCCEED)
                logger.info("job %s SUCCEED", self._job_env.job_id)
                return
            time.sleep(0.5)
        logger.warning("leader timed out waiting for pod flags %s",
                       want - set(status.load_job_flags(self._coord)))
        status.save_job_status(self._coord, status.Status.FAILED)

    def _cleanup(self):
        if self._procs:
            train_process.terminate_trainers(self._procs)
        # detach + stop the relay FIRST: the components below still
        # hold long-polls/leases through it, and must fall through to
        # the direct path while they shut down rather than hang on a
        # half-dead local relay
        self._stop_relay()
        for closer in (self._watcher, self._generator, self._health,
                       self._elector, self._resource_register,
                       self._pod_server):
            if closer is not None:
                try:
                    closer.stop()
                except Exception:
                    logger.exception("cleanup failed for %r", closer)
