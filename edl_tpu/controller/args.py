"""Launcher CLI argument parsing.

Reference parity: edl/utils/args_utils.py:32-96 (nodes_range,
nproc_per_node, etcd endpoints → store endpoints, job_id, log flags, hdfs →
checkpoint_path, positional training_script + args).
"""

import argparse


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        "edl-tpu-run",
        description="Elastic TPU collective training launcher")
    p.add_argument("--job_id", default=None,
                   help="job id (or $EDL_TPU_JOB_ID)")
    p.add_argument("--store_endpoints", default=None,
                   help="coordination store endpoints, comma separated")
    p.add_argument("--nodes_range", default=None,
                   help="elastic node range 'min:max' (or a single count)")
    p.add_argument("--nproc_per_node", type=int, default=None,
                   help="trainer processes per host (default 1 on TPU)")
    p.add_argument("--pod_ip", default=None,
                   help="this host's IP as seen by peers")
    p.add_argument("--checkpoint_path", default=None,
                   help="shared checkpoint directory for elastic resume")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--log_level", default=None)
    p.add_argument("training_script", help="the training program to run")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)
