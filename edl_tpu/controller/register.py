"""TTL-leased registration kept alive through the per-process keepalive hub.

Reference parity: edl/utils/register.py (refresh every ttl/2; refresh
failure ⇒ the node silently drops out of the cluster :57-68). Here refresh
failure marks the register stopped so the launcher notices and exits.

Refreshes are coalesced: every Register in a process shares ONE timer and
ONE batched ``lease_refresh_many`` RPC via
:class:`edl_tpu.coordination.keepalive.KeepaliveHub` (set
``EDL_TPU_KEEPALIVE_HUB=0`` to fall back to a private per-register
refresh thread).
"""

import os
import threading
import time

from edl_tpu.controller import constants
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger


class Register(object):
    def __init__(self, coord, service, server, value,
                 ttl=constants.ETCD_TTL, use_hub=None):
        self._coord = coord
        self._service = service
        self._server = server
        self._value = value
        self._ttl = ttl
        self._lease_id = coord.set_server_with_lease(service, server, value,
                                                     ttl)
        self._stop = threading.Event()
        self._broken = threading.Event()
        if use_hub is None:
            use_hub = os.environ.get("EDL_TPU_KEEPALIVE_HUB", "1") != "0"
        self._hub = None
        self._thread = None
        if use_hub:
            from edl_tpu.coordination.keepalive import hub_for
            self._hub = hub_for(coord)
            self._hub.add(self._lease_id, ttl, on_lost=self._on_lost)
        else:
            self._thread = threading.Thread(
                target=self._refresher, daemon=True,
                name="register-%s-%s" % (service, server))
            self._thread.start()

    # -- coalesced path (keepalive hub) --------------------------------

    def _on_lost(self):
        """Hub callback: the store no longer knows our lease. Never
        block the shared beat — re-register on a private thread."""
        if self._stop.is_set():
            return
        threading.Thread(
            target=self._relost, daemon=True,
            name="reregister-%s-%s" % (self._service, self._server)).start()

    def _relost(self):
        old = self._lease_id
        if self._reregister(errors.LeaseExpiredError(
                "lease %s for %s/%s lost" % (old, self._service,
                                             self._server))):
            if not self._stop.is_set() and self._hub is not None:
                self._hub.replace(old, self._lease_id, self._ttl,
                                  on_lost=self._on_lost)
        else:
            self._broken.set()

    # -- legacy path (private refresh thread) --------------------------

    def _refresher(self):
        while not self._stop.wait(self._ttl / 3.0):
            try:
                self._coord.refresh_server(self._service, self._server,
                                           self._lease_id)
            except errors.EdlError as e:
                # lease lost (expiry race or a store crash/restart) — keep
                # trying to re-register for a grace window so a store
                # restart does not take the whole cluster down with it
                if not self._reregister(e):
                    self._broken.set()
                    return

    def _reregister(self, cause, grace_factor=3):
        deadline = time.monotonic() + self._ttl * grace_factor
        while time.monotonic() < deadline and not self._stop.is_set():
            try:
                self._lease_id = self._coord.set_server_with_lease(
                    self._service, self._server, self._value, self._ttl)
                logger.warning("registration %s/%s re-established after %r",
                               self._service, self._server, cause)
                return True
            except errors.EdlError:
                self._stop.wait(self._ttl / 3.0)
        if self._stop.is_set():
            return True  # ordinary requested shutdown, not a loss
        logger.error("registration %s/%s lost for good: %r", self._service,
                     self._server, cause)
        return False

    def is_broken(self):
        return self._broken.is_set()

    def stop(self, revoke=True):
        self._stop.set()
        if self._hub is not None:
            self._hub.remove(self._lease_id)
        if self._thread is not None:
            self._thread.join(timeout=self._ttl)
        if revoke:
            try:
                self._coord.lease_revoke(self._lease_id)
            except errors.EdlError:
                pass
