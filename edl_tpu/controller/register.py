"""TTL-leased registration with a background refresh thread.

Reference parity: edl/utils/register.py (refresh every ttl/2; refresh
failure ⇒ the node silently drops out of the cluster :57-68). Here refresh
failure marks the register stopped so the launcher notices and exits.
"""

import threading

from edl_tpu.controller import constants
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger


class Register(object):
    def __init__(self, coord, service, server, value,
                 ttl=constants.ETCD_TTL):
        self._coord = coord
        self._service = service
        self._server = server
        self._ttl = ttl
        self._lease_id = coord.set_server_with_lease(service, server, value,
                                                     ttl)
        self._stop = threading.Event()
        self._broken = threading.Event()
        self._thread = threading.Thread(
            target=self._refresher, daemon=True,
            name="register-%s-%s" % (service, server))
        self._thread.start()

    def _refresher(self):
        while not self._stop.wait(self._ttl / 3.0):
            try:
                self._coord.refresh_server(self._service, self._server,
                                           self._lease_id)
            except errors.EdlError as e:
                logger.error("registration %s/%s lost: %r", self._service,
                             self._server, e)
                self._broken.set()
                return

    def is_broken(self):
        return self._broken.is_set()

    def stop(self, revoke=True):
        self._stop.set()
        self._thread.join(timeout=self._ttl)
        if revoke:
            try:
                self._coord.lease_revoke(self._lease_id)
            except errors.EdlError:
                pass
