"""Stage-keyed barrier: pods check in under the current cluster stage; when
the check-in set covers the cluster's pod set, everyone gets the cluster map.

Reference parity: edl/utils/pod_server.py:69-116 (Barrier collects pod_ids
per stage and returns the cluster JSON or a retryable error) and
pod_server_client.py:37-60 (retry-loop client). Served on the leader's pod
RPC server; clients locate the leader through the resource registry.
"""

import threading

from edl_tpu.controller import cluster as cluster_mod
from edl_tpu.controller import constants, leader
from edl_tpu.controller.resource_pods import load_resource_pods
from edl_tpu.obs import ledger as obs_ledger
from edl_tpu.obs.publisher import MetricsPublisher
from edl_tpu.robustness.policy import Deadline, RetryPolicy
from edl_tpu.rpc.client import RpcClient
from edl_tpu.rpc.server import RpcServer
from edl_tpu.utils import errors


class BarrierServicer(object):
    def __init__(self, coord):
        self._coord = coord
        self._lock = threading.Lock()
        self._stages = {}  # stage -> set(pod_id)

    def barrier(self, stage, pod_id):
        cluster = cluster_mod.load_from_store(self._coord)
        if cluster is None:
            raise errors.BarrierError("cluster not generated yet")
        if stage != cluster.stage:
            raise errors.BarrierError(
                "stage %s != current stage %s" % (stage, cluster.stage))
        with self._lock:
            checked = self._stages.setdefault(stage, set())
            checked.add(pod_id)
            want = set(cluster.pod_ids())
            if want.issubset(checked):
                # drop stale stages to bound memory
                for s in list(self._stages):
                    if s != stage:
                        del self._stages[s]
                return cluster.to_json()
        raise errors.BarrierError(
            "barrier waiting: %d/%d pods at stage %s"
            % (len(checked & want), len(want), stage))


class PodServer(object):
    """Per-pod RPC server hosting the barrier servicer (and, on the leader,
    answering every pod's barrier calls). Also exposes ``pod_stats`` — a
    scrapeable observability endpoint (net-new; the reference had no
    metrics surface, SURVEY.md §5.5)."""

    def __init__(self, coord, pod, stats_fn=None):
        self._rpc = RpcServer(host="0.0.0.0", port=0)
        self._servicer = BarrierServicer(coord)
        self._rpc.register("barrier", self._servicer.barrier)
        self._rpc.register("pod_stats", self._pod_stats)
        self._coord = coord
        self._stats_fn = stats_fn
        self._pod = pod
        # the pod process's registry/timeline feed for the fleet view
        # (job_stats merges every pod's obs_* publication)
        self._publisher = MetricsPublisher(coord, pod.id)

    def _pod_stats(self):
        try:  # a store hiccup must not fail the locally-known fields
            cluster = cluster_mod.load_from_store(self._coord)
        except Exception:
            cluster = None
        out = {
            "pod_id": self._pod.id,
            "pod_rank": self._pod.rank,
            "cluster_stage": cluster.stage if cluster else None,
            "cluster_size": len(cluster.pods) if cluster else 0,
            "world_size": cluster.world_size() if cluster else 0,
        }
        if self._stats_fn is not None:
            try:
                out.update(self._stats_fn())
            except Exception:  # stats must never break the barrier server
                pass
        return out

    def start(self):
        self._rpc.start()
        self._pod.port = self._rpc.port
        self._publisher.start()
        return self

    @property
    def port(self):
        return self._rpc.port

    def stop(self):
        self._publisher.stop()
        self._rpc.stop()


class _BarrierSession(object):
    """Caches the leader lookup and its RPC connection across the 0.5s
    retry loop; refreshed only when a call fails (leadership may move)."""

    def __init__(self, coord, pod_id):
        self._coord = coord
        self._pod_id = pod_id
        self._client = None
        self._leader_id = None

    def _connect(self):
        leader_id = leader.get_leader_id(self._coord)
        if leader_id is None:
            raise errors.BarrierError("no leader elected yet")
        if self._client is not None and leader_id == self._leader_id:
            return
        self.close()
        resources = load_resource_pods(self._coord)
        leader_pod = resources.get(leader_id)
        if leader_pod is None or leader_pod.port is None:
            raise errors.BarrierError(
                "leader pod %s not in resources" % leader_id)
        self._client = RpcClient(leader_pod.endpoint, timeout=10)
        self._leader_id = leader_id

    def attempt(self):
        self._connect()
        cluster = cluster_mod.load_from_store(self._coord)
        if cluster is None:
            raise errors.BarrierError("cluster not generated yet")
        try:
            cluster_json = self._client.call("barrier", cluster.stage,
                                             self._pod_id)
        except errors.ConnectError:
            self.close()
            raise
        return cluster_mod.Cluster().from_json(cluster_json)

    def close(self):
        if self._client is not None:
            self._client.close()
            self._client = None
        self._leader_id = None


# public alias: callers running their own retry loop (e.g. the
# launcher's abortable sliced barrier) reuse one session across attempts
BarrierSession = _BarrierSession


# a barrier attempt failing is the EXPECTED state while peers trickle
# in, so the cadence is a jittered ~fixed interval (multiplier 1), not
# an exponential backoff that would slow convergence right when the
# last pod arrives
_BARRIER_RETRY = RetryPolicy(base_delay=0.5, max_delay=0.75,
                             multiplier=1.0, jitter=0.4)


def barrier_wait(coord, pod_id, timeout=constants.BARRIER_TIMEOUT):
    """Block until every pod of the current cluster has checked in; returns
    the agreed Cluster. Raises TimeoutError_ after ``timeout`` seconds."""
    session = _BarrierSession(coord, pod_id)
    try:
        with obs_ledger.LEDGER.state("barrier_wait"):
            return _BARRIER_RETRY.call(session.attempt,
                                       deadline=Deadline(timeout))
    finally:
        session.close()
