"""Training-progress status written by trainers, read by the generator.

Reference parity: edl/utils/train_status.py (INITIAL/RUNNING/NEARTHEEND/
SUCCEED/FAILED :21-26; the generator stops scaling out when training is
NEARTHEEND — doc/edl_collective_design_doc.md:27).
"""

from edl_tpu.controller import constants


class TrainStatus(object):
    INITIAL = "INITIAL"
    RUNNING = "RUNNING"
    NEARTHEEND = "NEARTHEEND"
    SUCCEED = "SUCCEED"
    FAILED = "FAILED"


def save_train_status(coord, pod_id, status):
    coord.set_server_permanent(constants.SERVICE_TRAIN_STATUS, pod_id, status)


def load_train_status(coord, pod_id):
    return coord.get_value(constants.SERVICE_TRAIN_STATUS, pod_id)
