"""Trainer subprocess management: spawn with the env contract, watch, kill.

Reference parity: edl/utils/train_process.py — the PADDLE_* env contract
(:46-56) becomes the EDL_TPU_* contract below; process-tree SIGTERM→SIGKILL
via psutil (:89-112); child polling and rank-0 log tailing (:115-188).

The env contract (read back by edl_tpu.controller.env.TrainerEnv):
  EDL_TPU_JOB_ID / EDL_TPU_STORE_ENDPOINTS   job identity + coordination
  EDL_TPU_POD_ID / EDL_TPU_POD_RANK          this host
  EDL_TPU_TRAINER_ID / EDL_TPU_RANK_IN_POD   this process
  EDL_TPU_GLOBAL_RANK / EDL_TPU_WORLD_SIZE   process id / count for
                                             jax.distributed.initialize
  EDL_TPU_COORDINATOR                        rank-0 trainer endpoint
  EDL_TPU_TRAINER_ENDPOINTS                  all trainer endpoints (csv)
  EDL_TPU_LOCAL_DEVICES                      local chip indices (csv)
  EDL_TPU_CLUSTER_STAGE                      stage uuid of this incarnation
  EDL_TPU_MESH                               planned (dp, tp, pp, ep)
                                             factorization (json), when
                                             the generator ran a planner
"""

import json
import os
import subprocess
import sys
import time

import psutil

from edl_tpu.utils.logger import logger


class TrainerProc(object):
    def __init__(self, proc, trainer, log_path):
        self.proc = proc
        self.trainer = trainer
        self.log_path = log_path
        self.log_offset = 0


def start_trainers(job_env, pod, cluster, training_script, script_args,
                   log_dir):
    os.makedirs(log_dir, exist_ok=True)
    endpoints = cluster.trainer_endpoints()
    coordinator = endpoints[0]
    world = cluster.world_size()
    procs = []
    for t in pod.trainers:
        env = dict(os.environ)
        env.update({
            "EDL_TPU_JOB_ID": job_env.job_id,
            "EDL_TPU_STORE_ENDPOINTS": ",".join(job_env.store_endpoints),
            "EDL_TPU_POD_ID": pod.id,
            "EDL_TPU_POD_RANK": str(pod.rank),
            "EDL_TPU_TRAINER_ID": t.id,
            "EDL_TPU_RANK_IN_POD": str(t.rank_in_pod),
            "EDL_TPU_GLOBAL_RANK": str(t.global_rank),
            "EDL_TPU_WORLD_SIZE": str(world),
            "EDL_TPU_COORDINATOR": coordinator,
            "EDL_TPU_TRAINER_ENDPOINTS": ",".join(endpoints),
            "EDL_TPU_TRAINER_ENDPOINT": t.endpoint,
            "EDL_TPU_LOCAL_DEVICES": ",".join(str(d) for d in t.devices),
            "EDL_TPU_CLUSTER_STAGE": cluster.stage,
        })
        if job_env.checkpoint_path:
            env["EDL_TPU_CHECKPOINT_PATH"] = job_env.checkpoint_path
        if getattr(cluster, "mesh", None):
            # the generator's planned (dp, tp, pp, ep) factorization —
            # a stop-resume restart builds the SAME mesh the roofline
            # scored, not a flat dp default
            env["EDL_TPU_MESH"] = json.dumps(cluster.mesh)
        log_path = os.path.join(log_dir,
                                "workerlog.%d" % t.rank_in_pod)
        log_file = open(log_path, "ab", buffering=0)
        cmd = [sys.executable, "-u", training_script] + list(script_args)
        proc = subprocess.Popen(cmd, env=env, stdout=log_file,
                                stderr=subprocess.STDOUT)
        log_file.close()
        logger.info("spawned trainer rank=%s pid=%d log=%s", t.global_rank,
                    proc.pid, log_path)
        procs.append(TrainerProc(proc, t, log_path))
    return procs


def watch_trainers(procs, tail_rank0=True):
    """Poll children. Returns (all_done, any_failed). Tails the rank-0 log
    to our stdout (reference parity: train_process.py:115-127)."""
    alive, failed = False, False
    for tp in procs:
        ret = tp.proc.poll()
        if ret is None:
            alive = True
        elif ret != 0:
            failed = True
            logger.error("trainer pid=%d exited with code %d (log: %s)",
                         tp.proc.pid, ret, tp.log_path)
    if tail_rank0 and procs:
        tp = procs[0]
        try:
            with open(tp.log_path, "rb") as f:
                f.seek(tp.log_offset)
                chunk = f.read()
                tp.log_offset += len(chunk)
            if chunk:
                sys.stdout.write(chunk.decode("utf-8", "replace"))
                sys.stdout.flush()
        except OSError:
            pass
    return (not alive), failed


def terminate_trainers(procs, grace=10.0):
    """SIGTERM the whole process tree of each trainer, SIGKILL stragglers."""
    victims = []
    for tp in procs:
        if tp.proc.poll() is not None:
            continue
        try:
            parent = psutil.Process(tp.proc.pid)
            victims.extend(parent.children(recursive=True))
            victims.append(parent)
        except psutil.NoSuchProcess:
            pass
    for p in victims:
        try:
            p.terminate()
        except psutil.NoSuchProcess:
            pass
    _, survivors = psutil.wait_procs(victims, timeout=grace)
    for p in survivors:
        try:
            p.kill()
        except psutil.NoSuchProcess:
            pass
    for tp in procs:
        try:
            tp.proc.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            logger.error("trainer pid=%d refused to die", tp.proc.pid)
    time.sleep(0)  # let reaped children settle
