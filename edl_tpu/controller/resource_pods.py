"""Resource registry: every live launcher advertises its Pod JSON with a TTL.

Reference parity: edl/utils/resource_pods.py (keys
/<job>/resource/nodes/<pod_id>, TTL heartbeat; load_from_etcd:44;
wait_resource:57).
"""

from edl_tpu.controller import constants
from edl_tpu.controller.pod import Pod
from edl_tpu.controller.register import Register


class ResourceRegister(Register):
    def __init__(self, coord, pod):
        super().__init__(coord, constants.SERVICE_RESOURCE, pod.id,
                         pod.to_json())


def load_resource_pods(coord):
    """pod_id -> Pod for every live launcher."""
    return {name: Pod().from_json(value)
            for name, value in coord.get_service(constants.SERVICE_RESOURCE)}
