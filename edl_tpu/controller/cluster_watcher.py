"""Per-pod watcher on the cluster key; flags membership/stage changes.

Reference parity: edl/utils/cluster_watcher.py (_is_world_changed:71-95 —
changed when stage or the rank-ordered pod-id list differ). Built on the
store's long-poll watch instead of polling.
"""

import threading

from edl_tpu.controller import cluster as cluster_mod
from edl_tpu.controller import constants
from edl_tpu.utils.logger import logger


class ClusterWatcher(object):
    def __init__(self, coord, current_cluster):
        self._coord = coord
        self._current = current_cluster
        self._changed = threading.Event()
        self._new_cluster = None
        self._lock = threading.Lock()
        self._watcher = coord.watch_service(
            constants.SERVICE_CLUSTER, self._on_event,
            poll_timeout=constants.WATCH_INTERVAL)

    def _on_event(self, added, removed, all_servers):
        value = all_servers.get(constants.CLUSTER_SERVER)
        if value is None:
            return
        try:
            new = cluster_mod.Cluster().from_json(value)
        except Exception:
            logger.exception("bad cluster value in store")
            return
        if (new.stage != self._current.stage
                or new.pod_ids() != self._current.pod_ids()):
            with self._lock:
                self._new_cluster = new
            self._changed.set()

    def changed(self):
        return self._changed.is_set()

    def wait_changed(self, timeout):
        return self._changed.wait(timeout)

    def get_new_cluster(self):
        with self._lock:
            return self._new_cluster

    def stop(self):
        self._watcher.stop()
