"""Pod model: one launcher daemon on one TPU-VM host.

Reference parity: edl/utils/pod.py (uuid id, rank, addr, port, device list,
trainers; rank setter propagates global trainer ranks — pod.py:145-150;
from_env splits devices across nproc_per_node — pod.py:72-103). The TPU
default is one trainer process per host owning every local chip (the JAX
process model), rather than the per-GPU fan-out of the reference.
"""

from edl_tpu.controller.status import Status
from edl_tpu.controller.trainer import Trainer
from edl_tpu.utils import unique_name
from edl_tpu.utils.json_serializable import Serializable
from edl_tpu.utils.network import find_free_ports


class Pod(Serializable):
    _json_types = {"trainers": [Trainer]}

    def __init__(self):
        self.id = None
        self.rank = None
        self.addr = None
        self.port = None        # barrier/pod RPC port
        self.devices = []       # local chip indices on this host
        self.trainers = []
        self.status = Status.INITIAL

    @staticmethod
    def from_env(job_env):
        pod = Pod()
        pod.id = unique_name.uid()
        pod.rank = None
        pod.addr = job_env.pod_ip
        pod.port = None
        pod.devices = list(job_env.devices)
        n = job_env.nproc_per_node
        if pod.devices and n > len(pod.devices):
            raise ValueError(
                "nproc_per_node=%d exceeds %d local devices"
                % (n, len(pod.devices)))
        ports = find_free_ports(n)
        # contiguous split with the remainder spread over the first chunks,
        # so every device is assigned to exactly one trainer
        base, rem = divmod(len(pod.devices), n)
        offset = 0
        for i in range(n):
            size = base + (1 if i < rem else 0)
            devs = pod.devices[offset:offset + size]
            offset += size
            pod.trainers.append(Trainer.make(
                i, devs, "%s:%d" % (pod.addr, ports[i])))
        return pod

    def set_rank(self, rank, trainer_rank_base):
        """Assign pod rank and propagate global trainer ranks."""
        self.rank = rank
        for i, t in enumerate(self.trainers):
            t.global_rank = trainer_rank_base + i
        return trainer_rank_base + len(self.trainers)

    @property
    def endpoint(self):
        return "%s:%s" % (self.addr, self.port)
