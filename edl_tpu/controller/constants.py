"""Coordination-store service names and timing constants.

Reference parity: edl/utils/constants.py:15-27 (service names, TTL=15s).
Keys live under /<job_id>/<service>/nodes/<server> — job_id is the client
root, so jobs are fully namespace-isolated.
"""

SERVICE_RESOURCE = "resource"
SERVICE_LEADER = "leader"
SERVICE_CLUSTER = "cluster"
SERVICE_POD_STATUS = "pod_status"
SERVICE_JOB_STATUS = "job_status"
SERVICE_TRAIN_STATUS = "train_status"
SERVICE_READER = "reader"
SERVICE_STATE = "state"
SERVICE_JOB_FLAG = "job_flag"
SERVICE_METRICS = "metrics"
# leader HealthMonitor's health_report/v1 verdict doc (obs/health.py)
SERVICE_HEALTH = "health"
# peer-served restore plane: each trainer's StateServer endpoint +
# published snapshot version (edl_tpu/runtime/state_server.py)
SERVICE_STATE_SERVER = "state_server"
# zero-downtime live resize: the leader's two-phase intent, per-pod
# acks, and the trainers' live-capability keys
# (edl_tpu/runtime/live_resize.py)
SERVICE_LIVE_RESIZE = "live_resize"
# goodput autopilot's action/v1 journal and filed postmortem bundles
# (edl_tpu/obs/autopilot.py)
SERVICE_AUTOPILOT = "autopilot"
# watch-relay fan-out tree: each pod's WatchRelay advertises its
# endpoint here under a TTL lease; children resolve ancestors from
# this registry and fall through to direct store long-polls when no
# relay is advertised (edl_tpu/coordination/relay.py — the value is
# inlined there to keep coordination below controller; drift-guarded
# by tests/test_relay.py)
SERVICE_RELAY = "relay"
# diskless fault tolerance: each StateServer accepting erasure-coded
# partner checkpoint shards advertises here under a TTL lease; the
# pusher's partner ring and the rebuilder's holder set are both
# resolved from this registry (edl_tpu/runtime/redundancy.py)
SERVICE_REDUNDANCY = "redundancy"

LEADER_SERVER = "0"          # the single leader key
CLUSTER_SERVER = "cluster"   # the single cluster-map key
JOB_STATUS_SERVER = "job_status"

import os

ETCD_TTL = int(os.environ.get("EDL_TPU_TTL", "10"))  # registration lease TTL
REFRESH_INTERVAL = ETCD_TTL / 3.0
GENERATE_INTERVAL = 1.0      # leader cluster-generator period
WATCH_INTERVAL = 1.0         # cluster watcher poll period
SUPERVISE_INTERVAL = 1.0     # launcher supervision loop period
BARRIER_TIMEOUT = int(os.environ.get("EDL_TPU_BARRIER_TIMEOUT", "600"))
RESIZE_BARRIER_TIMEOUT = int(
    os.environ.get("EDL_TPU_RESIZE_BARRIER_TIMEOUT", "120"))
FLAG_WAIT_TIMEOUT = int(os.environ.get("EDL_TPU_FLAG_WAIT_TIMEOUT", "300"))
# trainers exiting with this code were PREEMPTED after an emergency
# checkpoint (liveft restart convention) — not failed; the launcher
# awaits the membership change and respawns in place if none comes
PREEMPT_EXIT_CODE = 101
PREEMPT_RESPAWN_WAIT = float(
    os.environ.get("EDL_TPU_PREEMPT_RESPAWN_WAIT", "20"))
