"""Job-side and trainer-side environment contracts.

Reference parity: edl/utils/env.py — JobEnv (:107, nodes_range "min:max"
:76-87, device discovery :22-30) and TrainerEnv (:179). GPU discovery via
CUDA_VISIBLE_DEVICES becomes TPU chip discovery: EDL_TPU_DEVICES if set,
else one entry per local chip reported by the runtime, else [0].
"""

import os

from edl_tpu.utils.network import get_host_ip


def _parse_nodes_range(s):
    if s is None:
        return 1, 1
    if ":" in s:
        lo, hi = s.split(":")
        lo, hi = int(lo), int(hi)
    else:
        lo = hi = int(s)
    if lo < 1 or hi < lo:
        raise ValueError("bad nodes_range %r" % s)
    return lo, hi


def _discover_devices():
    env = os.environ.get("EDL_TPU_DEVICES")
    if env is not None:
        return [int(x) for x in env.split(",") if x != ""]
    n = os.environ.get("EDL_TPU_NUM_DEVICES")
    if n is not None:
        return list(range(int(n)))
    return [0]


class JobEnv(object):
    def __init__(self, args=None):
        a = args or type("A", (), {})()

        def pick(attr, env_key, default=None):
            v = getattr(a, attr, None)
            if v is None:
                v = os.environ.get(env_key, default)
            return v

        self.job_id = pick("job_id", "EDL_TPU_JOB_ID")
        if not self.job_id:
            raise ValueError("job_id required (--job_id / EDL_TPU_JOB_ID)")
        endpoints = pick("store_endpoints", "EDL_TPU_STORE_ENDPOINTS",
                         "127.0.0.1:2379")
        self.store_endpoints = [e for e in str(endpoints).split(",") if e]
        self.min_nodes, self.max_nodes = _parse_nodes_range(
            pick("nodes_range", "EDL_TPU_NODES_RANGE", "1"))
        self.nproc_per_node = int(
            pick("nproc_per_node", "EDL_TPU_NPROC_PER_NODE", "1"))
        self.pod_ip = pick("pod_ip", "EDL_TPU_POD_IP", get_host_ip())
        self.devices = _discover_devices()
        self.checkpoint_path = pick("checkpoint_path",
                                    "EDL_TPU_CHECKPOINT_PATH", "")
        self.log_dir = pick("log_dir", "EDL_TPU_LOG_DIR", "./edl_tpu_logs")
        self.log_level = pick("log_level", "EDL_TPU_LOG_LEVEL", "INFO")


class TrainerEnv(object):
    """Read back the contract written by train_process.start_trainers."""

    def __init__(self, environ=None):
        e = environ or os.environ
        self.job_id = e.get("EDL_TPU_JOB_ID")
        self.store_endpoints = [
            x for x in e.get("EDL_TPU_STORE_ENDPOINTS", "").split(",") if x]
        self.pod_id = e.get("EDL_TPU_POD_ID")
        self.pod_rank = int(e.get("EDL_TPU_POD_RANK", "0"))
        self.trainer_id = e.get("EDL_TPU_TRAINER_ID")
        self.rank_in_pod = int(e.get("EDL_TPU_RANK_IN_POD", "0"))
        self.global_rank = int(e.get("EDL_TPU_GLOBAL_RANK", "0"))
        self.world_size = int(e.get("EDL_TPU_WORLD_SIZE", "1"))
        self.coordinator = e.get("EDL_TPU_COORDINATOR")
        self.trainer_endpoints = [
            x for x in e.get("EDL_TPU_TRAINER_ENDPOINTS", "").split(",") if x]
        self.endpoint = e.get("EDL_TPU_TRAINER_ENDPOINT")
        self.local_devices = [
            int(x) for x in e.get("EDL_TPU_LOCAL_DEVICES", "").split(",")
            if x != ""]
        self.cluster_stage = e.get("EDL_TPU_CLUSTER_STAGE")
        self.checkpoint_path = e.get("EDL_TPU_CHECKPOINT_PATH", "")
        # the generator's planned mesh factorization ({axis: size}),
        # None when no planner ran — training scripts pass it to
        # make_mesh so a restart lands on the scored factorization
        self.mesh = None
        raw = e.get("EDL_TPU_MESH")
        if raw:
            try:
                import json
                self.mesh = {str(k): int(v)
                             for k, v in json.loads(raw).items()}
            except (ValueError, TypeError, AttributeError):
                self.mesh = None

    @property
    def is_rank0(self):
        return self.global_rank == 0

    @property
    def under_launcher(self):
        return self.job_id is not None and self.trainer_id is not None
