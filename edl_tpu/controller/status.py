"""Pod/job status enums + coordination-store persistence.

Reference parity: edl/utils/status.py (Status enum :22-27, save/load pod and
job status under the pod_status/job_status services :37-113).
"""

from edl_tpu.controller import constants
from edl_tpu.utils import errors


class Status(object):
    INITIAL = "INITIAL"
    RUNNING = "RUNNING"
    PENDING = "PENDING"
    SUCCEED = "SUCCEED"
    FAILED = "FAILED"


def save_pod_status(coord, pod_id, status):
    coord.set_server_permanent(constants.SERVICE_POD_STATUS, pod_id, status)


def load_pod_status(coord, pod_id):
    return coord.get_value(constants.SERVICE_POD_STATUS, pod_id)


def load_pods_status(coord):
    """pod_id -> status for every pod that ever reported."""
    return dict(coord.get_service(constants.SERVICE_POD_STATUS))


def save_job_status(coord, status):
    coord.set_server_permanent(constants.SERVICE_JOB_STATUS,
                               constants.JOB_STATUS_SERVER, status)


def load_job_status(coord):
    return coord.get_value(constants.SERVICE_JOB_STATUS,
                           constants.JOB_STATUS_SERVER)


def save_job_flag(coord, pod_id, ok):
    """Per-pod exit flag; the leader aggregates these into the job status
    (reference parity: launcher.py:99-130 _exit)."""
    coord.set_server_permanent(constants.SERVICE_JOB_FLAG, pod_id,
                               Status.SUCCEED if ok else Status.FAILED)


def load_job_flags(coord):
    return dict(coord.get_service(constants.SERVICE_JOB_FLAG))


def check_not_failed(coord):
    if load_job_status(coord) == Status.FAILED:
        raise errors.StatusError("job status is FAILED")
