"""Elastic launch CLI: python -m edl_tpu.controller.launch <args> script.py

Reference parity: edl/collective/launch.py:32-59 (parse → JobEnv → store →
skip-if-SUCCEED → Pod.from_env → Launcher.init/launch).
"""

import sys

from edl_tpu.controller import constants, status
from edl_tpu.controller.args import parse_args
from edl_tpu.controller.env import JobEnv
from edl_tpu.controller.launcher import Launcher
from edl_tpu.controller.pod import Pod
from edl_tpu.coordination.client import CoordClient
from edl_tpu.utils.logger import logger


def main(argv=None):
    args = parse_args(argv)
    job_env = JobEnv(args)
    coord = CoordClient(job_env.store_endpoints, root=job_env.job_id)

    job_status = status.load_job_status(coord)
    if job_status == status.Status.SUCCEED:
        logger.info("job %s already SUCCEED; nothing to do", job_env.job_id)
        return 0
    if job_status == status.Status.FAILED:
        # a FAILED verdict and its stale cluster map would deadlock any new
        # launcher (the generator refuses to run under a terminal status);
        # a fresh launch means the operator wants a retry — reset control
        # state (training state/checkpoints are untouched)
        logger.warning("job %s previously FAILED; resetting control state "
                       "for retry", job_env.job_id)
        for service in (constants.SERVICE_JOB_STATUS, constants.SERVICE_CLUSTER,
                        constants.SERVICE_JOB_FLAG, constants.SERVICE_POD_STATUS,
                        constants.SERVICE_TRAIN_STATUS):
            coord._call("store_delete_prefix", coord.service_prefix(service))

    pod = Pod.from_env(job_env)
    launcher = Launcher(job_env, pod, coord, args.training_script,
                        args.training_script_args).init()
    ok = launcher.launch()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
