"""Cluster model: the rank-ordered pod map plus a stage uuid.

Reference parity: edl/utils/cluster.py — stage uuid regenerated on every
membership change (:137-138), leader = pods[0] (:129), store load helpers
(:153-175). The stage is the epoch token of the barrier protocol.
"""

from edl_tpu.controller import constants
from edl_tpu.controller.pod import Pod
from edl_tpu.controller.status import Status
from edl_tpu.utils import errors, unique_name
from edl_tpu.utils.json_serializable import Serializable
from edl_tpu.utils.errors import handle_errors_until_timeout


class Cluster(Serializable):
    _json_types = {"pods": [Pod]}

    def __init__(self):
        self.stage = unique_name.uid()
        self.pods = []
        self.status = Status.INITIAL
        # the generator's planned (dp, tp, pp, ep) factorization for
        # this stage's device count ({axis: size}, or None = flat dp);
        # rides the live-resize intent so survivors rebuild THIS mesh,
        # and the cluster map so stop-resume restarts do too
        self.mesh = None
        # redundancy partner rings ({pod_id: [partner pod ids]}, or
        # None): recorded for observability/audit — the rule itself
        # (redundancy.partner_ring over the sorted member set) is a
        # pure function every pod recomputes from this map, so the
        # assignment survives any resize with no negotiation, the
        # same determinism trick as the relay tree
        self.redundancy = None

    def new_stage(self):
        self.stage = unique_name.uid()

    def assign_ranks(self):
        base = 0
        for rank, pod in enumerate(self.pods):
            base = pod.set_rank(rank, base)

    def pod_ids(self):
        return [p.id for p in self.pods]

    def get_pod(self, pod_id):
        for p in self.pods:
            if p.id == pod_id:
                return p
        return None

    def leader_pod(self):
        return self.pods[0] if self.pods else None

    def get_leader_endpoint(self):
        leader = self.leader_pod()
        return leader.endpoint if leader else None

    def trainer_endpoints(self):
        return [t.endpoint for p in self.pods for t in p.trainers]

    def world_size(self):
        return sum(len(p.trainers) for p in self.pods)

    def total_devices(self):
        return sum(len(t.devices) for p in self.pods for t in p.trainers)


def save_to_store(coord, cluster):
    coord.set_server_permanent(constants.SERVICE_CLUSTER,
                               constants.CLUSTER_SERVER, cluster.to_json())


def load_from_store(coord):
    value = coord.get_value(constants.SERVICE_CLUSTER,
                            constants.CLUSTER_SERVER)
    if value is None:
        return None
    return Cluster().from_json(value)


@handle_errors_until_timeout
def wait_to_load_from_store(coord):
    cluster = load_from_store(coord)
    if cluster is None:
        raise errors.NotFoundError("cluster not generated yet")
    return cluster
