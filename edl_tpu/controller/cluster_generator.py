"""Leader-only cluster generator: computes the next cluster map from live
resources + statuses and commits it behind a leadership-guarded transaction.

Reference parity: edl/utils/cluster_generator.py — initial assembly from
resource pods (:95-134), disappeared/failed detection (:179-192), appending
INITIAL pods while below max_nodes (:136-153), min_nodes enforcement
(:255-264), and the leadership-guarded commit (:223-250).

TPU twist: a ``topology_valid`` hook constrains legal world sizes — TPU
slices only support certain host counts (SURVEY.md §7 "hard parts"), unlike
the reference's any-count-in-[min,max].
"""

import threading

from edl_tpu.controller import cluster as cluster_mod
from edl_tpu.controller import constants, status, train_status
from edl_tpu.controller.cluster import Cluster
from edl_tpu.controller.resource_pods import load_resource_pods
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger


class Generator(object):
    def __init__(self, coord, pod_id, min_nodes, max_nodes,
                 topology_valid=None):
        self._coord = coord
        self._pod_id = pod_id
        self._min = min_nodes
        self._max = max_nodes
        self._topology_valid = topology_valid or (lambda n: True)
        self._stop = threading.Event()
        self._thread = None
        self._lock = threading.Lock()

    def start(self):
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop = threading.Event()
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="cluster-generator")
            self._thread.start()
        return self

    def stop(self):
        with self._lock:
            self._stop.set()
            if self._thread is not None:
                self._thread.join(timeout=5)
                self._thread = None

    def _run(self):
        while not self._stop.wait(constants.GENERATE_INTERVAL):
            try:
                self._generate_once()
            except errors.EdlError as e:
                logger.warning("cluster generation error: %r", e)
            except Exception:
                logger.exception("cluster generation failed")

    # -- the actual policy ---------------------------------------------------

    def _generate_once(self):
        job = status.load_job_status(self._coord)
        if job in (status.Status.SUCCEED, status.Status.FAILED):
            return
        current = cluster_mod.load_from_store(self._coord)
        resources = load_resource_pods(self._coord)
        statuses = status.load_pods_status(self._coord)

        if current is None or not current.pods:
            new = self._initial_cluster(resources)
        else:
            new = self._next_cluster(current, resources, statuses)
        if new is None:
            return
        new.assign_ranks()
        self._commit(new)

    def _initial_cluster(self, resources):
        if len(resources) < self._min:
            return None
        n = min(len(resources), self._max)
        while n >= self._min and not self._topology_valid(n):
            n -= 1
        if n < self._min:
            logger.warning("no topology-valid size in [%d,%d] for %d pods",
                           self._min, self._max, len(resources))
            return None
        cluster = Cluster()
        # deterministic order: leader pod first, then by pod id
        ids = sorted(resources.keys())
        if self._pod_id in ids:
            ids.remove(self._pod_id)
            ids.insert(0, self._pod_id)
        cluster.pods = [resources[i] for i in ids[:n]]
        cluster.status = status.Status.RUNNING
        logger.info("initial cluster: %d pods, stage %s", n, cluster.stage)
        return cluster

    def _next_cluster(self, current, resources, statuses):
        alive, gone, finished = [], [], []
        for pod in current.pods:
            if statuses.get(pod.id) == status.Status.SUCCEED:
                # graceful departure: exclude from future clusters but do
                # not count as a failure (its launcher has exited and can
                # never answer a barrier again)
                finished.append(pod.id)
            elif pod.id not in resources:
                gone.append(pod.id)
            elif statuses.get(pod.id) == status.Status.FAILED:
                gone.append(pod.id)
            else:
                alive.append(pod)

        added = []
        if not finished and self._scale_out_allowed(statuses):
            room = self._max - len(alive)
            joinable = sorted(i for i in resources
                              if i not in set(current.pod_ids()))
            for pod_id in joinable[:max(0, room)]:
                added.append(resources[pod_id])

        if not gone and not added and not finished:
            return None
        if finished and not gone:
            # pods are completing; don't churn the cluster under them
            return None

        # shrink to the largest topology-valid size >= min (drop newly
        # added pods first, then alive pods from the tail)
        candidates = alive + added
        n = len(candidates)
        while n >= self._min and not self._topology_valid(n):
            n -= 1
        if n < self._min:
            logger.error(
                "no topology-valid cluster size in [%d,%d] reachable from "
                "%d live pods; marking job FAILED", self._min, self._max,
                len(candidates))
            status.save_job_status(self._coord, status.Status.FAILED)
            return None
        candidates = candidates[:n]

        new = Cluster()
        new.pods = candidates
        new.status = status.Status.RUNNING
        logger.info("new cluster: %d pods (%d gone, %d finished, %d added), "
                    "stage %s", n, len(gone), len(finished),
                    len([p for p in candidates if p in added]), new.stage)
        return new

    def _scale_out_allowed(self, statuses):
        """Don't bother scaling out when training is nearly done
        (reference parity: doc/edl_collective_design_doc.md:27)."""
        if status.Status.SUCCEED in statuses.values():
            return False
        all_ts = self._coord.get_service(constants.SERVICE_TRAIN_STATUS)
        for _, ts in all_ts:
            if ts in (train_status.TrainStatus.NEARTHEEND,
                      train_status.TrainStatus.SUCCEED):
                return False
        return True

    def _commit(self, new):
        cluster_key = self._coord.service_prefix(
            constants.SERVICE_CLUSTER) + constants.CLUSTER_SERVER
        ok = self._coord.put_if_leader(
            constants.SERVICE_LEADER, constants.LEADER_SERVER, self._pod_id,
            [(cluster_key, new.to_json())])
        if not ok:
            raise errors.NotLeaderError(
                "pod %s is no longer leader; cluster not committed"
                % self._pod_id)
