"""Leader-only cluster generator: computes the next cluster map from live
resources + statuses and commits it behind a leadership-guarded transaction.

Reference parity: edl/utils/cluster_generator.py — initial assembly from
resource pods (:95-134), disappeared/failed detection (:179-192), appending
INITIAL pods while below max_nodes (:136-153), min_nodes enforcement
(:255-264), and the leadership-guarded commit (:223-250).

TPU twist: a ``topology_valid`` hook constrains legal world sizes — TPU
slices only support certain host counts (SURVEY.md §7 "hard parts"), unlike
the reference's any-count-in-[min,max].
"""

import threading
import time
import uuid

from edl_tpu.controller import cluster as cluster_mod
from edl_tpu.controller import constants, status, train_status
from edl_tpu.controller.cluster import Cluster
from edl_tpu.controller.resource_pods import load_resource_pods
from edl_tpu.runtime import live_resize as live_mod
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger


class Generator(object):
    def __init__(self, coord, pod_id, min_nodes, max_nodes,
                 topology_valid=None, below_min_grace=None,
                 preferred_victims=None, live_ack_timeout=10.0,
                 scale_out_gate=None, mesh_planner=None):
        self._coord = coord
        self._pod_id = pod_id
        self._min = min_nodes
        self._max = max_nodes
        self._topology_valid = topology_valid or (lambda n: True)
        # optional roofline hook (parallel/costmodel.make_planner):
        # callable(total_devices, current_factors) -> {axis: size} or
        # None. With it, a new world commits the best-scored legal
        # (dp, tp, pp, ep) factorization instead of flat dp; without
        # it, cluster.mesh stays None and nothing changes.
        self._mesh_planner = mesh_planner
        # advisory hook (obs/health.HealthMonitor.preferred_victims):
        # when a shrink must drop pods, flagged stragglers go first
        self._preferred_victims = preferred_victims
        # optional veto hook (obs/autopilot.Autopilot.scale_out_allowed):
        # False suppresses adding joinable pods this pass. Fail-open —
        # a broken gate must not freeze growth.
        self._scale_out_gate = scale_out_gate
        # directed evictions (autopilot): pod -> monotonic expiry. A
        # directed pod is treated as gone on the next pass and excluded
        # from joinable until the directive expires (it stays REGISTERED
        # until its launcher exits, so without the exclusion the very
        # next pass would re-add it — the evict→rejoin flap).
        self._directed = {}
        self._stop = threading.Event()
        self._thread = None
        self._lock = threading.Lock()
        # a below-min observation is NOT immediately fatal: a mass lease
        # lapse (store failover, CPU starvation of every launcher's
        # heartbeat thread at once) looks identical to mass pod death
        # for up to a TTL, and live launchers re-register within one
        # (controller/register.py self-heals). Only a below-min state
        # that PERSISTS past the re-registration window is real.
        self._below_min_since = None
        self._below_min_grace = (below_min_grace if below_min_grace
                                 is not None
                                 else 2.0 * constants.ETCD_TTL)
        # how long the two-phase live commit waits for survivor acks
        # before aborting to the stop-resume ladder
        self._live_ack_timeout = float(live_ack_timeout)

    def start(self):
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop = threading.Event()
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="cluster-generator")
            self._thread.start()
        return self

    def stop(self):
        with self._lock:
            self._stop.set()
            if self._thread is not None:
                self._thread.join(timeout=5)
                self._thread = None

    def _run(self):
        while not self._stop.wait(constants.GENERATE_INTERVAL):
            try:
                self._generate_once()
            except errors.EdlError as e:
                logger.warning("cluster generation error: %r", e)
            except Exception:
                logger.exception("cluster generation failed")

    # -- the actual policy ---------------------------------------------------

    def _generate_once(self):
        job = status.load_job_status(self._coord)
        if job in (status.Status.SUCCEED, status.Status.FAILED):
            return
        self._abort_stale_intent()
        current = cluster_mod.load_from_store(self._coord)
        resources = load_resource_pods(self._coord)
        statuses = status.load_pods_status(self._coord)

        if current is None or not current.pods:
            new = self._initial_cluster(resources)
        else:
            new = self._next_cluster(current, resources, statuses)
        if new is None:
            return
        new.assign_ranks()
        self._plan_mesh(new, current)
        self._plan_redundancy(new)
        self._commit(new, current=current)

    @staticmethod
    def _cluster_devices(cluster):
        """Total accelerator count of a cluster map (trainer devices
        when assigned, else the pod's own device list)."""
        return sum((sum(len(t.devices) for t in p.trainers)
                    or len(getattr(p, "devices", ()) or ()))
                   for p in cluster.pods)

    def _plan_mesh(self, new, current):
        """Attach the planner's (dp, tp, pp, ep) choice for the new
        world's device count. The planner sees the mesh the fleet is
        currently ON, so its score includes the reshard cost of moving
        away from it. Fail-open: a broken planner means flat dp, never
        a blocked commit."""
        if self._mesh_planner is None:
            new.mesh = getattr(current, "mesh", None) \
                if current is not None else None
            return
        cur = getattr(current, "mesh", None) \
            if current is not None else None
        try:
            new.mesh = self._mesh_planner(self._cluster_devices(new),
                                          cur)
            if new.mesh is not None:
                logger.info("mesh plan for stage %s: %s", new.stage,
                            new.mesh)
        except Exception:
            logger.exception("mesh planner failed; committing flat dp")
            new.mesh = None

    @staticmethod
    def _plan_redundancy(new):
        """Attach the redundancy partner rings for the new membership
        to the cluster map. The ring rule (redundancy.partner_ring:
        the next k+m members in the sorted cyclic order of the pod-id
        set) is a pure function of the membership — every pod derives
        the identical assignment from the committed map, so rings
        survive any resize with no negotiation, exactly like the relay
        tree's parent rule. The map copy exists for observability and
        drift tests, not as a source of truth. Fail-open: a planning
        error never blocks a commit."""
        try:
            from edl_tpu.runtime import redundancy
            if not redundancy.enabled():
                new.redundancy = None
                return
            k, m = redundancy.coding_params()
            ids = new.pod_ids()
            new.redundancy = {
                pid: redundancy.partner_ring(ids, pid, k + m)
                for pid in ids}
        except Exception:
            logger.exception("redundancy ring planning failed; "
                             "committing without rings")
            new.redundancy = None

    def _initial_cluster(self, resources):
        if len(resources) < self._min:
            return None
        n = min(len(resources), self._max)
        while n >= self._min and not self._topology_valid(n):
            n -= 1
        if n < self._min:
            logger.warning("no topology-valid size in [%d,%d] for %d pods",
                           self._min, self._max, len(resources))
            return None
        cluster = Cluster()
        # deterministic order: leader pod first, then by pod id
        ids = sorted(resources.keys())
        if self._pod_id in ids:
            ids.remove(self._pod_id)
            ids.insert(0, self._pod_id)
        cluster.pods = [resources[i] for i in ids[:n]]
        cluster.status = status.Status.RUNNING
        logger.info("initial cluster: %d pods, stage %s", n, cluster.stage)
        return cluster

    def _failover_hold(self):
        """True while a store failover's settle window is open: the
        promoted standby plants a leased guard key (standby.py), because
        a failover drops EVERY ephemeral registration at once — reading
        "missing from resources" as "dead" during the re-registration
        window would evict live pods from their own cluster. Explicit
        FAILED statuses still count; only absence is forgiven."""
        from edl_tpu.coordination.standby import failover_guard_active
        return failover_guard_active(self._coord)

    # -- directed eviction (the autopilot's actuator) ------------------------

    def direct_evict(self, pod_id, ttl_s=30.0):
        """Direct the next generation pass to drop ``pod_id`` from the
        cluster (and keep it out of joinable for ``ttl_s``, since the
        evicted pod stays store-registered until its launcher exits —
        re-adding it immediately would be the evict→rejoin flap). The
        ordinary shrink/backfill machinery does the rest: the cluster
        re-forms without the pod, and a standby (surplus registered pod)
        backfills through the usual scale-out. Refuses to evict the pod
        hosting this generator — decapitating the leader to save the
        job is never a remediation."""
        if pod_id == self._pod_id:
            raise errors.EdlError(
                "refusing directed self-eviction of leader pod %s"
                % pod_id)
        with self._lock:
            self._directed[pod_id] = time.monotonic() + float(ttl_s)
        logger.warning("directed eviction: pod %s will be dropped on the "
                       "next generation pass (rejoin blocked %.0fs)",
                       pod_id, ttl_s)
        return True

    def _directed_evictions(self):
        """Live directed-eviction set; expired directives pruned."""
        now = time.monotonic()
        with self._lock:
            expired = [p for p, t in self._directed.items() if t <= now]
            for pod in expired:
                del self._directed[pod]
            return set(self._directed)

    def _next_cluster(self, current, resources, statuses):
        hold = self._failover_hold()
        directed = self._directed_evictions()
        alive, gone, finished = [], [], []
        for pod in current.pods:
            if statuses.get(pod.id) == status.Status.SUCCEED:
                # graceful departure: exclude from future clusters but do
                # not count as a failure (its launcher has exited and can
                # never answer a barrier again)
                finished.append(pod.id)
            elif statuses.get(pod.id) == status.Status.FAILED:
                gone.append(pod.id)
            elif pod.id in directed:
                # autopilot-directed eviction: drop it even though it is
                # still registered and running
                gone.append(pod.id)
            elif pod.id not in resources:
                if hold:
                    logger.info("failover settle window: keeping pod %s "
                                "despite missing registration",
                                pod.id)
                    alive.append(pod)
                else:
                    gone.append(pod.id)
            else:
                alive.append(pod)

        def reachable(n_hi):
            n = n_hi
            while n >= self._min:
                if self._topology_valid(n):
                    return True
                n -= 1
            return False

        if reachable(len(alive)):
            # healthy membership clears any pending below-min clock,
            # INCLUDING the no-change early return below (a healed blip
            # commits no new cluster, so the reset cannot live only on
            # the cluster-forming path). "Healthy" must mean a VALID
            # cluster is reachable, not merely alive >= min — when the
            # topology hook rejects every size down to min, resetting
            # here would re-arm the grace clock each pass and the job
            # would livelock instead of failing.
            self._below_min_since = None

        added = []
        if not finished and self._scale_out_allowed(statuses):
            room = self._max - len(alive)
            joinable = sorted(i for i in resources
                              if i not in set(current.pod_ids())
                              and i not in directed)
            for pod_id in joinable[:max(0, room)]:
                added.append(resources[pod_id])

        if not gone and not added and not finished:
            return None
        if finished and not gone:
            # pods are completing; don't churn the cluster under them
            return None

        # shrink to the largest topology-valid size >= min (drop newly
        # added pods first, then alive pods from the tail — unless the
        # health monitor has flagged stragglers, which move to the tail
        # so the eviction lands on them first)
        candidates = alive + added
        n = len(candidates)
        while n >= self._min and not self._topology_valid(n):
            n -= 1
        if n < self._min:
            now = time.monotonic()
            if self._below_min_since is None:
                self._below_min_since = now
            waited = now - self._below_min_since
            if waited < self._below_min_grace:
                logger.warning(
                    "below min_nodes: %d live pods < %d for %.1fs "
                    "(grace %.1fs) — waiting for re-registration before "
                    "declaring failure", len(candidates), self._min,
                    waited, self._below_min_grace)
                return None
            logger.error(
                "no topology-valid cluster size in [%d,%d] reachable from "
                "%d live pods for %.1fs; marking job FAILED", self._min,
                self._max, len(candidates), waited)
            status.save_job_status(self._coord, status.Status.FAILED)
            return None
        self._below_min_since = None
        if n < len(candidates):
            candidates = self._order_for_eviction(candidates, n)
        candidates = candidates[:n]

        new = Cluster()
        new.pods = candidates
        new.status = status.Status.RUNNING
        logger.info("new cluster: %d pods (%d gone, %d finished, %d added), "
                    "stage %s", n, len(gone), len(finished),
                    len([p for p in candidates if p in added]), new.stage)
        return new

    def _order_for_eviction(self, candidates, n):
        """Reorder ``candidates`` before the tail-drop to ``n`` so
        health-flagged stragglers are evicted first. The hook is
        ADVISORY and fail-open: any error means the default order
        stands; the leader pod is never moved (evicting the pod that
        hosts the generator and monitor would decapitate the job to
        save it); the worst-ranked victim goes LAST so a multi-pod
        shrink takes the worst first."""
        if self._preferred_victims is None:
            return candidates
        try:
            ranked = list(self._preferred_victims() or ())
        except Exception:
            logger.exception("preferred_victims hook failed; using "
                             "default eviction order")
            return candidates
        victims = [v for v in ranked
                   if v != self._pod_id
                   and v in {p.id for p in candidates}]
        if not victims:
            return candidates
        tail_order = {v: i for i, v in enumerate(victims)}
        keep = [p for p in candidates if p.id not in tail_order]
        # candidates[:n] keeps the FRONT, so eviction consumes the tail
        # back-to-front: the worst-ranked victim (rank 0) must sit LAST
        tail = sorted((p for p in candidates if p.id in tail_order),
                      key=lambda p: -tail_order[p.id])
        logger.info("scale-in eviction order honors health verdicts: "
                    "victims %s move to the tail", victims)
        return keep + tail

    def _scale_out_allowed(self, statuses):
        """Don't bother scaling out when training is nearly done
        (reference parity: doc/edl_collective_design_doc.md:27), or
        while the autopilot's goodput-payback gate vetoes growth
        (fail-open: a broken gate never blocks)."""
        if status.Status.SUCCEED in statuses.values():
            return False
        all_ts = self._coord.get_service(constants.SERVICE_TRAIN_STATUS)
        for _, ts in all_ts:
            if ts in (train_status.TrainStatus.NEARTHEEND,
                      train_status.TrainStatus.SUCCEED):
                return False
        if self._scale_out_gate is not None:
            try:
                if self._scale_out_gate() is False:
                    logger.info("scale-out vetoed by autopilot gate "
                                "(goodput payback outside horizon)")
                    return False
            except Exception:
                logger.exception("scale_out_gate failed; allowing "
                                 "scale-out (fail open)")
        return True

    # -- live resize: the leader-coordinated two-phase commit ----------------

    def _abort_stale_intent(self):
        """Leader-loss-mid-reshard recovery: a leader that finds a
        ``prepare`` intent it did not publish (or one past its
        deadline) aborts it, so survivors stop draining and the
        stop-resume ladder runs. A coordinator death between prepare
        and commit therefore degrades to stop-resume, never a wedge."""
        try:
            intent = live_mod.read_intent(self._coord)
        except errors.EdlError:
            return
        if not intent or intent.get("phase") != live_mod.PREPARE:
            return
        foreign = intent.get("leader") not in (None, self._pod_id)
        if not foreign and not live_mod.intent_expired(intent):
            return
        if live_mod.abort(self._coord, self._pod_id, intent,
                          reason="stale prepare (leader=%s, expired=%s)"
                          % (intent.get("leader"),
                             live_mod.intent_expired(intent))):
            logger.warning("aborted stale live-resize intent %s "
                           "(published by %s)", intent.get("id"),
                           intent.get("leader"))

    def _live_eligible(self, current, new):
        """The live in-place path replaces kill/respawn only when every
        pod of the NEW cluster is already running (a survivors-only
        change — a joining pod has no process to reshape) and each
        survivor advertises the live-resize capability key."""
        if current is None or not current.pods or not new.pods:
            return False
        cur_ids = set(current.pod_ids())
        new_ids = set(new.pod_ids())
        if not new_ids.issubset(cur_ids):
            return False
        try:
            ready = live_mod.ready_participants(self._coord)
        except errors.EdlError:
            return False
        return new_ids.issubset(ready)

    def _try_live_commit(self, new, cluster_key):
        """Two-phase live commit: leader-guarded ``prepare`` intent →
        every survivor drains + reshards + acks → one guarded
        transaction flips the intent to ``commit`` AND installs the new
        cluster map, so the launcher adopts it without killing anyone.
        Any nack, ack timeout, or lost leadership aborts the intent and
        returns False — the caller falls through to stop-resume."""
        devices = {p.id: (sum(len(t.devices) for t in p.trainers)
                          or len(p.devices)) for p in new.pods}
        intent = live_mod.make_intent(
            uuid.uuid4().hex, new.pod_ids(), devices=devices,
            leader=self._pod_id, cluster_json=new.to_json(),
            mesh=getattr(new, "mesh", None),
            deadline_s=self._live_ack_timeout + 10.0)
        if not live_mod.publish_prepare(self._coord, self._pod_id, intent):
            raise errors.NotLeaderError(
                "pod %s lost leadership publishing live-resize intent"
                % self._pod_id)
        all_ok, acks = live_mod.wait_for_acks(self._coord, intent,
                                              self._live_ack_timeout)
        if not all_ok:
            nacks = sorted(w for w, a in acks.items() if not a.get("ok"))
            missing = sorted(set(intent["survivors"]) - set(acks))
            live_mod.abort(self._coord, self._pod_id, intent,
                           reason="nack=%s missing=%s" % (nacks, missing))
            logger.warning("live resize %s aborted (nack=%s, missing=%s);"
                           " falling back to stop-resume", intent["id"],
                           nacks, missing)
            return False
        if not live_mod.commit(self._coord, self._pod_id, intent,
                               extra_puts=[(cluster_key, new.to_json())]):
            raise errors.NotLeaderError(
                "pod %s lost leadership committing live resize"
                % self._pod_id)
        logger.info("live resize %s committed: %d survivors adopted the "
                    "new cluster in place (no kill)", intent["id"],
                    len(intent["survivors"]))
        return True

    def _commit(self, new, current=None):
        cluster_key = self._coord.service_prefix(
            constants.SERVICE_CLUSTER) + constants.CLUSTER_SERVER
        if self._live_eligible(current, new):
            if self._try_live_commit(new, cluster_key):
                return
        ok = self._coord.put_if_leader(
            constants.SERVICE_LEADER, constants.LEADER_SERVER, self._pod_id,
            [(cluster_key, new.to_json())])
        if not ok:
            raise errors.NotLeaderError(
                "pod %s is no longer leader; cluster not committed"
                % self._pod_id)
