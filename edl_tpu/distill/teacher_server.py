"""TPU teacher inference server — the in-tree replacement for the Paddle
Serving GPU servers the reference's distill plane called into
(SURVEY.md §2.6; client usage distill_worker.py:197-321).

Serves a jitted model function over the framed-RPC substrate:
- ``get_feed_fetch()`` — feed/fetch name+shape introspection (the contract
  the reference client discovered from serving conf files);
- ``predict(feed)`` — feed dict of ndarrays → fetch dict of ndarrays.
  Inputs are padded to a fixed batch size so XLA compiles once.
- ``stats()`` — device-batch occupancy counters for the bench/ops planes.

Adaptive batching (Clipper/ORCA style): handler threads no longer run
the model themselves behind one device lock — they enqueue (feed,
future) items and a single device thread coalesces queued requests from
ANY client into one compiled-batch program execution, copying rows into
a preallocated feed buffer (no per-request ``np.concatenate``) and
scattering row slices of the output back to each waiter. A half-full
student batch therefore shares its program execution with other
requests instead of burning a full-batch run alone; single-request
behavior, the read-only feed contract, and the wire protocol are
unchanged.

A teacher registers itself into the coordination store via
edl_tpu.distill.registry and is matched to students by the discovery/
balance layer.
"""

import argparse
import queue
import signal
import threading
import time

import numpy as np

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.robustness import faults
from edl_tpu.robustness.policy import Deadline
from edl_tpu.rpc import ndarray as nd
from edl_tpu.rpc.server import FEATURES as _RPC_FEATURES
from edl_tpu.rpc.server import RpcServer
from edl_tpu.serve.admission import AdmissionController
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger

_DEVICE_BATCHES = obs_metrics.counter(
    "edl_teacher_batches_total", "teacher device-batch executions")
_DEVICE_ROWS = obs_metrics.counter(
    "edl_teacher_rows_total", "real (unpadded) rows served")
_BATCH_FILL = obs_metrics.histogram(
    "edl_teacher_batch_fill", "real rows per device execution as a "
    "fraction of max_batch",
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
_TEACHER_QUEUE = obs_metrics.gauge(
    "edl_teacher_queue_depth", "requests waiting for the device thread")


class _ItemFuture(object):
    """Rendezvous between a handler thread and the device thread."""

    __slots__ = ("_event", "_value", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error = None

    def set(self, value=None, error=None):
        self._value, self._error = value, error
        self._event.set()

    def result(self, timeout):
        if not self._event.wait(timeout):
            raise errors.RpcError("device thread never served the batch")
        if self._error is not None:
            raise self._error
        return self._value


class _BatchItem(object):
    __slots__ = ("feed", "n", "future", "admitted_at", "deadline_ms")

    def __init__(self, feed, n, admitted_at=None, deadline_ms=None):
        self.feed = feed
        self.n = n
        self.future = _ItemFuture()
        self.admitted_at = admitted_at
        self.deadline_ms = deadline_ms


class TeacherServer(object):
    """Wrap ``predict_fn(feed: dict[str, np.ndarray]) -> dict`` behind RPC.

    Contract: ``predict_fn`` must treat the feed arrays as READ-ONLY
    (they may be zero-copy views into the decoded request, or — under
    adaptive batching — slices of a reused staging buffer that is only
    valid for the duration of the call); copy first to keep or mutate.

    ``feed_specs``/``fetch_specs``: {name: (shape_without_batch, dtype_str)}.
    ``max_batch``: server-side compiled batch size; requests are padded up
    and sliced back, so any client batch <= max_batch reuses one program.
    ``adaptive_batch``: coalesce concurrent requests into shared device
    batches on a single device thread (default). False restores the
    serial pad-and-lock path (the bench baseline / escape hatch).
    ``batch_timeout_ms``: how long the device thread may wait for more
    requests when a batch is still short of ``max_batch``. The default
    0 never delays — it coalesces whatever is already queued (pipelined
    clients keep the queue full), so a lone request pays no latency tax.
    """

    def __init__(self, predict_fn, feed_specs, fetch_specs, max_batch=128,
                 host="0.0.0.0", port=0, adaptive_batch=True,
                 batch_timeout_ms=0.0, admission=None,
                 decode_engine=None):
        self._fn = predict_fn
        # optional autoregressive plane (serve/decode_engine.py): adds
        # the lm_generate / lm_submit / lm_poll RPCs, folds engine
        # stats into stats(), and joins the drain protocol
        self._decode = decode_engine
        # admission control (serve/admission.py): None/True builds the
        # default controller (bounded queue only — no rate limit, no
        # projection shed until configured, so plain fleets behave as
        # before); False disables it; an AdmissionController instance
        # is used as-is (the serve-plane configuration surface)
        if admission is False:
            self._admission = None
        elif admission is None or admission is True:
            self._admission = AdmissionController()
        else:
            self._admission = admission
        self._feed_specs = {k: (list(s), d) for k, (s, d)
                            in feed_specs.items()}
        self._fetch_specs = {k: (list(s), d) for k, (s, d)
                             in fetch_specs.items()}
        self._max_batch = max_batch
        self._adaptive = bool(adaptive_batch)
        self._batch_timeout = max(0.0, float(batch_timeout_ms)) / 1000.0
        self._lock = threading.Lock()  # serializes device access (sync path)
        self._queue = queue.Queue()
        self._stop_ev = threading.Event()
        self._device_thread = None
        self._bufs = {}  # group key -> {name: staging array}
        self._stats_lock = threading.Lock()
        self._batches = 0   # device executions
        self._rows = 0      # real (unpadded) rows served
        self._rpc = RpcServer(host=host, port=port)
        self._rpc.register("get_feed_fetch", self.get_feed_fetch)
        self._rpc.register("predict", self._predict_rpc)
        self._rpc.register("stats", self.stats)
        self._rpc.register("set_knobs", self.apply_knobs)
        self._rpc.register("drain", self.drain)
        if self._decode is not None:
            self._rpc.register("lm_generate", self._lm_generate_rpc)
            self._rpc.register("lm_submit", self._lm_submit_rpc)
            self._rpc.register("lm_poll", self._lm_poll_rpc)

    def get_feed_fetch(self):
        features = list(_RPC_FEATURES)
        if self._adaptive:
            features.append("adaptive_batch")
        if self._admission is not None:
            features.append("serve.admission")
        out = {"feed": self._feed_specs, "fetch": self._fetch_specs,
               "max_batch": self._max_batch, "features": features,
               "batch_timeout_ms": self._batch_timeout * 1000.0}
        if self._decode is not None:
            features.append("decode.engine")
            out.update(self.decode_capacities())
        return out

    def decode_capacities(self):
        """Phase-disaggregated capacity weights for the balance table
        (distill/balance.py): ``capacity_prefill`` — how many one-shot
        forwards this server absorbs per scheduling quantum (the batch
        plane, same meaning as ``capacity``) — and ``capacity_decode`` —
        resident-sequence capacity, bounded by KV slots. Pass through
        ``TeacherRegister(info=...)`` so prefill-heavy and decode-heavy
        clients hash against the capacity that actually limits them.

        ``capacity_prefill`` is REUSE-ADJUSTED: a server whose prefix
        cache absorbs fraction f of prompt tokens does only (1-f) of
        the prefill work per nominal request, so it advertises
        1/(1-f) x the raw capacity (capped at 10x — a pathological
        reuse_frac must not zero out the denominator)."""
        if self._decode is None:
            return {}
        prefill = float(self._max_batch)
        try:
            pfx = self._decode.stats().get("decode_prefix") or {}
            if pfx.get("enabled"):
                reuse = min(0.9, max(0.0,
                                     float(pfx.get("reuse_frac") or 0.0)))
                prefill /= (1.0 - reuse)
        except Exception:  # noqa: BLE001 — capacity ad stays best-effort
            pass
        return {"capacity_prefill": prefill,
                "capacity_decode": float(self._decode.slots)}

    # -- the autoregressive plane (serve/decode_engine.py) -----------------

    def _lm_generate_rpc(self, prompt, max_new_tokens, deadline_ms=None):
        """Blocking generate: admit (or typed OverloadedError), decode
        to completion, return the report (tokens include the prompt).
        Ships on the pipelined plane — call_async keeps many sequences
        in flight per connection while each handler thread parks on its
        sequence future."""
        report = self._decode.generate(prompt, max_new_tokens,
                                       deadline_ms=deadline_ms,
                                       timeout=600.0)
        return report

    def _lm_submit_rpc(self, prompt, max_new_tokens, deadline_ms=None):
        h = self._decode.submit(prompt, max_new_tokens,
                                deadline_ms=deadline_ms)
        return {"seq": h.seq_id}

    def _lm_poll_rpc(self, seq, start=0):
        """Token streaming: tokens generated since ``start`` + done flag
        (raises the sequence's typed error once failed)."""
        tokens, done = self._decode.handle(seq).tokens_from(start)
        return {"tokens": tokens, "done": done}

    def apply_knobs(self, knobs):
        """Runtime tuning surface (``set_knobs`` RPC — the same contract
        as the reader's: apply known knobs, ignore unknown ones, return
        what was applied). ``batch_timeout_ms`` (clamped >= 0, <= 1000)
        retunes the device thread's coalescing wait on the fly; the
        thread reads it per batch, so the new value takes effect on the
        next coalescing round."""
        if not isinstance(knobs, dict):
            return {}
        applied = {}
        if "batch_timeout_ms" in knobs:
            try:
                ms = max(0.0, min(1000.0,
                                  float(knobs["batch_timeout_ms"])))
            except (TypeError, ValueError):
                ms = None
            if ms is not None:
                self._batch_timeout = ms / 1000.0
                applied["batch_timeout_ms"] = ms
        return applied

    def stats(self):
        """Batch-occupancy counters (``occupancy`` is the fraction of
        compiled-batch rows that carried real requests) plus — with
        admission control on — the serving-plane signals the
        ``ServeScaler`` folds: queue depth, pending rows, projected
        queue wait, shed counters, and the draining flag. Served as a
        plain (non-pipelined) RPC the substrate dispatches inline on
        the connection read thread, so this stays answerable while the
        device queue is saturated — observability survives overload."""
        with self._stats_lock:
            batches, rows = self._batches, self._rows
        cap = batches * self._max_batch
        out = {
            "batches": batches, "rows": rows,
            "max_batch": self._max_batch,
            "occupancy": (rows / cap) if cap else 0.0,
            "queue_depth": self._queue.qsize(),
        }
        if self._admission is not None:
            out.update(self._admission.stats())
        if self._decode is not None:
            out.update(self._decode.stats())
        return obs_metrics.mirror_stats("edl_teacher", out)

    def drain(self, deadline_s=30.0):
        """Drain-safe shutdown, step 3 of the decommission protocol
        (serve/drain.py): flip admission to ``draining`` (new predicts
        get a typed OverloadedError the reader requeues elsewhere),
        then wait until the device queue and every admitted row have
        resolved. Returns a report; ``drained: False`` means in-flight
        work outlived ``deadline_s`` — the caller decides whether to
        stop anyway (the device loop's shutdown drain still resolves
        every queued future, so nothing is ever silently lost)."""
        if faults.PLANE is not None:
            faults.PLANE.fire("serve.drain", endpoint=self.endpoint,
                              pending=self._queue.qsize())
        if self._admission is not None:
            self._admission.set_draining(True)
        if self._decode is not None:
            # flip the decode front door too, then let BOTH planes
            # finish their in-flight work: resident sequences decode to
            # completion, waiting ones still get slots — zero stranded
            self._decode.admission.set_draining(True)
        deadline = Deadline(deadline_s if deadline_s else 30.0)
        served_before = self._rows
        while not self._drained():
            if not deadline.sleep(0.02):
                break
        with self._stats_lock:
            served = self._rows - served_before
        return {"drained": self._drained(),
                "endpoint": self.endpoint,
                "queue_depth": self._queue.qsize(),
                "pending_rows": (0 if self._admission is None
                                 else self._admission.stats()
                                 ["pending_rows"]),
                "served_during_drain": served}

    def _drained(self):
        if self._adaptive and self._queue.qsize() > 0:
            return False
        if self._decode is not None:
            st = self._decode.stats()
            if st["decode_waiting"] or st["decode_active"]:
                return False
        return self._admission is None or self._admission.idle()

    def _validate(self, feed):
        """Reject malformed feeds with a typed FeedSpecError naming the
        offending spec and shape. FeedSpecError subclasses
        DataAccessError, so the reader surfaces it to the consumer in
        order (poisoned task, never retried) — retrying a permanently
        bad feed against other teachers would ping-pong it forever."""
        missing = set(self._feed_specs) - set(feed)
        if missing:
            name = sorted(missing)[0]
            raise errors.FeedSpecError(
                "missing feeds: %s" % sorted(missing), spec=name,
                shape=tuple(self._feed_specs[name][0]))
        n, first = None, None
        for name, arr in feed.items():
            if n is None:
                n, first = len(arr), name
            elif len(arr) != n:
                raise errors.FeedSpecError(
                    "feed batch mismatch: %s has %d rows, %s has %d"
                    % (first, n, name, len(arr)), spec=name,
                    shape=tuple(np.asarray(arr).shape))
        if n == 0:
            raise errors.FeedSpecError("empty batch", spec=first,
                                       shape=(0,))
        if n > self._max_batch:
            raise errors.FeedSpecError(
                "batch %d exceeds max_batch %d" % (n, self._max_batch),
                spec=first, shape=tuple(np.asarray(feed[first]).shape))
        return n

    def _predict_rpc(self, feed_encoded, deadline_ms=None):
        # v2 tensor frames deliver feeds as owned arrays recv'd
        # straight off the socket (framing.py MAGIC_V2); decode_tree
        # is then a no-op but keeps pre-v2 senders (tagged-dict
        # payloads) working. Contract stays uniform: treat feeds as
        # immutable — copy first if an implementation must mutate.
        feed = nd.decode_tree(feed_encoded, copy=False)
        feed = {k: np.asarray(v) for k, v in feed.items()}
        n = self._validate(feed)
        # the admission decision (serve/admission.py): shed NOW with a
        # typed OverloadedError instead of queueing work the SLO has
        # already lost; ``deadline_ms`` is the caller's per-request
        # budget — the device loop sheds dead-on-arrival items
        admitted_at = None
        if self._admission is not None:
            admitted_at = self._admission.admit(n)
        if not self._adaptive:
            t0 = time.monotonic()
            try:
                return self._predict_serial(feed, n)
            finally:
                if self._admission is not None:
                    self._admission.release(
                        n, service_s=time.monotonic() - t0)
        item = _BatchItem(feed, n, admitted_at=admitted_at,
                          deadline_ms=deadline_ms)
        self._queue.put(item)
        _TEACHER_QUEUE.set(self._queue.qsize())
        # generous rendezvous bound: the device thread always resolves
        # every item it dequeues (success, error, or shutdown drain)
        return item.future.result(timeout=600.0)

    def _predict_serial(self, feed, n):
        """The pre-batching path: pad this request alone to max_batch
        behind the device lock. Kept as the bench baseline and the
        ``adaptive_batch=False`` escape hatch."""
        padded = {}
        for name, arr in feed.items():
            if n < self._max_batch:
                pad = np.zeros((self._max_batch - n,) + arr.shape[1:],
                               arr.dtype)
                arr = np.concatenate([arr, pad], axis=0)
            padded[name] = arr
        with self._lock:
            out = self._fn(padded)
            with self._stats_lock:
                self._batches += 1
                self._rows += n
        _DEVICE_BATCHES.inc()
        _DEVICE_ROWS.inc(n)
        _BATCH_FILL.observe(n / float(self._max_batch))
        # raw arrays: the v2 tensor frame ships them out-of-band with
        # no tobytes()/msgpack-bin copies (framing.py MAGIC_V2)
        return {k: np.asarray(v)[:n] for k, v in out.items()}

    # -- the device thread -------------------------------------------------

    @staticmethod
    def _group_key(feed):
        """Requests may only share a device batch when their feeds
        agree on everything but the row count."""
        return tuple(sorted((name, arr.shape[1:], arr.dtype.str)
                            for name, arr in feed.items()))

    def _buffers(self, key):
        bufs = self._bufs.get(key)
        if bufs is None:
            if len(self._bufs) >= 8:  # bound staging memory under churn
                self._bufs.pop(next(iter(self._bufs)))
            bufs = self._bufs[key] = {
                name: np.zeros((self._max_batch,) + tuple(trail),
                               np.dtype(dt))
                for name, trail, dt in key}
        return bufs

    def _dead_on_arrival(self, item):
        """Shed a queued item whose per-request deadline elapsed while
        it waited — running it would burn device time on a reply the
        caller has already abandoned."""
        if (self._admission is None or item.admitted_at is None
                or not self._admission.expired(item.admitted_at,
                                               item.deadline_ms)):
            return False
        item.future.set(error=self._admission.shed_expired(item.n))
        return True

    def _device_loop(self):
        carry = None
        while not self._stop_ev.is_set():
            if carry is not None:
                item, carry = carry, None
            else:
                try:
                    item = self._queue.get(timeout=0.2)
                except queue.Empty:
                    continue
            if self._dead_on_arrival(item):
                continue
            key = self._group_key(item.feed)
            group, rows = [item], item.n
            deadline = time.monotonic() + self._batch_timeout
            while rows < self._max_batch:
                # timeout 0 = drain only what is already queued; a
                # positive budget waits for stragglers but a full
                # batch always flushes immediately
                try:
                    nxt = self._queue.get(
                        timeout=max(0.0, deadline - time.monotonic()))
                except queue.Empty:
                    break
                if self._dead_on_arrival(nxt):
                    continue
                if (self._group_key(nxt.feed) != key
                        or rows + nxt.n > self._max_batch):
                    carry = nxt  # incompatible: heads the next batch
                    break
                group.append(nxt)
                rows += nxt.n
            self._run_group(key, group, rows)
        # shutdown drain: never leave a handler thread parked forever
        pending = [carry] if carry is not None else []
        while True:
            try:
                pending.append(self._queue.get_nowait())
            except queue.Empty:
                break
        for item in pending:
            item.future.set(error=errors.StopError("teacher stopping"))
            if self._admission is not None and item.admitted_at \
                    is not None:
                self._admission.release(item.n)

    def _run_group(self, key, group, rows):
        t0 = time.monotonic()
        try:
            if len(group) == 1 and rows == self._max_batch:
                feed = group[0].feed  # already full: run it in place
            else:
                bufs = self._buffers(key)
                lo = 0
                for item in group:
                    for name, arr in item.feed.items():
                        bufs[name][lo:lo + item.n] = arr
                    lo += item.n
                if rows < self._max_batch:
                    # zero the pad tail: stale rows from the previous
                    # batch must not leak into this execution (keeps
                    # outputs bit-identical with the serial zero-pad)
                    for name in bufs:
                        bufs[name][rows:] = 0
                feed = bufs
            out = self._fn(feed)
            outs = {}
            for k, v in out.items():
                v = np.asarray(v)
                if any(np.may_share_memory(v, b) for b in feed.values()):
                    # a passthrough fn returned (a view of) the staging
                    # buffer; the next batch would overwrite it while
                    # responses are still being serialized
                    v = v.copy()
                outs[k] = v
            with self._stats_lock:
                self._batches += 1
                self._rows += rows
            _DEVICE_BATCHES.inc()
            _DEVICE_ROWS.inc(rows)
            _BATCH_FILL.observe(rows / float(self._max_batch))
        except Exception as e:  # noqa: BLE001 — fail every waiter, keep serving
            for item in group:
                item.future.set(error=e)
            if self._admission is not None:
                self._admission.release(rows)
            return
        if self._admission is not None:
            # the device wall time of this batch feeds the queue-wait
            # projection (the EWMA admission sheds against)
            self._admission.release(rows,
                                    service_s=time.monotonic() - t0)
        lo = 0
        for item in group:
            item.future.set(value={k: v[lo:lo + item.n]
                                   for k, v in outs.items()})
            lo += item.n

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        if self._adaptive and self._device_thread is None:
            self._stop_ev.clear()
            self._device_thread = threading.Thread(
                target=self._device_loop, daemon=True,
                name="teacher-device")
            self._device_thread.start()
        if self._decode is not None and not self._decode.running:
            self._decode.start()
        self._rpc.start()
        logger.info("teacher serving on %s (max_batch=%d, adaptive=%s)",
                    self._rpc.endpoint, self._max_batch, self._adaptive)
        return self

    @property
    def endpoint(self):
        return self._rpc.endpoint

    @property
    def port(self):
        return self._rpc.port

    def stop(self):
        self._rpc.stop()
        if self._device_thread is not None:
            self._stop_ev.set()
            self._device_thread.join(timeout=5)
            self._device_thread = None
        if self._decode is not None:
            self._decode.stop()


def nop_teacher(fetch_specs, max_batch=128, host="0.0.0.0", port=0,
                feed_specs=None, **kwargs):
    """A fake teacher returning zeros — the test backend (reference parity:
    _TestNopPaddlePredictServer, distill_worker.py:324-333)."""
    feed_specs = feed_specs or {"ins": ([1], "<f4")}

    def predict(feed):
        n = max_batch
        return {name: np.zeros((n,) + tuple(shape), np.dtype(dtype))
                for name, (shape, dtype) in fetch_specs.items()}

    return TeacherServer(predict, feed_specs, fetch_specs,
                         max_batch=max_batch, host=host, port=port,
                         **kwargs)


def resnet_teacher(depth=50, num_classes=1000, image_size=224,
                   max_batch=64, host="0.0.0.0", port=0, feed_bf16=True,
                   groups=1, base_width=64, vd=True):
    """A real TPU teacher: ResNet/ResNeXt(depth) logits + softmax
    (groups=32, base_width=16, vd=False = the reference's distill
    teacher ResNeXt101_32x16d_wsl architecture — BASELINE.md).

    feed_bf16 halves the host→device feed bytes (the dominant serving cost
    on transfer-bound links) at negligible accuracy cost for soft labels.
    """
    import jax
    import jax.numpy as jnp
    import ml_dtypes

    from edl_tpu.models import resnet

    model = resnet.ResNet(depth=depth, num_classes=num_classes, vd=vd,
                          groups=groups, base_width=base_width,
                          dtype=jnp.bfloat16)
    dummy = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), dummy, train=False)

    @jax.jit
    def infer(image):
        logits = model.apply(variables, image, train=False)
        return logits, jax.nn.softmax(logits)

    def predict(feed):
        image = feed["image"]
        if feed_bf16:
            image = image.astype(ml_dtypes.bfloat16)
        logits, probs = infer(image)
        return {"logits": np.asarray(logits), "probs": np.asarray(probs)}

    return TeacherServer(
        predict,
        feed_specs={"image": ([image_size, image_size, 3], "<f4")},
        fetch_specs={"logits": ([num_classes], "<f4"),
                     "probs": ([num_classes], "<f4")},
        max_batch=max_batch, host=host, port=port)


def gpt_teacher(num_layers=2, d_model=64, num_heads=4, mlp_dim=128,
                vocab_size=256, seq_len=32, max_batch=64, host="0.0.0.0",
                port=0, params=None, quantize=None, **kwargs):
    """A causal-LM teacher: per-position next-token logits + probs —
    sequence-level knowledge distillation (the LM counterpart of the
    reference's ERNIE→BOW soft-label serving). Fixed ``seq_len`` so XLA
    compiles one program; clients pad shorter sequences.

    ``params`` (a trained Gpt param tree) makes it a real teacher; the
    default random init serves as a shape-true stand-in for tests.

    ``quantize``: None | "int8" | "bf16" — serve from absmax
    per-channel int8 (or bf16) kernels (ops/quant.py); the dequant runs
    inside the jitted forward so the int8 arrays are what sit in HBM.
    Logits parity vs f32 is gated in tier-1
    (tests/test_decode_engine.py)."""
    import jax
    import jax.numpy as jnp

    from edl_tpu.models import gpt
    from edl_tpu.ops import quant

    model = gpt.Gpt(num_layers=num_layers, d_model=d_model,
                    num_heads=num_heads, mlp_dim=mlp_dim,
                    vocab_size=vocab_size, max_len=max(seq_len, 16),
                    dtype=jnp.bfloat16)
    if params is None:
        dummy = jnp.zeros((1, seq_len), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), dummy)["params"]
    if quantize is not None:
        params = quant.quantize_tree(params, quantize)

    @jax.jit
    def infer(qparams, ids):
        p = quant.dequantize_tree(qparams)
        logits = model.apply({"params": p}, ids)
        return logits, jax.nn.softmax(logits)

    def predict(feed):
        ids = np.asarray(feed["input_ids"], np.int32)
        logits, probs = infer(params, ids)
        return {"logits": np.asarray(logits), "probs": np.asarray(probs)}

    return TeacherServer(
        predict,
        feed_specs={"input_ids": ([seq_len], "<i4")},
        fetch_specs={"logits": ([seq_len, vocab_size], "<f4"),
                     "probs": ([seq_len, vocab_size], "<f4")},
        max_batch=max_batch, host=host, port=port, **kwargs)


def lm_teacher(num_layers=2, d_model=64, num_heads=4, mlp_dim=128,
               vocab_size=256, max_len=128, slots=8, max_batch=16,
               host="0.0.0.0", port=0, params=None, quantize=None,
               decode_admission=None, **kwargs):
    """An autoregressive LM teacher: the one-shot per-position logits
    plane of :func:`gpt_teacher` PLUS the continuous-batching decode
    engine (serve/decode_engine.py) behind ``lm_generate`` /
    ``lm_submit`` / ``lm_poll``. Prefill-heavy clients use ``predict``;
    decode-heavy ones hold KV slots — the two capacities are advertised
    separately (``decode_capacities``) so the balance table can
    disaggregate the phases. ``quantize`` (None|"int8"|"bf16") applies
    to BOTH planes from one shared quantized param tree."""
    import jax
    import jax.numpy as jnp

    from edl_tpu.models import gpt
    from edl_tpu.ops import quant
    from edl_tpu.serve.decode_engine import DecodeEngine

    # decode path runs f32: greedy sampling is gated token-identical
    # against models.gpt.generate, which bf16 activations would break
    model = gpt.Gpt(num_layers=num_layers, d_model=d_model,
                    num_heads=num_heads, mlp_dim=mlp_dim,
                    vocab_size=vocab_size, max_len=max_len,
                    dtype=jnp.float32)
    if params is None:
        dummy = jnp.zeros((1, 8), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), dummy)["params"]
    if quantize is not None:
        params = quant.quantize_tree(params, quantize)
    engine = DecodeEngine(model, params, slots=slots,
                          admission=decode_admission)

    @jax.jit
    def infer(qparams, ids):
        p = quant.dequantize_tree(qparams)
        logits = model.apply({"params": p}, ids)
        return logits, jax.nn.softmax(logits)

    def predict(feed):
        ids = np.asarray(feed["input_ids"], np.int32)
        logits, probs = infer(params, ids)
        return {"logits": np.asarray(logits), "probs": np.asarray(probs)}

    seq_len = max_len
    return TeacherServer(
        predict,
        feed_specs={"input_ids": ([seq_len], "<i4")},
        fetch_specs={"logits": ([seq_len, vocab_size], "<f4"),
                     "probs": ([seq_len, vocab_size], "<f4")},
        max_batch=max_batch, host=host, port=port,
        decode_engine=engine, **kwargs)


def main():
    p = argparse.ArgumentParser("edl_tpu teacher server")
    p.add_argument("--model", default="nop",
                   choices=["nop", "resnet", "resnext", "gpt"])
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--depth", type=int, default=None,
                   help="resnet depth (default 50; resnext default 101)")
    p.add_argument("--num_classes", type=int, default=1000)
    p.add_argument("--image_size", type=int, default=224)
    p.add_argument("--max_batch", type=int, default=64)
    p.add_argument("--vocab_size", type=int, default=256)
    p.add_argument("--seq_len", type=int, default=32)
    args = p.parse_args()
    if args.model == "resnet":
        server = resnet_teacher(args.depth or 50, args.num_classes,
                                args.image_size, args.max_batch,
                                port=args.port)
    elif args.model == "resnext":
        # the reference's distill teacher config: ResNeXt101_32x16d
        server = resnet_teacher(args.depth or 101, args.num_classes,
                                args.image_size, args.max_batch,
                                port=args.port, groups=32, base_width=16,
                                vd=False)
    elif args.model == "gpt":
        server = gpt_teacher(vocab_size=args.vocab_size,
                             seq_len=args.seq_len,
                             max_batch=args.max_batch, port=args.port)
    else:
        # image-shaped feeds so the NOP backend is interchangeable with
        # the resnet one (same student driver, model cost zeroed out)
        server = nop_teacher(
            {"logits": ([args.num_classes], "<f4"),
             "probs": ([args.num_classes], "<f4")},
            feed_specs={"image": ([args.image_size, args.image_size, 3],
                                  "<f4")},
            max_batch=args.max_batch, port=args.port)
    server.start()
    print("TEACHER_ENDPOINT=%s" % server.endpoint, flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    server.stop()


if __name__ == "__main__":
    main()
