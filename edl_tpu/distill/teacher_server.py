"""TPU teacher inference server — the in-tree replacement for the Paddle
Serving GPU servers the reference's distill plane called into
(SURVEY.md §2.6; client usage distill_worker.py:197-321).

Serves a jitted model function over the framed-RPC substrate:
- ``get_feed_fetch()`` — feed/fetch name+shape introspection (the contract
  the reference client discovered from serving conf files);
- ``predict(feed)`` — feed dict of ndarrays → fetch dict of ndarrays.
  Inputs are padded to a fixed batch size so XLA compiles once.

A teacher registers itself into the coordination store via
edl_tpu.distill.registry and is matched to students by the discovery/
balance layer.
"""

import argparse
import signal
import threading

import numpy as np

from edl_tpu.rpc import ndarray as nd
from edl_tpu.rpc.server import RpcServer
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger


class TeacherServer(object):
    """Wrap ``predict_fn(feed: dict[str, np.ndarray]) -> dict`` behind RPC.

    Contract: ``predict_fn`` must treat the feed arrays as READ-ONLY
    (they may be zero-copy views into the decoded request); copy first
    to mutate in place.

    ``feed_specs``/``fetch_specs``: {name: (shape_without_batch, dtype_str)}.
    ``max_batch``: server-side compiled batch size; requests are padded up
    and sliced back, so any client batch <= max_batch reuses one program.
    """

    def __init__(self, predict_fn, feed_specs, fetch_specs, max_batch=128,
                 host="0.0.0.0", port=0):
        self._fn = predict_fn
        self._feed_specs = {k: (list(s), d) for k, (s, d)
                            in feed_specs.items()}
        self._fetch_specs = {k: (list(s), d) for k, (s, d)
                             in fetch_specs.items()}
        self._max_batch = max_batch
        self._lock = threading.Lock()  # serialize device access
        self._rpc = RpcServer(host=host, port=port)
        self._rpc.register("get_feed_fetch", self.get_feed_fetch)
        self._rpc.register("predict", self._predict_rpc)

    def get_feed_fetch(self):
        return {"feed": self._feed_specs, "fetch": self._fetch_specs,
                "max_batch": self._max_batch}

    def _predict_rpc(self, feed_encoded):
        # v2 tensor frames deliver feeds as owned arrays recv'd
        # straight off the socket (framing.py MAGIC_V2); decode_tree
        # is then a no-op but keeps pre-v2 senders (tagged-dict
        # payloads) working. Contract stays uniform: treat feeds as
        # immutable — copy first if an implementation must mutate.
        feed = nd.decode_tree(feed_encoded, copy=False)
        missing = set(self._feed_specs) - set(feed)
        if missing:
            raise errors.DataAccessError("missing feeds: %s"
                                         % sorted(missing))
        n = None
        for name, arr in feed.items():
            if n is None:
                n = len(arr)
            elif len(arr) != n:
                raise errors.DataAccessError("feed batch mismatch")
        if n == 0:
            raise errors.DataAccessError("empty batch")
        if n > self._max_batch:
            raise errors.DataAccessError(
                "batch %d exceeds max_batch %d" % (n, self._max_batch))
        padded = {}
        for name, arr in feed.items():
            arr = np.asarray(arr)
            if n < self._max_batch:
                pad = np.zeros((self._max_batch - n,) + arr.shape[1:],
                               arr.dtype)
                arr = np.concatenate([arr, pad], axis=0)
            padded[name] = arr
        with self._lock:
            out = self._fn(padded)
        # raw arrays: the v2 tensor frame ships them out-of-band with
        # no tobytes()/msgpack-bin copies (framing.py MAGIC_V2)
        return {k: np.asarray(v)[:n] for k, v in out.items()}

    def start(self):
        self._rpc.start()
        logger.info("teacher serving on %s (max_batch=%d)",
                    self._rpc.endpoint, self._max_batch)
        return self

    @property
    def endpoint(self):
        return self._rpc.endpoint

    @property
    def port(self):
        return self._rpc.port

    def stop(self):
        self._rpc.stop()


def nop_teacher(fetch_specs, max_batch=128, host="0.0.0.0", port=0,
                feed_specs=None):
    """A fake teacher returning zeros — the test backend (reference parity:
    _TestNopPaddlePredictServer, distill_worker.py:324-333)."""
    feed_specs = feed_specs or {"ins": ([1], "<f4")}

    def predict(feed):
        n = max_batch
        return {name: np.zeros((n,) + tuple(shape), np.dtype(dtype))
                for name, (shape, dtype) in fetch_specs.items()}

    return TeacherServer(predict, feed_specs, fetch_specs,
                         max_batch=max_batch, host=host, port=port)


def resnet_teacher(depth=50, num_classes=1000, image_size=224,
                   max_batch=64, host="0.0.0.0", port=0, feed_bf16=True,
                   groups=1, base_width=64, vd=True):
    """A real TPU teacher: ResNet/ResNeXt(depth) logits + softmax
    (groups=32, base_width=16, vd=False = the reference's distill
    teacher ResNeXt101_32x16d_wsl architecture — BASELINE.md).

    feed_bf16 halves the host→device feed bytes (the dominant serving cost
    on transfer-bound links) at negligible accuracy cost for soft labels.
    """
    import jax
    import jax.numpy as jnp
    import ml_dtypes

    from edl_tpu.models import resnet

    model = resnet.ResNet(depth=depth, num_classes=num_classes, vd=vd,
                          groups=groups, base_width=base_width,
                          dtype=jnp.bfloat16)
    dummy = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), dummy, train=False)

    @jax.jit
    def infer(image):
        logits = model.apply(variables, image, train=False)
        return logits, jax.nn.softmax(logits)

    def predict(feed):
        image = feed["image"]
        if feed_bf16:
            image = image.astype(ml_dtypes.bfloat16)
        logits, probs = infer(image)
        return {"logits": np.asarray(logits), "probs": np.asarray(probs)}

    return TeacherServer(
        predict,
        feed_specs={"image": ([image_size, image_size, 3], "<f4")},
        fetch_specs={"logits": ([num_classes], "<f4"),
                     "probs": ([num_classes], "<f4")},
        max_batch=max_batch, host=host, port=port)


def gpt_teacher(num_layers=2, d_model=64, num_heads=4, mlp_dim=128,
                vocab_size=256, seq_len=32, max_batch=64, host="0.0.0.0",
                port=0, params=None):
    """A causal-LM teacher: per-position next-token logits + probs —
    sequence-level knowledge distillation (the LM counterpart of the
    reference's ERNIE→BOW soft-label serving). Fixed ``seq_len`` so XLA
    compiles one program; clients pad shorter sequences.

    ``params`` (a trained Gpt param tree) makes it a real teacher; the
    default random init serves as a shape-true stand-in for tests."""
    import jax
    import jax.numpy as jnp

    from edl_tpu.models import gpt

    model = gpt.Gpt(num_layers=num_layers, d_model=d_model,
                    num_heads=num_heads, mlp_dim=mlp_dim,
                    vocab_size=vocab_size, max_len=max(seq_len, 16),
                    dtype=jnp.bfloat16)
    if params is None:
        dummy = jnp.zeros((1, seq_len), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), dummy)["params"]

    @jax.jit
    def infer(ids):
        logits = model.apply({"params": params}, ids)
        return logits, jax.nn.softmax(logits)

    def predict(feed):
        ids = np.asarray(feed["input_ids"], np.int32)
        logits, probs = infer(ids)
        return {"logits": np.asarray(logits), "probs": np.asarray(probs)}

    return TeacherServer(
        predict,
        feed_specs={"input_ids": ([seq_len], "<i4")},
        fetch_specs={"logits": ([seq_len, vocab_size], "<f4"),
                     "probs": ([seq_len, vocab_size], "<f4")},
        max_batch=max_batch, host=host, port=port)


def main():
    p = argparse.ArgumentParser("edl_tpu teacher server")
    p.add_argument("--model", default="nop",
                   choices=["nop", "resnet", "resnext", "gpt"])
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--depth", type=int, default=None,
                   help="resnet depth (default 50; resnext default 101)")
    p.add_argument("--num_classes", type=int, default=1000)
    p.add_argument("--image_size", type=int, default=224)
    p.add_argument("--max_batch", type=int, default=64)
    p.add_argument("--vocab_size", type=int, default=256)
    p.add_argument("--seq_len", type=int, default=32)
    args = p.parse_args()
    if args.model == "resnet":
        server = resnet_teacher(args.depth or 50, args.num_classes,
                                args.image_size, args.max_batch,
                                port=args.port)
    elif args.model == "resnext":
        # the reference's distill teacher config: ResNeXt101_32x16d
        server = resnet_teacher(args.depth or 101, args.num_classes,
                                args.image_size, args.max_batch,
                                port=args.port, groups=32, base_width=16,
                                vd=False)
    elif args.model == "gpt":
        server = gpt_teacher(vocab_size=args.vocab_size,
                             seq_len=args.seq_len,
                             max_batch=args.max_batch, port=args.port)
    else:
        # image-shaped feeds so the NOP backend is interchangeable with
        # the resnet one (same student driver, model cost zeroed out)
        server = nop_teacher(
            {"logits": ([args.num_classes], "<f4"),
             "probs": ([args.num_classes], "<f4")},
            feed_specs={"image": ([args.image_size, args.image_size, 3],
                                  "<f4")},
            max_batch=args.max_batch, port=args.port)
    server.start()
    print("TEACHER_ENDPOINT=%s" % server.endpoint, flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    server.stop()


if __name__ == "__main__":
    main()
