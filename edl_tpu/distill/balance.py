"""Client/teacher matchmaking: the connection-capped greedy balancer.

Reference parity: edl/distill/balance_table.py Service.rebalance (:139-338)
— invariants preserved:
- per-server connection cap  = ceil-ish (clients + servers - 1) // servers
- per-client server cap      = max(1, servers // clients), bounded by the
  client's require_num
- greedy unlink of over-cap links, then greedy link of under-served clients
  to least-loaded servers; any change bumps the affected client's version so
  its next heartbeat ships the new list.
"""

import json
import threading
import time

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils.logger import logger

_REASSIGNMENTS = obs_metrics.counter(
    "edl_balance_reassignments_total",
    "existing client->server assignments moved by the balancer")

# heartbeats arrive every 2s (discovery_client.py); a client silent for
# 5 intervals is gone — elastic resizes restart trainers with fresh pids,
# so crashed students would otherwise accumulate as ghost clients forever,
# inflating the per-server cap and pinning teachers to dead students
# (reference: balance_table liveness cleanup)
CLIENT_TTL = 10.0


class _Client(object):
    __slots__ = ("id", "require", "servers", "version", "last_seen",
                 "phase")

    def __init__(self, cid, require, now, phase=None):
        self.id = cid
        self.require = max(1, require)
        self.servers = set()
        self.version = 0
        self.last_seen = now
        # serving-phase affinity (None | "prefill" | "decode"): which
        # advertised capacity this client consumes — a one-shot/prefill
        # client scales with batch capacity, a decode client with KV
        # slots (capacity_prefill / capacity_decode in the teacher's
        # registration info, teacher_server.decode_capacities)
        self.phase = phase


class Service(object):
    """One distill service: a set of teacher servers and student clients."""

    def __init__(self, name, client_ttl=CLIENT_TTL, clock=time.monotonic):
        self.name = name
        self._lock = threading.Lock()
        self._servers = {}   # endpoint -> set(client_id)
        self._info = {}      # endpoint -> registration info dict
        self._clients = {}   # client_id -> _Client
        self._client_ttl = client_ttl
        self._clock = clock
        self._rebalances = 0
        self._reassigned = 0
        self._evicted = 0

    def _evict_stale_locked(self):
        """Drop clients whose last heartbeat is older than the TTL, then
        rebalance so their capacity returns to live clients."""
        cutoff = self._clock() - self._client_ttl
        stale = [cid for cid, c in self._clients.items()
                 if c.last_seen < cutoff]
        for cid in stale:
            c = self._clients.pop(cid)
            for ep in c.servers:
                self._servers.get(ep, set()).discard(cid)
            logger.info("balance: evicted stale client %s (service %s)",
                        cid, self.name)
        if stale:
            self._evicted += len(stale)
            self._rebalance()

    # -- membership ------------------------------------------------------------

    @staticmethod
    def _parse_info(value):
        """Registration values arrive as the registry's JSON string
        (or already as a dict from in-process callers). Unparseable
        info degrades to {} — an opaque teacher is weight 1.0."""
        if isinstance(value, dict):
            return value
        if isinstance(value, bytes):
            value = value.decode("utf-8", "replace")
        if isinstance(value, str) and value:
            try:
                out = json.loads(value)
                return out if isinstance(out, dict) else {}
            except ValueError:
                return {}
        return {}

    def set_servers(self, endpoints):
        """``endpoints`` is either an iterable of endpoint strings (all
        weight 1.0) or a dict ``{endpoint: info}`` — the registry's
        registration values, whose ``capacity`` (relative weight) and
        ``draining`` fields make the balancer load-aware: a draining
        teacher's connection cap drops to zero so its clients move off
        before the TTL even lapses."""
        if isinstance(endpoints, dict):
            info = {ep: self._parse_info(v)
                    for ep, v in endpoints.items()}
        else:
            info = {ep: {} for ep in endpoints}
        with self._lock:
            self._evict_stale_locked()
            self._info = info
            endpoints = set(info)
            for ep in list(self._servers):
                if ep not in endpoints:
                    for cid in self._servers.pop(ep):
                        c = self._clients.get(cid)
                        if c is not None:
                            c.servers.discard(ep)
                            c.version += 1
                            self._count_move()
            for ep in endpoints:
                self._servers.setdefault(ep, set())
            self._rebalance()

    def register_client(self, client_id, require_num, phase=None):
        """``phase`` (None | "prefill" | "decode") picks which
        advertised capacity the client weighs against — phase
        disaggregation over one teacher fleet."""
        if phase not in (None, "prefill", "decode"):
            phase = None
        with self._lock:
            self._evict_stale_locked()
            if client_id not in self._clients:
                self._clients[client_id] = _Client(
                    client_id, require_num, self._clock(), phase=phase)
                self._rebalance()
            c = self._clients[client_id]
            c.last_seen = self._clock()
            if c.phase != phase:
                c.phase = phase
                self._rebalance()
            return {"version": c.version, "servers": sorted(c.servers)}

    def unregister_client(self, client_id):
        with self._lock:
            c = self._clients.pop(client_id, None)
            if c is None:
                return False
            for ep in c.servers:
                self._servers.get(ep, set()).discard(client_id)
            self._rebalance()
            return True

    def heartbeat(self, client_id, version):
        """Returns {"version", "servers"} — servers only when the client's
        view is stale (reference: versioned heartbeat, discovery_client)."""
        with self._lock:
            self._evict_stale_locked()
            c = self._clients.get(client_id)
            if c is None:
                return None
            c.last_seen = self._clock()
            if c.version == version:
                return {"version": version}
            return {"version": c.version, "servers": sorted(c.servers)}

    # -- the balancing core (callers hold the lock) -----------------------------

    def _count_move(self):
        self._reassigned += 1
        _REASSIGNMENTS.inc()

    def _weight(self, ep, phase=None):
        """Relative capacity weight from the registration info: a
        draining teacher weighs 0 (its clients move off immediately —
        the load-aware half of the drain protocol), a ``capacity``
        field scales the connection cap, anything else is 1.0.

        With ``phase`` set, ``capacity_prefill`` / ``capacity_decode``
        take precedence over the generic ``capacity`` — a teacher
        without a decode engine advertises no ``capacity_decode`` and
        keeps its generic weight, while one that DOES advertises both,
        so prefill-heavy and decode-heavy clients see the capacity that
        actually limits them. Phase capacities are ABSOLUTE sizes
        (batch rows / KV slots); they are normalized against the fleet
        mean in :meth:`_server_cap`, so a slot-rich teacher takes
        proportionally more decode clients."""
        info = self._info.get(ep) or {}
        if info.get("draining"):
            return 0.0
        key = "capacity_%s" % phase if phase else None
        if key and key in info:
            try:
                return max(0.0, float(info[key]))
            except (TypeError, ValueError):
                return 1.0
        try:
            w = float(info.get("capacity", 1.0))
        except (TypeError, ValueError):
            w = 1.0
        return max(0.0, w)

    def _phase_norm(self, phase):
        """Fleet-mean phase capacity, the denominator that turns the
        absolute per-phase sizes into relative weights (generic
        ``capacity`` is already relative, mean 1.0 by convention)."""
        if not phase:
            return 1.0
        vals = [self._weight(ep, phase) for ep in self._servers]
        vals = [v for v in vals if v > 0.0]
        if not vals:
            return 1.0
        return sum(vals) / len(vals)

    def _server_cap(self, ep, per_server, phase=None):
        w = self._weight(ep, phase)
        if w <= 0.0:
            return 0
        if phase:
            w = w / self._phase_norm(phase)
        if w == 1.0:
            return per_server
        return max(1, int(round(per_server * w)))

    def _caps(self):
        n_servers = sum(1 for ep in self._servers
                        if self._weight(ep) > 0.0)
        n_clients = len(self._clients)
        if n_servers == 0 or n_clients == 0:
            return 0, 0
        per_server = (n_clients + n_servers - 1) // n_servers
        per_client = max(1, n_servers // n_clients)
        return per_server, per_client

    def _rebalance(self):
        """Churn-minimal greedy rebalance: existing links are touched
        ONLY when a cap forces it (server over its weighted cap, client
        over its allowance, draining server emptying), so an unchanged
        server set moves nothing and a single join/leave moves ~1/N of
        the assignments (regression-tested). Every moved link of a
        pre-existing client counts in ``edl_balance_reassignments_total``
        — assignment churn is an operator-visible cost."""
        self._rebalances += 1
        per_server, per_client = self._caps()
        if per_server == 0:
            for c in self._clients.values():
                if c.servers:
                    c.servers.clear()
                    c.version += 1
            for ep in self._servers:
                self._servers[ep].clear()
            return

        # 1. unlink: servers over their weighted cap / clients over
        #    their allowance — the only step that moves existing links
        for ep, linked in self._servers.items():
            cap = self._server_cap(ep, per_server)
            while len(linked) > cap:
                cid = max(linked,
                          key=lambda i: len(self._clients[i].servers))
                linked.discard(cid)
                self._clients[cid].servers.discard(ep)
                self._clients[cid].version += 1
                self._count_move()
        for c in self._clients.values():
            allowance = min(per_client, c.require)
            while len(c.servers) > allowance:
                ep = max(c.servers, key=lambda e: len(self._servers[e]))
                c.servers.discard(ep)
                self._servers[ep].discard(c.id)
                c.version += 1
                self._count_move()

        # 2. link: starved clients to least-loaded servers with
        #    weighted headroom — against the client's PHASE capacity,
        #    so decode clients skip slot-less teachers and pile onto
        #    slot-rich ones while prefill clients spread by batch size
        for c in self._clients.values():
            allowance = min(per_client, c.require)
            while len(c.servers) < allowance:
                candidates = [
                    ep for ep, linked in self._servers.items()
                    if ep not in c.servers
                    and len(linked) < self._server_cap(ep, per_server,
                                                       c.phase)]
                if not candidates:
                    break
                ep = min(candidates, key=lambda e: len(self._servers[e]))
                c.servers.add(ep)
                self._servers[ep].add(c.id)
                c.version += 1
        # 3. every client gets at least one server if any can take it
        #    (draining/zero-weight servers are a last resort only)
        for c in self._clients.values():
            if not c.servers and self._servers:
                live = [ep for ep in self._servers
                        if self._weight(ep, c.phase) > 0.0]
                ep = min(live or self._servers,
                         key=lambda e: len(self._servers[e]))
                c.servers.add(ep)
                self._servers[ep].add(c.id)
                c.version += 1

    def stats(self):
        with self._lock:
            loads = [len(v) for v in self._servers.values()]
            _, per_client = self._caps()
            sats = [len(c.servers) / max(1, min(per_client, c.require))
                    for c in self._clients.values()]
            return {
                "servers": {ep: len(v) for ep, v in self._servers.items()},
                "clients": {c.id: sorted(c.servers)
                            for c in self._clients.values()},
                # fairness: how evenly teachers are loaded and how close
                # each student is to its entitled teacher count
                "fairness": {
                    "load_min": min(loads) if loads else 0,
                    "load_max": max(loads) if loads else 0,
                    "load_imbalance": (max(loads) - min(loads)
                                       if loads else 0),
                    "satisfaction": (round(sum(sats) / len(sats), 4)
                                     if sats else 1.0),
                    "rebalances": self._rebalances,
                    "reassignments": self._reassigned,
                    "evicted": self._evicted,
                },
            }


class BalanceTable(object):
    """All services known to one discovery server (reference
    balance_table.py BalanceTable :359-689; consistent-hash sharding across
    discovery servers lives in discovery_server)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._services = {}

    def service(self, name):
        with self._lock:
            svc = self._services.get(name)
            if svc is None:
                svc = self._services[name] = Service(name)
                logger.info("balance table: new service %s", name)
            return svc

    def names(self):
        with self._lock:
            return sorted(self._services)
