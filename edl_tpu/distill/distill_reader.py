"""DistillReader: wrap a student's data generator so every batch is
augmented with teacher-model predictions fetched from an elastic fleet of
TPU inference servers.

Reference parity: edl/distill/distill_reader.py + distill_worker.py —
the same observable protocol, re-implemented with threads instead of forked
processes (the heavy lifting is remote TPU inference + msgpack IO, which
threads overlap fine):

- user data is framed into ordered tasks; a bounded semaphore provides
  ordering back-pressure (reference task_semaphore, distill_worker.py:599);
- one predict worker per teacher connection; the manage loop diffs the
  discovered teacher set, starts workers for new teachers and stops workers
  for dropped ones (reference predict_manage_worker :58-171);
- a failed task is re-queued and its worker retires the connection; the
  epoch completes only when every fed task has a result — the accounting
  the reference implemented with poison pills + feed/predict counters
  (:435-506) is expressed here with per-epoch fed/done counters;
- results are re-ordered by task id so the student sees its batches in the
  original order (reference fetch_out :720-769).

Pipelining: each worker keeps up to ``pipeline_depth`` predicts in
flight on its connection via ``RpcClient.call_async`` (and an oversized
batch's max_batch chunks ride the same pipeline), so the wire streams
the next feeds while the teacher's device computes the current batch —
the overlap the zero-copy v2 tensor frames were built for. Depth falls
back to 1 against a teacher that does not advertise ``rpc.pipeline`` in
``get_feed_fetch``. On a connection failure every in-flight task is
requeued (the per-endpoint in-flight registry holds the full set, not
just one task), so the delivery guarantee is unchanged.
"""

import collections
import queue
import threading
import time

import numpy as np

from edl_tpu.distill.discovery_client import DiscoveryClient, FixedDiscover
from edl_tpu.robustness.policy import CircuitBreaker
from edl_tpu.rpc import ndarray as nd
from edl_tpu.rpc.client import RpcClient
from edl_tpu.rpc.pool import ClientPool
from edl_tpu.utils import errors, timeline
from edl_tpu.utils.logger import logger

#: sentinel payload marking a result slot that carries a permanent
#: per-task error instead of predictions (raised to the consumer in
#: order, so a poisoned batch cannot requeue forever)
_TASK_ERROR = object()


class _PredictFuture(object):
    """All chunk replies of one logical predict; ``result()`` joins."""

    __slots__ = ("_futs",)

    def __init__(self, futs):
        self._futs = futs

    def result(self):
        # raw arrays rode the v2 tensor frame (out-of-band zero-copy
        # segments); decode_tree is a no-op on the already-decoded
        # reply but keeps pre-v2 peers working
        outs = [nd.decode_tree(f.result()) for f in self._futs]
        if len(outs) == 1:
            return outs[0]
        return {k: np.concatenate([o[k] for o in outs], axis=0)
                for k in outs[0]}


class _TeacherConn(object):
    """One connection to one teacher; splits oversized batches to the
    teacher's compiled max_batch. With a :class:`ClientPool` the
    connection is the pool's shared client for the endpoint (redialed
    only when retired); without one the conn owns a private client —
    the pre-pool behavior."""

    def __init__(self, endpoint, timeout=60.0, pool=None):
        self.endpoint = endpoint
        self._pool = pool
        self._rpc = (pool.get(endpoint) if pool is not None
                     else RpcClient(endpoint, timeout=timeout))
        spec = self._rpc.call("get_feed_fetch")
        self.max_batch = spec.get("max_batch", 64)
        self.fetch_names = list(spec.get("fetch", {}))
        self.features = tuple(spec.get("features", ()))
        self.pipelined = "rpc.pipeline" in self.features

    def predict_async(self, feed):
        """Issue one logical predict; oversized feeds are split into
        max_batch chunks that are ALL sent before any reply is awaited,
        so a 4-chunk batch costs ~1 round trip instead of 4."""
        if not feed:
            raise errors.DataAccessError("empty feed: no input arrays")
        n = len(next(iter(feed.values())))
        if n == 0:
            # fail fast client-side: the teacher would reject it anyway,
            # and an empty chunk list used to IndexError in the join
            raise errors.DataAccessError("empty feed: zero-row batch")
        futs = []
        for lo in range(0, n, self.max_batch):
            chunk = {k: v[lo:lo + self.max_batch] for k, v in feed.items()}
            futs.append(self._rpc.call_async("predict", chunk))
        return _PredictFuture(futs)

    def predict(self, feed):
        return self.predict_async(feed).result()

    def close(self):
        # a pooled client is shared: its lifetime belongs to the pool
        # (idle reaping / retire-on-error), not to this worker
        if self._pool is None:
            self._rpc.close()


class DistillReader(object):
    """``pipeline_depth``: predicts kept in flight per teacher
    connection (1 = the pre-pipelining lockstep behavior; also forced
    to 1 when the teacher doesn't advertise ``rpc.pipeline``).
    ``predict_timeout``: per-RPC deadline for one predict chunk."""

    def __init__(self, ins, predicts, max_in_flight=8,
                 teacher_backoff=5.0, pipeline_depth=4,
                 predict_timeout=60.0, pool=None):
        self._ins = list(ins)
        self._predicts = list(predicts)
        self._max_in_flight = max_in_flight
        self._pipeline_depth = max(1, int(pipeline_depth))
        self._predict_timeout = predict_timeout
        # shared client pool: one connection per teacher across worker
        # generations (a worker restart used to redial), retired on
        # transport failure so the next worker dials fresh
        self._pool = pool if pool is not None \
            else ClientPool(timeout=predict_timeout)
        self._owns_pool = pool is None

        self._gen = None
        self._gen_kind = None
        self._discover = None

        self._in_q = queue.Queue()
        self._results = {}
        self._results_cond = threading.Condition()
        self._stop = threading.Event()
        self._workers = {}          # endpoint -> (thread, stop_event)
        # per-teacher circuit breaker (replaces an ad-hoc timestamp map
        # that grew without bound as teacher endpoints churned): one
        # failure opens the circuit for ``teacher_backoff`` seconds,
        # then a single half-open probe worker decides recovery
        self._breaker = CircuitBreaker(failure_threshold=1,
                                       reset_timeout=teacher_backoff)
        self._inflight = {}         # endpoint -> [tasks being predicted]
        self._inflight_lock = threading.Lock()
        self._manager = None
        self._started = False
        self._epoch = 0             # generation token fencing epochs
        self.stall_timeout = 300.0  # no-progress watchdog for the consumer

    # -- configuration (reference setter surface) ------------------------------

    def set_sample_generator(self, gen, batch_size):
        """gen yields one sample tuple; batched here to ``batch_size``."""
        self._gen, self._gen_kind = gen, ("sample", batch_size)
        return self

    def set_sample_list_generator(self, gen):
        """gen yields a list of sample tuples (one student batch)."""
        self._gen, self._gen_kind = gen, ("sample_list", None)
        return self

    def set_batch_generator(self, gen):
        """gen yields a tuple/list of batched arrays matching ``ins``."""
        self._gen, self._gen_kind = gen, ("batch", None)
        return self

    def set_fixed_teacher(self, endpoints):
        self._discover = FixedDiscover(endpoints).start()
        return self

    def set_dynamic_teacher(self, discovery_endpoint, service_name,
                            require_num=1):
        self._discover = DiscoveryClient(
            discovery_endpoint, service_name, require_num).start()
        return self

    # -- worker management -------------------------------------------------------

    def _ensure_started(self):
        if self._started:
            return
        if self._gen is None or self._discover is None:
            raise errors.StatusError(
                "DistillReader needs a generator and a teacher source")
        self._manager = threading.Thread(target=self._manage_loop,
                                         daemon=True,
                                         name="distill-manager")
        self._manager.start()
        self._started = True

    def _manage_loop(self):
        while not self._stop.wait(1.0):
            self._sync_workers()

    def _sync_workers(self):
        want = set(self._discover.get_servers())
        # breaker state only for teachers that still exist: endpoint
        # churn must not grow the map without bound
        self._breaker.prune(want)
        # drop workers whose teacher disappeared; requeue anything a dead
        # worker was still holding so no task is ever lost
        for ep in list(self._workers):
            thread, stop_ev = self._workers[ep]
            if ep not in want:
                stop_ev.set()
            if not thread.is_alive():
                del self._workers[ep]
                with self._inflight_lock:
                    orphans = self._inflight.pop(ep, None) or []
                for orphan in orphans:
                    logger.warning("requeueing task %d orphaned by dead "
                                   "worker %s", orphan[1], ep)
                    self._in_q.put(orphan)
        # start workers for new teachers; an open circuit (recent
        # failure) gates the endpoint until its half-open probe window
        for ep in want:
            if ep in self._workers:
                continue
            if not self._breaker.allow(ep):
                continue
            stop_ev = threading.Event()
            thread = threading.Thread(
                target=self._predict_loop, args=(ep, stop_ev), daemon=True,
                name="distill-predict-%s" % ep)
            thread.start()
            self._workers[ep] = (thread, stop_ev)

    # -- the per-teacher worker --------------------------------------------------

    def _track(self, endpoint, task, add):
        with self._inflight_lock:
            tasks = self._inflight.setdefault(endpoint, [])
            if add:
                tasks.append(task)
            else:
                try:
                    tasks.remove(task)
                except ValueError:
                    pass  # already handed to _sync_workers' requeue

    def _post_result(self, epoch, task_id, payload, preds):
        with self._results_cond:
            self._results[(epoch, task_id)] = (payload, preds)
            self._results_cond.notify_all()

    def _fill_pipeline(self, conn, endpoint, pending, depth):
        """Issue predicts until ``depth`` are in flight or the task
        queue is (momentarily) empty. Returns False when the
        connection failed and the worker must retire."""
        while len(pending) < depth:
            try:
                # block only when idle; with work in flight just top up
                task = self._in_q.get(timeout=0.0 if pending else 0.2)
            except queue.Empty:
                return True
            epoch, task_id, feed, payload = task
            if epoch != self._epoch:  # stale task from an abandoned epoch
                continue
            self._track(endpoint, task, add=True)
            try:
                fut = conn.predict_async(feed)
            except errors.OverloadedError as e:
                # the teacher SHED this task (typed, with a retry-after
                # hint): the task is fine, the endpoint is saturated —
                # requeue for another teacher and back off this one
                self._track(endpoint, task, add=False)
                self._in_q.put(task)
                self._back_off_teacher(endpoint, e)
                return False
            except errors.DataAccessError as e:
                # the task itself is poisoned (empty/malformed feed):
                # requeueing would ping-pong it between teachers forever,
                # so surface it to the consumer in order
                self._track(endpoint, task, add=False)
                self._post_result(epoch, task_id, _TASK_ERROR, e)
            except Exception as e:  # noqa: BLE001 — transport: requeue
                self._track(endpoint, task, add=False)
                logger.warning("teacher %s failed task %d (%r); "
                               "requeueing", endpoint, task_id, e)
                self._in_q.put(task)
                self._retire_teacher(endpoint)
                return False
            else:
                pending.append((task, fut))
        return True

    def _retire_teacher(self, endpoint):
        """A transport failure opens the breaker AND retires the pooled
        client — the teacher may have restarted as a new generation, so
        the next worker must dial fresh."""
        self._breaker.record_failure(endpoint)
        self._pool.retire(endpoint)

    def _back_off_teacher(self, endpoint, e):
        """A typed shed (OverloadedError) opens the breaker — the
        manage loop gates the endpoint for ``teacher_backoff`` before
        a half-open probe — but the connection is HEALTHY (the teacher
        answered, fast), so the pooled client stays: backing off must
        not force a redial storm against an overloaded server."""
        hint = e.retry_after_s
        logger.warning("teacher %s shed work (%r); backing off%s",
                       endpoint, e,
                       "" if hint is None
                       else " (server hints %.2fs)" % hint)
        self._breaker.record_failure(endpoint)

    def _predict_loop(self, endpoint, stop_ev):
        try:
            conn = _TeacherConn(endpoint, timeout=self._predict_timeout,
                                pool=self._pool)
        except errors.EdlError as e:
            logger.warning("teacher %s unreachable: %r", endpoint, e)
            self._retire_teacher(endpoint)
            return
        # feature negotiation: a pre-pipelining teacher gets lockstep
        # depth 1 — exactly the old strict call/response traffic
        depth = self._pipeline_depth if conn.pipelined else 1
        logger.info("distill worker up for teacher %s (depth=%d)",
                    endpoint, depth)
        tl = timeline.get_timeline()
        pending = collections.deque()  # (task, _PredictFuture) in flight
        ok = True
        while not (stop_ev.is_set() or self._stop.is_set()):
            if not self._fill_pipeline(conn, endpoint, pending, depth):
                ok = False
                break
            if not pending:
                continue
            task, fut = pending.popleft()
            epoch, task_id, feed, payload = task
            try:
                with tl.span("predict@%s" % endpoint):
                    preds = fut.result()
            except errors.OverloadedError as e:
                # typed shed from admission control: requeue elsewhere,
                # open the breaker, keep the (healthy) pooled client
                self._track(endpoint, task, add=False)
                self._in_q.put(task)
                self._back_off_teacher(endpoint, e)
                ok = False
                break
            except errors.DataAccessError as e:
                self._track(endpoint, task, add=False)
                self._post_result(epoch, task_id, _TASK_ERROR, e)
                continue
            except Exception as e:  # noqa: BLE001 — transport: requeue
                self._track(endpoint, task, add=False)
                logger.warning("teacher %s failed task %d (%r); requeueing",
                               endpoint, task_id, e)
                self._in_q.put(task)
                self._retire_teacher(endpoint)
                ok = False
                break
            self._track(endpoint, task, add=False)
            self._breaker.record_success(endpoint)
            self._post_result(epoch, task_id, payload, preds)
        # a dead connection fails every in-flight future, so anything
        # still pending is requeued here, not lost (requeue-safe drain)
        for task, _ in pending:
            self._track(endpoint, task, add=False)
            if ok:
                logger.warning("requeueing task %d in flight at worker "
                               "%s retirement", task[1], endpoint)
            self._in_q.put(task)
        conn.close()
        logger.info("distill worker for %s retired", endpoint)

    # -- epoch iteration -----------------------------------------------------------

    def _frame_tasks(self):
        """Yield (feed_dict, payload) per student batch."""
        kind, batch_size = self._gen_kind
        if kind == "batch":
            for arrays in self._gen():
                arrays = [np.asarray(a) for a in arrays]
                feed = dict(zip(self._ins, arrays))
                yield feed, arrays
        else:
            def batches():
                if kind == "sample_list":
                    yield from self._gen()
                else:
                    buf = []
                    for sample in self._gen():
                        buf.append(sample)
                        if len(buf) >= batch_size:
                            yield buf
                            buf = []
                    if buf:
                        yield buf
            for samples in batches():
                cols = list(zip(*samples))
                arrays = [np.asarray(np.stack(c)) for c in cols]
                feed = dict(zip(self._ins, arrays[:len(self._ins)]))
                yield feed, samples

    def __call__(self):
        """One pass over the student data, each batch augmented with the
        teacher predictions, in the original order."""
        self._ensure_started()
        # bump the epoch token: workers drop tasks/results from abandoned
        # epochs, and any feeder thread from a previous epoch exits
        self._epoch += 1
        epoch = self._epoch
        while True:
            try:
                self._in_q.get_nowait()
            except queue.Empty:
                break
        with self._results_cond:
            self._results.clear()
        sem = threading.Semaphore(self._max_in_flight)
        fed = {"n": 0, "done_feeding": False, "error": None}

        def feeder():
            try:
                for task_id, (feed, payload) in enumerate(
                        self._frame_tasks()):
                    if self._stop.is_set() or self._epoch != epoch:
                        return
                    sem.acquire()
                    fed["n"] = task_id + 1
                    self._in_q.put((epoch, task_id, feed, payload))
            except BaseException as e:  # noqa: BLE001 — re-raised in __call__
                # a generator that raises mid-epoch must NOT look like a
                # clean completion to the consumer (silent data loss)
                fed["error"] = e
            finally:
                fed["done_feeding"] = True
                with self._results_cond:
                    self._results_cond.notify_all()

        feeder_thread = threading.Thread(target=feeder, daemon=True,
                                         name="distill-feeder")
        feeder_thread.start()

        next_id = 0
        last_progress = time.monotonic()
        while True:
            with self._results_cond:
                while (epoch, next_id) not in self._results:
                    if (fed["done_feeding"] and next_id >= fed["n"]):
                        feeder_thread.join(timeout=5)
                        if fed["error"] is not None:
                            raise fed["error"]
                        return
                    self._results_cond.wait(timeout=0.5)
                    if self._stop.is_set():
                        return
                    if (time.monotonic() - last_progress
                            > self.stall_timeout):
                        raise errors.DataAccessError(
                            "distill pipeline stalled %.0fs waiting for "
                            "task %d (workers=%s, queued=%d)"
                            % (self.stall_timeout, next_id,
                               sorted(self._workers), self._in_q.qsize()))
                payload, preds = self._results.pop((epoch, next_id))
            sem.release()
            last_progress = time.monotonic()
            if payload is _TASK_ERROR:
                raise preds  # the per-task DataAccessError, in order
            yield self._assemble(payload, preds)
            next_id += 1

    def _assemble(self, payload, preds):
        pred_arrays = [preds[name] for name in self._predicts]
        if self._gen_kind[0] == "batch":
            return tuple(payload) + tuple(pred_arrays)
        out = []
        for i, sample in enumerate(payload):
            out.append(tuple(sample) + tuple(a[i] for a in pred_arrays))
        return out

    def stop(self):
        self._stop.set()
        for _, stop_ev in self._workers.values():
            stop_ev.set()
        if self._discover is not None:
            self._discover.stop()
        if self._owns_pool:
            # failing the in-flight predicts wakes any worker blocked
            # in fut.result(); the requeue-safe drain handles the rest
            self._pool.close()
