"""Discovery server: matches student clients to teacher servers.

Reference parity: edl/distill/discovery_server.py + the BalanceTable
consistent-hash sharding (balance_table.py:359-689): multiple discovery
servers self-register under a ``__balance__`` service; each service name is
owned by one discovery server on the hash ring; requests for a service
owned elsewhere get a REDIRECT with the owner's endpoint
(discovery_client.py handles reconnects).

Teacher membership comes from the coordination store (the registry module's
TTL leases) via a prefix watch per service.
"""

import argparse
import signal
import threading

from edl_tpu.coordination.client import CoordClient
from edl_tpu.distill import registry
from edl_tpu.distill.balance import BalanceTable
from edl_tpu.distill.consistent_hash import ConsistentHash
from edl_tpu.rpc.server import RpcServer
from edl_tpu.utils.logger import logger

BALANCE_SERVICE = "__balance__"

CODE_OK = "OK"
CODE_REDIRECT = "REDIRECT"
CODE_UNREGISTERED = "UNREGISTERED"
CODE_NO_READY = "NO_READY"


class DiscoveryServer(object):
    def __init__(self, coord, host="0.0.0.0", port=0, ttl=10):
        self._coord = coord
        self._table = BalanceTable()
        self._hash = ConsistentHash()
        self._watchers = {}
        self._lock = threading.Lock()
        self._ttl = ttl
        self._lease = None
        self._refresher = None
        self._stop = threading.Event()
        self._peer_watcher = None

        self._rpc = RpcServer(host=host, port=port)
        self._rpc.register("register_client", self.register_client)
        self._rpc.register("heartbeat", self.heartbeat)
        self._rpc.register("unregister_client", self.unregister_client)
        self._rpc.register("stats", self.stats)

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self._rpc.start()
        self._lease = self._coord.set_server_with_lease(
            BALANCE_SERVICE, self.endpoint, "", self._ttl)
        self._refresher = threading.Thread(target=self._refresh_loop,
                                           daemon=True)
        self._refresher.start()
        self._peer_watcher = self._coord.watch_service(
            BALANCE_SERVICE, self._on_peers, poll_timeout=1.0)
        logger.info("discovery server on %s", self.endpoint)
        return self

    def _refresh_loop(self):
        while not self._stop.wait(self._ttl / 3.0):
            try:
                self._coord.refresh_server(BALANCE_SERVICE, self.endpoint,
                                           self._lease)
            except Exception:
                logger.exception("discovery self-registration lost")

    def _on_peers(self, added, removed, all_servers):
        self._hash.update(all_servers.keys())
        logger.info("discovery peers now %s", sorted(all_servers))

    @property
    def endpoint(self):
        return self._rpc.endpoint

    def stop(self):
        self._stop.set()
        if self._peer_watcher:
            self._peer_watcher.stop()
        with self._lock:
            for w in self._watchers.values():
                w.stop()
            self._watchers.clear()
        if self._lease is not None:
            try:
                self._coord.lease_revoke(self._lease)
            except Exception:
                pass
        self._rpc.stop()

    # -- sharding ------------------------------------------------------------

    def _owner(self, service_name):
        node, _ = self._hash.get_node(service_name)
        return node

    def _ensure_service(self, service_name):
        """Start watching this service's teachers on first touch."""
        with self._lock:
            if service_name in self._watchers:
                return
            svc = self._table.service(service_name)

            def on_change(added, removed, all_servers, _svc=svc):
                # the full {endpoint: info} map: registration info
                # carries capacity weights and draining flags, which
                # make the balancer load-aware (balance.Service)
                _svc.set_servers(dict(all_servers))

            self._watchers[service_name] = self._coord.watch_service(
                registry.teacher_service(service_name), on_change,
                poll_timeout=1.0)

    # -- RPC surface ----------------------------------------------------------

    def register_client(self, client_id, service_name, require_num,
                        phase=None):
        owner = self._owner(service_name)
        if owner is not None and owner != self.endpoint:
            return {"code": CODE_REDIRECT, "endpoint": owner}
        self._ensure_service(service_name)
        out = self._table.service(service_name).register_client(
            client_id, require_num, phase=phase)
        code = CODE_OK if out["servers"] else CODE_NO_READY
        return {"code": code, "version": out["version"],
                "servers": out["servers"]}

    def heartbeat(self, client_id, service_name, version):
        owner = self._owner(service_name)
        if owner is not None and owner != self.endpoint:
            return {"code": CODE_REDIRECT, "endpoint": owner}
        out = self._table.service(service_name).heartbeat(client_id, version)
        if out is None:
            return {"code": CODE_UNREGISTERED}
        out["code"] = CODE_OK
        return out

    def unregister_client(self, client_id, service_name):
        self._table.service(service_name).unregister_client(client_id)
        return {"code": CODE_OK}

    def stats(self):
        return {name: self._table.service(name).stats()
                for name in self._table.names()}


def main():
    p = argparse.ArgumentParser("edl_tpu distill discovery server")
    p.add_argument("--store_endpoints", default="127.0.0.1:2379")
    p.add_argument("--root", default="distill_jobs")
    p.add_argument("--port", type=int, default=0)
    args = p.parse_args()
    coord = CoordClient(args.store_endpoints, root=args.root)
    server = DiscoveryServer(coord, port=args.port).start()
    print("DISCOVERY_ENDPOINT=%s" % server.endpoint, flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    server.stop()


if __name__ == "__main__":
    main()
