"""Consistent hash ring with virtual nodes and copy-on-write updates.

Reference parity: edl/discovery/consistent_hash.py:105-141 (300 virtual
nodes, MD5 ring, version counter, copy-on-write thread safety). Shards
service names across discovery servers.
"""

import bisect
import hashlib
import threading


def _hash(key):
    return int(hashlib.md5(key.encode("utf-8")).hexdigest(), 16)


class ConsistentHash(object):
    VIRTUAL_NODES = 300

    def __init__(self, nodes=()):
        self._lock = threading.Lock()
        self._version = 0
        self._nodes = set()
        self._ring = []          # sorted [(hash, node)]
        if nodes:
            self.update(nodes)

    def update(self, nodes, weights=None):
        """Replace the node set (copy-on-write: readers see old or new).

        ``weights`` ({node: relative capacity}) scales each node's
        virtual-node count, so a capacity-2.0 teacher owns ~2x the key
        space and a draining one (weight 0) owns none — the hash-ring
        half of load-aware balancing. Unlisted nodes weigh 1.0; a
        positive weight always gets at least one vnode."""
        nodes = set(nodes)
        weights = weights or {}
        ring = []
        for node in nodes:
            try:
                w = float(weights.get(node, 1.0))
            except (TypeError, ValueError):
                w = 1.0
            vnodes = 0 if w <= 0.0 else max(1, int(round(
                self.VIRTUAL_NODES * w)))
            for i in range(vnodes):
                ring.append((_hash("%s#%d" % (node, i)), node))
        ring.sort()
        with self._lock:
            self._nodes = nodes
            self._ring = ring
            self._version += 1
            return self._version

    def add_node(self, node):
        with self._lock:
            nodes = set(self._nodes)
        nodes.add(node)
        return self.update(nodes)

    def remove_node(self, node):
        with self._lock:
            nodes = set(self._nodes)
        nodes.discard(node)
        return self.update(nodes)

    def get_node(self, key):
        """(node, version) owning ``key``; (None, version) on empty ring."""
        with self._lock:
            ring = self._ring
            version = self._version
        if not ring:
            return None, version
        idx = bisect.bisect(ring, (_hash(key), chr(0x10FFFF)))
        if idx >= len(ring):
            idx = 0
        return ring[idx][1], version

    @property
    def version(self):
        with self._lock:
            return self._version

    def nodes(self):
        with self._lock:
            return set(self._nodes)
