"""Student-side discovery client: register, heartbeat, follow redirects,
surface the current teacher list.

Reference parity: edl/distill/discovery_client.py (response-code dispatch
:70-80, 2s versioned heartbeat :169-182, redirect reconnect :115-131,
client uuid :184).
"""

import os
import threading
import uuid

from edl_tpu.distill import discovery_server as ds
from edl_tpu.robustness import faults
from edl_tpu.robustness.policy import CircuitBreaker, Deadline, \
    RetryPolicy
from edl_tpu.rpc.client import RpcClient
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger


def _gen_client_id():
    return "%s-%d-%s" % (os.uname().nodename, os.getpid(),
                         uuid.uuid4().hex[:8])


class DiscoveryClient(object):
    def __init__(self, endpoint, service_name, require_num=1,
                 heartbeat_interval=2.0, phase=None):
        self._endpoint = endpoint
        self._service = service_name
        self._require = require_num
        # serving-phase affinity (None | "prefill" | "decode"): which
        # advertised teacher capacity this client weighs against in the
        # balance table (distill/balance.py phase disaggregation)
        self._phase = phase
        self._interval = heartbeat_interval
        self.client_id = _gen_client_id()
        self._rpc = None
        self._version = -1
        self._servers = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        # discovery-outage degradation (stale-but-serving): a dead
        # discovery server opens this breaker — exactly one
        # ``breaker.open`` event per outage — and re-register probes
        # run at the bounded half-open rate (one per heartbeat
        # interval) instead of hammering; the last-known teacher table
        # keeps serving untouched the whole time, and a returned
        # server is re-joined within one probe period
        self._breaker = CircuitBreaker(failure_threshold=1,
                                       reset_timeout=heartbeat_interval)
        self._poll = RetryPolicy(base_delay=0.2, max_delay=1.0,
                                 multiplier=1.5, jitter=0.5)

    # -- wire helpers -----------------------------------------------------------

    def _connect(self, endpoint):
        if self._rpc is not None:
            self._rpc.close()
        self._rpc = RpcClient(endpoint, timeout=10)

    def _register(self):
        """Register, following redirects to the shard owner."""
        endpoint = self._endpoint
        for _ in range(8):
            self._connect(endpoint)
            resp = self._rpc.call("register_client", self.client_id,
                                  self._service, self._require,
                                  self._phase)
            code = resp.get("code")
            if code == ds.CODE_REDIRECT:
                endpoint = resp["endpoint"]
                continue
            if code in (ds.CODE_OK, ds.CODE_NO_READY):
                with self._lock:
                    self._version = resp["version"]
                    self._servers = list(resp.get("servers", []))
                self._breaker.record_success(self._endpoint)
                return
            raise errors.RpcError("register failed: %r" % resp)
        raise errors.RpcError("too many discovery redirects")

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        self._register()
        self._thread = threading.Thread(target=self._heartbeat_loop,
                                        daemon=True,
                                        name="discovery-heartbeat")
        self._thread.start()
        return self

    def _heartbeat_loop(self):
        while not self._stop.wait(self._interval):
            if self._breaker.state(self._endpoint) \
                    != CircuitBreaker.CLOSED:
                # outage mode: the last-known table keeps serving; a
                # re-register probe runs at the breaker's bounded
                # half-open rate (one per interval) — a returned
                # server closes the breaker inside _register()
                if self._breaker.allow(self._endpoint):
                    try:
                        self._register()
                    except errors.EdlError:
                        self._breaker.record_failure(self._endpoint)
                continue
            try:
                resp = self._rpc.call("heartbeat", self.client_id,
                                      self._service, self._version)
                code = resp.get("code")
                self._breaker.record_success(self._endpoint)
                if code == ds.CODE_REDIRECT:
                    self._connect(resp["endpoint"])
                    self._register()
                    continue
                if code == ds.CODE_UNREGISTERED:
                    logger.info("discovery dropped us; re-registering")
                    self._register()
                    continue
                if "servers" in resp:
                    with self._lock:
                        self._version = resp["version"]
                        self._servers = list(resp["servers"])
            except errors.EdlError as e:
                # the table in self._servers is NOT cleared: clients
                # keep routing on the last-known membership while the
                # discovery server is away (stale-but-serving). The
                # closed→open transition logs exactly ONE breaker.open
                # event per outage (half-open re-probes mark
                # ``reopened`` instead).
                logger.warning("discovery heartbeat error: %r", e)
                self._breaker.record_failure(self._endpoint)

    def get_servers(self):
        if faults.PLANE is not None:
            # chaos: a "drop" here makes the whole teacher fleet vanish
            # from this client's view (endpoint flap drills)
            f = faults.PLANE.fire("distill.discovery",
                                  service=self._service)
            if f is not None:
                return []
        with self._lock:
            return list(self._servers)

    def wait_for_servers(self, timeout=60):
        deadline = Deadline(timeout)
        attempt = 0
        while True:
            servers = self.get_servers()
            if servers:
                return servers
            attempt += 1
            if not self._poll.sleep(attempt, deadline):
                raise errors.TimeoutError_(
                    "no teachers discovered within %ss" % timeout)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._interval * 2 + 1)
        if self._rpc is not None:
            try:
                self._rpc.call("unregister_client", self.client_id,
                               self._service)
            except errors.EdlError:
                pass
            self._rpc.close()


class FixedDiscover(object):
    """A static teacher list (reference FixedServiceDiscover,
    distill_reader.py:38-45)."""

    def __init__(self, endpoints):
        self._endpoints = list(endpoints)

    def start(self):
        return self

    def get_servers(self):
        if faults.PLANE is not None:
            # same flap drill as the dynamic client: fixed fleets are
            # what chaos tests usually stand up
            f = faults.PLANE.fire("distill.discovery", service="fixed")
            if f is not None:
                return []
        return list(self._endpoints)

    def wait_for_servers(self, timeout=0):
        return list(self._endpoints)

    def stop(self):
        pass
