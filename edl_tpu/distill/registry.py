"""Teacher registration daemon: advertise a live inference server into the
coordination store while its port answers TCP.

Reference parity: edl/discovery/register.py (TTL registration gated on a
TCP alive probe :40-74; CLI __main__:99) and the redis flavor
(edl/distill/redis/server_register.py). One store, one code path here.
"""

import argparse
import json
import signal
import threading
import time

from edl_tpu.coordination.client import CoordClient
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger
from edl_tpu.utils.network import is_server_alive

TEACHER_SERVICE_PREFIX = "distill"


def teacher_service(service_name):
    return "%s/%s" % (TEACHER_SERVICE_PREFIX, service_name)


class TeacherRegister(object):
    """Register ``endpoint`` under distill/<service_name> with a TTL lease,
    refreshing while the server answers TCP; deregisters when it dies."""

    def __init__(self, coord, service_name, endpoint, info=None, ttl=10):
        self._coord = coord
        self._service = teacher_service(service_name)
        self._endpoint = endpoint
        self._info = json.dumps(info or {})
        self._ttl = ttl
        self._lease = None
        self._lease_lock = threading.Lock()
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="teacher-register")

    def start(self):
        self._thread.start()
        return self

    def drain(self):
        """Stop advertising NOW: revoke the lease and never
        re-register — step 1 of the drain-safe decommission protocol
        (serve/drain.py). Discovery stops handing this endpoint to new
        clients immediately; clients already holding it age it out
        within one TTL."""
        self._draining.set()
        with self._lease_lock:
            lease, self._lease = self._lease, None
        if lease is not None:
            try:
                self._coord.lease_revoke(lease)
                logger.info("teacher %s draining; deregistered from %s",
                            self._endpoint, self._service)
            except errors.EdlError as e:
                # the TTL is the backstop: an unreachable store just
                # means the lease lapses on its own
                logger.warning("drain revoke failed (TTL will lapse): "
                               "%r", e)

    @property
    def draining(self):
        return self._draining.is_set()

    def _run(self):
        while not self._stop.is_set():
            alive = (not self._draining.is_set()
                     and is_server_alive(self._endpoint, timeout=2))
            try:
                with self._lease_lock:
                    lease = self._lease
                if alive and lease is None:
                    lease = self._coord.set_server_with_lease(
                        self._service, self._endpoint, self._info, self._ttl)
                    with self._lease_lock:
                        self._lease = lease
                    logger.info("teacher %s registered in %s",
                                self._endpoint, self._service)
                elif alive:
                    self._coord.refresh_server(self._service, self._endpoint,
                                               lease)
                elif lease is not None and not self._draining.is_set():
                    logger.warning("teacher %s dead; deregistering",
                                   self._endpoint)
                    self._coord.lease_revoke(lease)
                    with self._lease_lock:
                        self._lease = None
            except errors.EdlError as e:
                logger.warning("teacher register error: %r", e)
                with self._lease_lock:
                    self._lease = None
            self._stop.wait(self._ttl / 3.0)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=self._ttl)
        with self._lease_lock:
            lease, self._lease = self._lease, None
        if lease is not None:
            try:
                self._coord.lease_revoke(lease)
            except errors.EdlError:
                pass


def list_teachers(coord, service_name):
    """endpoint -> info for every live teacher of a service."""
    return dict(coord.get_service(teacher_service(service_name)))


def main():
    p = argparse.ArgumentParser("edl_tpu teacher register")
    p.add_argument("--store_endpoints", default="127.0.0.1:2379")
    p.add_argument("--root", default="distill_jobs")
    p.add_argument("--service_name", required=True)
    p.add_argument("--server", required=True, help="teacher host:port")
    p.add_argument("--ttl", type=int, default=10)
    args = p.parse_args()
    coord = CoordClient(args.store_endpoints, root=args.root)
    # wait for the server to come up before daemonizing the heartbeat
    deadline = time.time() + 60
    while not is_server_alive(args.server) and time.time() < deadline:
        time.sleep(1)
    reg = TeacherRegister(coord, args.service_name, args.server,
                          ttl=args.ttl).start()
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    reg.stop()


if __name__ == "__main__":
    main()
