"""Learning-rate schedules with the reference's semantics, as optax-style
step → lr callables (usable directly as optax schedules).

Reference parity: example/collective/resnet50/train_with_fleet.py:114-225 —
linear warmup followed by piecewise or cosine decay, with the base lr
linearly scaled by total batch size / 256 ("lr_scale" rule). Elastic twist:
``scaled_for_world`` recomputes the schedule when the world resizes
(doc/edl_collective_design_doc.md:15-17, state.py:142 adjust hooks).
"""

import jax.numpy as jnp


def linear_warmup(base_schedule, warmup_steps, start_lr=0.0):
    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = start_lr + (base_schedule(warmup_steps) - start_lr) * (
            step / jnp.maximum(warmup_steps, 1))
        return jnp.where(step < warmup_steps, warm, base_schedule(step))
    return schedule


def piecewise_decay(base_lr, boundaries, gamma=0.1):
    """lr = base_lr * gamma^(number of boundaries passed)."""
    bs = jnp.asarray(boundaries, jnp.float32)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        idx = jnp.sum(step >= bs)
        return base_lr * (gamma ** idx)
    return schedule


def cosine_decay(base_lr, total_steps, final_lr=0.0):
    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        frac = jnp.clip(step / jnp.maximum(total_steps, 1), 0.0, 1.0)
        return final_lr + (base_lr - final_lr) * 0.5 * (
            1.0 + jnp.cos(jnp.pi * frac))
    return schedule


def scale_lr_for_batch(base_lr, total_batch_size, base_batch_size=256):
    """The linear-scaling rule the reference applies (train_with_fleet.py
    lr = lr * total_batch/256)."""
    return base_lr * (total_batch_size / float(base_batch_size))
