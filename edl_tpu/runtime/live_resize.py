"""Zero-downtime live resize: the store protocol and the in-place
reshard engine.

Stop-resume elasticity (the reference's model) kills every trainer on a
membership change and pays kill + barrier + restore + compile on the
way back. A SURVIVING process already holds everything the new world
needs — the committed state (host snapshot + device arrays), the peer
restore plane, and (with prewarm) the new world's AOT step executable —
so the only work a resize truly requires is a reshard and an executable
swap. This module provides the two halves:

**The protocol** (leader-coordinated two-phase commit over the
coordination store, SERVICE_LIVE_RESIZE):

- trainers that can reshape in place advertise a TTL-leased
  ``ready_<who>`` capability key (:func:`advertise_capability`);
- the coordinator (cluster generator, resize driver, bench) publishes a
  ``prepare`` intent under the single ``intent`` key — leader-guarded,
  so a deposed leader's intent is a no-op (:func:`publish_prepare`);
- each surviving trainer drains to a step boundary, reshards
  (:meth:`ElasticTrainer.live_resize`), and writes an ``ack_<who>``
  key (:func:`write_ack`);
- all-acks-ok → the coordinator atomically flips the intent to
  ``commit`` *and* installs the new cluster map in ONE guarded
  transaction (:func:`commit`) — the launcher sees the committed intent
  and adopts the map without killing anyone;
- any nack, timeout, or leader change → ``abort`` (:func:`abort`) and
  the existing stop-resume ladder runs unchanged. A fresh leader
  finding a stale foreign/expired ``prepare`` aborts it first
  (generator `_abort_stale_intent`), so a coordinator death mid-reshard
  degrades to stop-resume, never to a wedge.

**The engine** (:func:`reshard_placed`): build the new world's
:class:`~edl_tpu.runtime.checkpoint.PlacedTarget`, paste every span the
process already holds locally from the live device arrays (zero copy in
from host: ``np.asarray`` on a CPU/host-local shard aliases the
buffer), fetch only the still-missing spans from peer StateServers at
the published version (:meth:`PeerRestorer.fill_from_peers`), then the
per-span FS fallback — the same ladder as a stop-resume restore, minus
the process restart.

Scope: the engine reshapes within ONE process (the JAX runtime cannot
re-run ``jax.distributed.initialize``). Within that process the
predicate is SPAN COMPUTABILITY, not replication: any state sharding
whose PartitionSpecs transplant onto the target mesh (every named axis
present, every sharded dim divisible) is in scope — a tp-degree
change, a pp-stage re-split, or an expert re-balance is per-leaf span
intersection like any other restore, and the intent may carry a
``mesh`` factorization (the generator's roofline choice, see
parallel/costmodel.py) for the trainer to rebuild. Multi-process
worlds and hybrid (dcn) topologies keep stop-resume; the capability
key simply never appears, and the generator's eligibility check falls
through. See docs/elastic_resize.md for the saved-mesh × target-mesh
support matrix.
"""

import json
import time

import numpy as np

from edl_tpu.controller import constants
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger

INTENT_KEY = "intent"
PREPARE = "prepare"
COMMIT = "commit"
ABORT = "abort"

# a prepare older than this is stale even without an explicit deadline
DEFAULT_DEADLINE_S = 30.0


def make_intent(intent_id, survivors, devices=None, leader=None,
                cluster_json=None, mesh=None,
                deadline_s=DEFAULT_DEADLINE_S):
    """The intent document. ``survivors`` are the pods/trainers that
    must ack; ``devices`` the per-survivor device target (None = keep);
    ``cluster_json`` the new cluster map the commit installs; ``mesh``
    an optional {axis: size} factorization for the survivors to
    rebuild (None = keep model axes, rescale dp)."""
    return {
        "id": str(intent_id),
        "phase": PREPARE,
        "survivors": [str(s) for s in survivors],
        "devices": devices,
        "leader": leader,
        "cluster": cluster_json,
        "mesh": mesh,
        "deadline_ts": time.time() + float(deadline_s),
        "ts": time.time(),
    }


def _intent_full_key(coord):
    return coord.service_prefix(constants.SERVICE_LIVE_RESIZE) + INTENT_KEY


def read_intent(coord):
    raw = coord.get_value(constants.SERVICE_LIVE_RESIZE, INTENT_KEY)
    if not raw:
        return None
    try:
        return json.loads(raw)
    except ValueError:
        return None


def intent_expired(intent, now=None):
    return (now or time.time()) > float(intent.get("deadline_ts", 0))


def publish_prepare(coord, leader_value, intent):
    """Phase 1: leader-guarded write of the prepare intent. Returns
    True iff this coordinator still held the leader key."""
    try:
        return bool(coord.put_if_leader(
            constants.SERVICE_LEADER, constants.LEADER_SERVER,
            leader_value, [(_intent_full_key(coord),
                            json.dumps(intent))]))
    except errors.NotLeaderError:
        return False


def commit(coord, leader_value, intent, extra_puts=()):
    """Phase 2: atomically flip the intent to ``commit`` AND apply
    ``extra_puts`` (the new cluster map) in one leader-guarded
    transaction — survivors and the launcher observe either the whole
    live resize or none of it. Returns True iff still leader."""
    doc = dict(intent)
    doc["phase"] = COMMIT
    doc["commit_ts"] = time.time()
    puts = [(_intent_full_key(coord), json.dumps(doc))]
    puts.extend(extra_puts)
    try:
        return bool(coord.put_if_leader(
            constants.SERVICE_LEADER, constants.LEADER_SERVER,
            leader_value, puts))
    except errors.NotLeaderError:
        return False


def abort(coord, leader_value, intent, reason=""):
    """Flip a prepare intent to ``abort`` (leader-guarded); the ladder
    falls back to stop-resume. Returns True iff still leader."""
    doc = dict(intent)
    doc["phase"] = ABORT
    doc["abort_reason"] = reason
    doc["abort_ts"] = time.time()
    try:
        return bool(coord.put_if_leader(
            constants.SERVICE_LEADER, constants.LEADER_SERVER,
            leader_value, [(_intent_full_key(coord), json.dumps(doc))]))
    except errors.NotLeaderError:
        return False


def write_ack(coord, who, intent_id, ok, reason=None, info=None):
    """A survivor's vote on the prepare intent (permanent key; the
    intent id scopes it, so stale acks from a previous resize are
    ignored by :func:`read_acks`)."""
    doc = {"id": str(intent_id), "who": str(who), "ok": bool(ok),
           "reason": reason, "ts": time.time()}
    if info:
        doc.update(info)
    coord.set_server_permanent(constants.SERVICE_LIVE_RESIZE,
                               "ack_%s" % who, json.dumps(doc))


def read_acks(coord, intent_id):
    """{who: ack doc} for acks scoped to ``intent_id``."""
    out = {}
    for name, value in coord.get_service(constants.SERVICE_LIVE_RESIZE):
        if not name.startswith("ack_"):
            continue
        try:
            doc = json.loads(value)
        except ValueError:
            continue
        if doc.get("id") == str(intent_id):
            out[doc.get("who") or name[len("ack_"):]] = doc
    return out


def advertise_capability(coord, who, info=None, ttl=None):
    """TTL-leased ``ready_<who>`` key: "this participant can reshape in
    place". Returns the Register (caller stops it on close); None when
    the store is unreachable (best-effort — losing the key only costs
    the live path, never correctness)."""
    from edl_tpu.controller.register import Register
    value = json.dumps(dict(info or {}, who=str(who)))
    try:
        return Register(coord, constants.SERVICE_LIVE_RESIZE,
                        "ready_%s" % who, value,
                        ttl=ttl or constants.ETCD_TTL)
    except errors.EdlError as e:
        logger.warning("live resize: capability advertise failed (%r)", e)
        return None


def ready_participants(coord):
    """Set of ``who`` with a live ``ready_*`` capability key."""
    out = set()
    try:
        for name, _ in coord.get_service(constants.SERVICE_LIVE_RESIZE):
            if name.startswith("ready_"):
                out.add(name[len("ready_"):])
    except errors.EdlError:
        pass
    return out


def wait_for_acks(coord, intent, timeout, poll=0.1):
    """Block until every survivor acked (any verdict) or the deadline
    passes. Returns (all_ok, {who: ack})."""
    want = set(intent.get("survivors") or ())
    t_end = time.monotonic() + float(timeout)
    acks = {}
    while time.monotonic() < t_end:
        acks = read_acks(coord, intent["id"])
        if want.issubset(acks):
            return all(a.get("ok") for a in acks.values()), acks
        time.sleep(poll)
    return False, acks


class LiveResizeWatcher(object):
    """Trainer-side intent watcher: a store watch on SERVICE_LIVE_RESIZE
    keeps a pending prepare intent addressed to ``who``; the training
    loop polls :meth:`pending` at step boundaries (a lock + dict read —
    nothing on the hot path) and calls :meth:`done` after acking."""

    def __init__(self, coord, who):
        import threading
        self._coord = coord
        self._who = str(who)
        self._lock = threading.Lock()
        self._pending = None
        self._handled = set()
        self._watcher = coord.watch_service(constants.SERVICE_LIVE_RESIZE,
                                            self._on_change)
        # the watch delivers future changes; pick up a pre-existing one
        self._consider(read_intent(coord))

    def _on_change(self, added, removed, all_servers):
        raw = (all_servers or {}).get(INTENT_KEY)
        if raw is None:
            return
        try:
            self._consider(json.loads(raw))
        except ValueError:
            pass

    def _consider(self, rec):
        if (not rec or rec.get("phase") != PREPARE
                or self._who not in (rec.get("survivors") or ())
                or rec.get("id") in self._handled
                or intent_expired(rec)):
            return
        with self._lock:
            self._pending = rec

    def pending(self):
        with self._lock:
            rec = self._pending
        if rec is not None and intent_expired(rec):
            self.done(rec.get("id"))
            return None
        return rec

    def done(self, intent_id):
        with self._lock:
            self._handled.add(intent_id)
            if self._pending and self._pending.get("id") == intent_id:
                self._pending = None

    def stop(self):
        try:
            self._watcher.stop()
        except Exception:
            pass


# -- the reshard engine ----------------------------------------------------


def _leaf_spec(x):
    import jax
    a = x if hasattr(x, "shape") and hasattr(x, "dtype") else np.asarray(x)
    return jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)


def reshard_placed(tree, shardings, coord=None, ckpt=None, version=None,
                   self_endpoint=None, timeout=20.0):
    """Reshard a live pytree onto new shardings IN PLACE of a restore,
    walking the recovery ladder: paste locally-held spans straight
    from the device arrays (no wire, no disk), fill the rest by peer
    range-reads at the committed ``version``, decode spans no live
    peer serves from the redundancy tier's parity shards
    (runtime/redundancy.py — zero FS reads even when pods died), then
    the per-span FS fallback as the cold layer. Returns
    (new_tree, stats) where stats = {"source", "local_bytes",
    "peer_bytes", "parity_bytes", "fs_keys", "peers"}.

    Raises MissingKeysError when spans remain uncovered — the caller
    rolls back to the old mesh and the stop-resume ladder takes over.
    """
    import jax
    from edl_tpu.runtime.checkpoint import (PlacedTarget, _concrete_spans,
                                            _path_key)

    target = jax.tree_util.tree_map(_leaf_spec, tree)
    pt = PlacedTarget(target, shardings)
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    local_bytes = 0
    for path, leaf in flat:
        key = _path_key(path)
        if key not in pt.need:
            continue
        if hasattr(leaf, "addressable_shards") and hasattr(leaf,
                                                           "sharding"):
            seen = set()
            for s in leaf.addressable_shards:
                spans = _concrete_spans(s.index, leaf.shape)
                if spans in seen:
                    continue
                seen.add(spans)
                if not pt.overlaps_local(key, spans):
                    continue
                arr = np.asarray(s.data)
                pt.paste(key, spans, arr)
                local_bytes += arr.nbytes
        else:
            arr = np.asarray(leaf)
            spans = tuple((0, d) for d in arr.shape)
            if pt.overlaps_local(key, spans):
                pt.paste(key, spans, arr)
                local_bytes += arr.nbytes

    stats = {"source": "local", "local_bytes": int(local_bytes),
             "peer_bytes": 0, "parity_bytes": 0, "fs_keys": [],
             "peers": 0}
    missing = pt.missing()
    if missing and coord is not None and version is not None:
        from edl_tpu.runtime.state_server import PeerRestorer
        try:
            peer_stats = PeerRestorer(
                coord, ckpt, self_endpoint=self_endpoint,
                timeout=timeout).fill_from_peers(version, pt)
            stats["source"] = "local+peer"
            stats["peer_bytes"] = peer_stats["peer_bytes"]
            stats["peers"] = peer_stats["peers"]
        except errors.PeerRestoreError as e:
            logger.info("live reshard: no peer path (%s); trying the "
                        "parity rung", e)
        missing = pt.missing()
    if missing and coord is not None and version is not None:
        # parity rung: spans only dead pods held decode from the
        # erasure-coded shards survivors hold — still zero FS reads
        from edl_tpu.runtime import redundancy
        if redundancy.enabled():
            try:
                par = redundancy.fill_from_parity(
                    coord, version, pt, self_endpoint=self_endpoint,
                    timeout=timeout)
                if par["owners"]:
                    stats["source"] += "+parity"
                    stats["parity_bytes"] = par["parity_bytes"]
            except errors.EdlError as e:
                logger.info("live reshard: parity rung unavailable "
                            "(%s); trying the FS fallback", e)
            missing = pt.missing()
    if missing and ckpt is not None and version is not None:
        for key in missing:
            pt.reset_key(key)
        ckpt.fill_placed_from_fs(version, pt, keys=missing)
        stats["source"] += "+fs"
        stats["fs_keys"] = sorted(missing)
    from edl_tpu.runtime.checkpoint import MissingKeysError
    missing = pt.missing()
    if missing:
        raise MissingKeysError(missing)
    return pt.assemble(), stats
