"""Filesystem abstraction behind checkpointing.

Reference parity: the LocalFS/BDFS(HDFS) wrapper Paddle Fleet used for
checkpoints (example/collective/resnet50/train_with_fleet.py:422-424). The
TPU equivalent targets POSIX (NFS/local) and GCS; GCS has no atomic rename,
so the checkpoint layer commits via manifest-last writes instead of relying
on rename (SURVEY.md §7 "hard parts").
"""

import os
import shutil


class FileSystem(object):
    def exists(self, path):
        raise NotImplementedError

    def makedirs(self, path):
        raise NotImplementedError

    def open(self, path, mode):
        raise NotImplementedError

    def listdir(self, path):
        raise NotImplementedError

    def delete_tree(self, path):
        raise NotImplementedError

    def rename(self, src, dst):
        raise NotImplementedError


class LocalFS(FileSystem):
    def exists(self, path):
        return os.path.exists(path)

    def makedirs(self, path):
        os.makedirs(path, exist_ok=True)

    def open(self, path, mode):
        return open(path, mode)

    def listdir(self, path):
        try:
            return sorted(os.listdir(path))
        except FileNotFoundError:
            return []

    def delete_tree(self, path):
        shutil.rmtree(path, ignore_errors=True)

    def rename(self, src, dst):
        os.replace(src, dst)


class GCSFS(FileSystem):
    """Placeholder for a GCS backend (no egress in this environment).

    The checkpoint layer only needs exists/open/listdir/delete/makedirs —
    all expressible over the GCS JSON API; commits are already manifest-last
    so no rename primitive is required.
    """

    def __init__(self, *a, **k):
        raise NotImplementedError(
            "GCS backend requires google-cloud-storage; use LocalFS on a "
            "shared mount, or add the dependency in your deployment image")


def get_fs(path):
    if str(path).startswith("gs://"):
        return GCSFS()
    return LocalFS()
