"""Filesystem abstraction behind checkpointing.

Reference parity: the LocalFS/BDFS(HDFS) wrapper Paddle Fleet used for
checkpoints (example/collective/resnet50/train_with_fleet.py:422-424). The
TPU equivalent targets POSIX (NFS/local) and GCS; GCS has no atomic rename,
so the checkpoint layer commits via manifest-last writes instead of relying
on rename (SURVEY.md §7 "hard parts").

GCSFS speaks the GCS JSON API directly over urllib (no google-cloud-storage
dependency): point it at a real endpoint with auth via a bearer token, or
at any GCS emulator via STORAGE_EMULATOR_HOST (the in-tree one lives in
edl_tpu/tools/fake_gcs.py).
"""

import io
import json
import os
import shutil
import urllib.error
import urllib.parse
import urllib.request
import zlib


class FileSystem(object):
    def exists(self, path):
        raise NotImplementedError

    def makedirs(self, path):
        raise NotImplementedError

    def open(self, path, mode):
        raise NotImplementedError

    def write_chunks(self, path, chunks):
        """Stream an iterable of byte chunks to ``path``, computing
        zlib.crc32 incrementally; returns (nbytes, crc). The streaming
        write primitive of the async checkpoint engine — backends that
        can pipeline (resumable uploads, O_DIRECT) override this; the
        default rides open()."""
        nbytes = 0
        crc = 0
        with self.open(path, "wb") as f:
            for chunk in chunks:
                f.write(chunk)
                crc = zlib.crc32(chunk, crc)
                nbytes += len(chunk)
        return nbytes, crc

    def read_range(self, path, offset, length):
        """Read ``length`` bytes starting at ``offset``. Reads past EOF
        return the available suffix (may be shorter than ``length``);
        an offset at/past EOF returns b"". The random-access primitive
        behind placed restores: a process that owns one device block of
        a leaf pulls just that byte span instead of the whole file."""
        raise NotImplementedError

    def listdir(self, path):
        raise NotImplementedError

    def delete_tree(self, path):
        raise NotImplementedError

    def delete(self, path):
        """Delete a single file; missing files are not an error."""
        raise NotImplementedError

    def rename(self, src, dst):
        raise NotImplementedError


class LocalFS(FileSystem):
    def exists(self, path):
        return os.path.exists(path)

    def makedirs(self, path):
        os.makedirs(path, exist_ok=True)

    def open(self, path, mode):
        return open(path, mode)

    def read_range(self, path, offset, length):
        if length <= 0:
            return b""
        with open(path, "rb") as f:
            f.seek(offset)
            return f.read(length)

    def listdir(self, path):
        try:
            return sorted(os.listdir(path))
        except FileNotFoundError:
            return []

    def delete_tree(self, path):
        shutil.rmtree(path, ignore_errors=True)

    def delete(self, path):
        try:
            os.remove(path)
        except FileNotFoundError:
            pass

    def rename(self, src, dst):
        os.replace(src, dst)


def _split_gs(path):
    """gs://bucket/a/b -> (bucket, "a/b")."""
    if not str(path).startswith("gs://"):
        raise ValueError("not a gs:// path: %r" % (path,))
    rest = str(path)[len("gs://"):]
    bucket, _, obj = rest.partition("/")
    return bucket, obj.strip("/")


class _GCSWriter(io.BytesIO):
    """Buffers locally; uploads the object on close (GCS objects are
    immutable blobs — there is no partial append)."""

    def __init__(self, fs, bucket, name):
        super().__init__()
        self._fs, self._bucket, self._name = fs, bucket, name
        self._closed_once = False

    def close(self):
        if not self._closed_once:
            self._closed_once = True
            self._fs._upload(self._bucket, self._name, self.getvalue())
        super().close()


class GCSFS(FileSystem):
    """GCS over the JSON API: flat object namespace, no rename — the
    checkpoint layer's manifest-last commit is designed for exactly this
    (a version is valid iff its MANIFEST object exists).

    endpoint: emulator/base URL; defaults to $STORAGE_EMULATOR_HOST or the
    public GCS endpoint. token: OAuth bearer for real GCS (emulators need
    none).
    """

    def __init__(self, endpoint=None, token=None, timeout=30.0):
        self._base = (endpoint or os.environ.get("STORAGE_EMULATOR_HOST")
                      or "https://storage.googleapis.com").rstrip("/")
        self._token = token
        self._timeout = timeout

    # -- http plumbing ----------------------------------------------------

    def _request(self, method, url, data=None, ctype=None, headers=None):
        req = urllib.request.Request(url, data=data, method=method)
        if ctype:
            req.add_header("Content-Type", ctype)
        for k, v in (headers or {}).items():
            req.add_header(k, v)
        if self._token:
            req.add_header("Authorization", "Bearer %s" % self._token)
        return urllib.request.urlopen(req, timeout=self._timeout)

    def _obj_url(self, bucket, name, **params):
        url = "%s/storage/v1/b/%s/o/%s" % (
            self._base, urllib.parse.quote(bucket, safe=""),
            urllib.parse.quote(name, safe=""))
        if params:
            url += "?" + urllib.parse.urlencode(params)
        return url

    def _upload(self, bucket, name, data):
        url = "%s/upload/storage/v1/b/%s/o?%s" % (
            self._base, urllib.parse.quote(bucket, safe=""),
            urllib.parse.urlencode({"uploadType": "media", "name": name}))
        with self._request("POST", url, data=data,
                           ctype="application/octet-stream") as resp:
            resp.read()

    def _download(self, bucket, name):
        with self._request("GET", self._obj_url(bucket, name,
                                                alt="media")) as resp:
            return resp.read()

    def _download_range(self, bucket, name, offset, length):
        rng = "bytes=%d-%d" % (offset, offset + length - 1)
        try:
            with self._request("GET", self._obj_url(bucket, name,
                                                    alt="media"),
                               headers={"Range": rng}) as resp:
                data = resp.read()
                if resp.status == 206:
                    return data
        except urllib.error.HTTPError as e:
            if e.code == 416:  # offset at/past EOF
                return b""
            raise
        # a server that ignores Range answers 200 with the full object
        return data[offset:offset + length]

    def _list(self, bucket, prefix, delimiter=None):
        params = {"prefix": prefix}
        if delimiter:
            params["delimiter"] = delimiter
        url = "%s/storage/v1/b/%s/o?%s" % (
            self._base, urllib.parse.quote(bucket, safe=""),
            urllib.parse.urlencode(params))
        with self._request("GET", url) as resp:
            out = json.loads(resp.read().decode())
        return ([it["name"] for it in out.get("items", [])],
                out.get("prefixes", []))

    # -- FileSystem API ---------------------------------------------------

    def exists(self, path):
        bucket, obj = _split_gs(path)
        if not obj:
            return True
        try:
            with self._request("GET", self._obj_url(bucket, obj)) as resp:
                resp.read()
            return True
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
        # "directory": any object under the prefix
        items, prefixes = self._list(bucket, obj + "/", delimiter="/")
        return bool(items or prefixes)

    def makedirs(self, path):
        pass  # GCS has no directories

    def open(self, path, mode):
        bucket, obj = _split_gs(path)
        if "w" in mode:
            raw = _GCSWriter(self, bucket, obj)
            return raw if "b" in mode else io.TextIOWrapper(raw)
        try:
            data = self._download(bucket, obj)
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise FileNotFoundError(path)
            raise
        return (io.BytesIO(data) if "b" in mode
                else io.StringIO(data.decode()))

    def read_range(self, path, offset, length):
        if length <= 0:
            return b""
        bucket, obj = _split_gs(path)
        try:
            return self._download_range(bucket, obj, offset, length)
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise FileNotFoundError(path)
            raise

    def listdir(self, path):
        bucket, obj = _split_gs(path)
        prefix = obj + "/" if obj else ""
        items, prefixes = self._list(bucket, prefix, delimiter="/")
        names = [n[len(prefix):] for n in items]
        names += [p[len(prefix):].rstrip("/") for p in prefixes]
        return sorted(n for n in names if n)

    def delete_tree(self, path):
        bucket, obj = _split_gs(path)
        items, _ = self._list(bucket, obj + "/" if obj else "")
        for name in items + [obj]:
            try:
                with self._request(
                        "DELETE", self._obj_url(bucket, name)) as resp:
                    resp.read()
            except urllib.error.HTTPError as e:
                if e.code != 404:
                    raise

    def delete(self, path):
        bucket, obj = _split_gs(path)
        try:
            with self._request(
                    "DELETE", self._obj_url(bucket, obj)) as resp:
                resp.read()
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise

    def rename(self, src, dst):
        raise NotImplementedError(
            "GCS has no atomic rename; the checkpoint layer commits "
            "manifest-last and never calls rename on object stores")


def get_fs(path):
    if str(path).startswith("gs://"):
        return GCSFS()
    return LocalFS()
