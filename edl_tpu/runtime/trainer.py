"""The elastic JAX trainer harness — the in-tree replacement for what the
reference delegated to Paddle Fleet (SURVEY.md §2.6, §3.2): distributed
init, device mesh, pjit train step with gradient reduction over the mesh,
checkpoint save/restore, and train-status reporting to the control plane.

Design (TPU-first):
- one process per host (the JAX process model); `jax.distributed.initialize`
  wires processes using the launcher's env contract (coordinator = rank-0
  trainer endpoint) — there is no NCCL-style rendezvous to manage;
- params/opt state replicated, batch sharded over the `dp` mesh axis; the
  backward-pass gradient all-reduce is inserted by XLA from the sharding
  annotations (no hand-written psum for plain DP; shard_map paths live in
  edl_tpu.parallel for tp/sp);
- stop-resume elasticity: the launcher restarts this process on membership
  change; `resume()` restores the newest valid checkpoint and the State's
  adjust hooks re-tune hyperparameters for the new world size.
"""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from edl_tpu.controller import train_status as train_status_mod
from edl_tpu.controller.env import TrainerEnv
from edl_tpu.coordination.client import CoordClient
from edl_tpu.obs import events as obs_events
from edl_tpu.obs import flight as obs_flight
from edl_tpu.obs import ledger as obs_ledger
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.robustness import faults
from edl_tpu.runtime import checkpoint as checkpoint_mod
from edl_tpu.runtime import state as state_mod
from edl_tpu.runtime.checkpoint import CheckpointManager, MissingKeysError
from edl_tpu.runtime.mesh import DATA_AXIS, data_sharding, make_mesh
from edl_tpu.utils.logger import logger

_STEP_MS = obs_metrics.histogram(
    "edl_train_step_ms", "train_step wall time (host dispatch)")
# prewarm effectiveness: job_doctor names a cold compile cache from
# these (a first step in prewarm scope either loaded an AOT executable
# or paid a full XLA compile)
_PREWARM_HITS = obs_metrics.counter(
    "edl_resize_prewarm_hits_total",
    "first steps that loaded a prewarmed AOT step executable")
_PREWARM_MISSES = obs_metrics.counter(
    "edl_resize_prewarm_misses_total",
    "first steps in prewarm scope with no usable AOT artifact "
    "(full compile paid)")

_distributed_initialized = False


def make_train_state(params, tx, extra_state=None):
    """The canonical train-state pytree shared by ElasticTrainer, bench.py
    and the driver dry-run."""
    return {
        "params": params,
        "opt_state": tx.init(params),
        "step": jnp.zeros((), jnp.int32),
        "extra": extra_state if extra_state is not None else {},
    }


# named activation-recompute policies for make_train_step/ElasticTrainer;
# per-LAYER recompute (the big lever) is the models' own `remat` flag —
# these whole-loss policies tune what the fwd/bwd boundary may save
_REMAT_POLICIES = {
    "full": lambda: None,  # jax.checkpoint default: save nothing
    "dots": lambda: jax.checkpoint_policies.checkpoint_dots,
    "dots_no_batch":
        lambda: jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
}


def _remat_wrapper(remat_policy):
    """Validate ``remat_policy`` eagerly and return the loss wrapper
    (identity for None) — shared by make_train_step/make_accum_step."""
    if remat_policy is not None and remat_policy not in _REMAT_POLICIES:
        raise ValueError("remat_policy %r not in %s"
                         % (remat_policy, sorted(_REMAT_POLICIES)))

    def wrap(fn):
        if remat_policy is None:
            return fn
        return jax.checkpoint(fn, policy=_REMAT_POLICIES[remat_policy]())

    return wrap


def make_train_step(loss_fn, tx, has_aux=False, remat_policy=None):
    """Build the canonical SGD step over a make_train_state pytree.

    loss_fn: (params, batch, rng) -> loss, or with has_aux
    (params, extra, batch, rng) -> (loss, new_extra). Returns
    step(train_state, batch, rng) -> (train_state, loss), jit-ready.

    remat_policy: None or one of "full"|"dots"|"dots_no_batch" — wraps the
    loss in jax.checkpoint with the named policy (activation recompute;
    reference knob train_with_fleet.py:322-325). Combine with the models'
    own per-layer ``remat`` flag for layer-boundary-only memory."""
    _maybe_remat = _remat_wrapper(remat_policy)

    def step(train_state, batch, rng):
        if has_aux:
            @_maybe_remat
            def compute(params):
                return loss_fn(params, train_state["extra"], batch, rng)
            (loss, extra), grads = jax.value_and_grad(
                compute, has_aux=True)(train_state["params"])
        else:
            @_maybe_remat
            def compute(params):
                return loss_fn(params, batch, rng)
            loss, grads = jax.value_and_grad(compute)(train_state["params"])
            extra = train_state["extra"]
        updates, opt_state = tx.update(grads, train_state["opt_state"],
                                       train_state["params"])
        params = optax.apply_updates(train_state["params"], updates)
        return {
            "params": params,
            "opt_state": opt_state,
            "step": train_state["step"] + 1,
            "extra": extra,
        }, loss

    return step


def make_multi_step(loss_fn, tx, steps_per_call, has_aux=False,
                    remat_policy=None):
    """A lax.scan over ``steps_per_call`` canonical train steps in ONE
    dispatch: step(train_state, batches, rng) -> (train_state, losses)
    where every leaf of ``batches`` has a leading [steps_per_call] axis
    and losses is [steps_per_call].

    Amortizes per-step host dispatch latency — the lever when the host
    is remote or slow relative to the device (dev tunnels, small step
    times). The rng is folded with the in-scan step counter so each
    scanned step sees a distinct stream, exactly as if single steps were
    dispatched with rng = fold_in(rng, state["step"])."""
    if steps_per_call < 1:
        raise ValueError("steps_per_call must be >= 1")
    base = make_train_step(loss_fn, tx, has_aux=has_aux,
                           remat_policy=remat_policy)

    def step(train_state, batches, rng):
        def body(state, batch):
            state2, loss = base(
                state, batch, jax.random.fold_in(rng, state["step"]))
            return state2, loss
        return lax.scan(body, train_state, batches,
                        length=steps_per_call)

    return step


def make_accum_step(loss_fn, tx, accum_steps, has_aux=False,
                    remat_policy=None, overlap_axis=None, mesh=None):
    """Gradient accumulation: ONE optimizer update from ``accum_steps``
    microbatches, scanned in one dispatch.

    step(train_state, batches, rng) -> (train_state, loss) where every
    leaf of ``batches`` has a leading [accum_steps] axis (microbatch-
    major) and loss is the mean microbatch loss. Gradients are averaged
    over microbatches — for a mean-reduced loss this equals the full-
    batch gradient, so the update is independent of ``accum_steps`` (up
    to fp roundoff); ``extra`` state (e.g. BatchNorm running stats)
    chains through the microbatches sequentially, exactly as if they
    were separate steps.

    The elastic lever: on a scale-down the per-chip batch must absorb
    total_batch_size/world more rows; instead of growing activation
    memory, raise ``grad_accum`` — the global batch per UPDATE (and so
    convergence behavior) is unchanged across the resize. The reference
    kept global batch constant by resharding rows only
    (train_with_fleet.py:360-361); accumulation extends that policy past
    the per-device memory ceiling. The rng is folded per microbatch so
    dropout streams differ across microbatches.

    Collective–compute overlap (``overlap_axis``/``mesh``): with a data
    axis named, the step runs under shard_map over that axis and the
    gradient all-reduce for microbatch *i* is DELAYED into the scan
    carry — issued at the top of iteration *i+1*, where it has no data
    dependence on that iteration's fwd/bwd, so XLA schedules the pmean
    (one collective per leaf — naturally bucketed) behind the compute.
    The last microbatch's reduce runs after the scan. When the axis has
    size 1 (or ``mesh`` is None) there are no collectives to hide, so
    the EAGER step is returned unchanged and the no-op is logged —
    clean degradation (bitwise-identical updates by construction, and
    no 2x gradient carry), not an error. Incompatible with
    ``has_aux`` (per-shard extra state has no defined reduction), and
    the loss's rng stream is shared across shards (fine for rng-free or
    row-independent losses; dropout masks would repeat per shard)."""
    if accum_steps < 1:
        raise ValueError("accum_steps must be >= 1")
    _maybe_remat = _remat_wrapper(remat_policy)

    if overlap_axis is not None:
        if has_aux:
            raise ValueError(
                "overlap_axis is incompatible with has_aux: extra "
                "state is per-shard under shard_map and has no defined "
                "reduction")
        axes = ((overlap_axis,) if isinstance(overlap_axis, str)
                else tuple(overlap_axis))
        axis_size = 1
        if mesh is not None:
            axis_size = int(np.prod([mesh.shape[a] for a in axes
                                     if a in mesh.shape]))
        if mesh is not None and axis_size > 1:
            return _make_overlap_accum_step(loss_fn, tx, accum_steps,
                                            _maybe_remat, axes, mesh)
        logger.info(
            "make_accum_step: dp overlap over %s is a no-op (axis size "
            "%d) — no collectives to hide, returning the eager "
            "accumulation step unchanged", axes, axis_size)
        # fall through to the eager step below

    def step(train_state, batches, rng):
        params = train_state["params"]

        def body(carry, xs):
            extra, grad_acc, loss_acc = carry
            i, batch = xs
            rng_i = jax.random.fold_in(rng, i)
            if has_aux:
                @_maybe_remat
                def compute(p):
                    return loss_fn(p, extra, batch, rng_i)
                (loss, new_extra), grads = jax.value_and_grad(
                    compute, has_aux=True)(params)
            else:
                @_maybe_remat
                def compute(p):
                    return loss_fn(p, batch, rng_i)
                loss, grads = jax.value_and_grad(compute)(params)
                new_extra = extra
            grad_acc = jax.tree_util.tree_map(jnp.add, grad_acc, grads)
            return (new_extra, grad_acc, loss_acc + loss), None

        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        (extra, grad_sum, loss_sum), _ = lax.scan(
            body, (train_state["extra"], zeros, jnp.zeros((), jnp.float32)),
            (jnp.arange(accum_steps), batches), length=accum_steps)
        grads = jax.tree_util.tree_map(lambda g: g / accum_steps, grad_sum)
        updates, opt_state = tx.update(grads, train_state["opt_state"],
                                       params)
        return {
            "params": optax.apply_updates(params, updates),
            "opt_state": opt_state,
            "step": train_state["step"] + 1,
            "extra": extra,
        }, loss_sum / accum_steps

    return step


def _make_overlap_accum_step(loss_fn, tx, accum_steps, _maybe_remat,
                             axes, mesh):
    """The delayed-reduction accumulation schedule (see make_accum_step's
    overlap paragraph). Only built when the overlap axes have size > 1 —
    the degenerate case returns the eager step from make_accum_step —
    and split out so the eager path stays byte-for-byte what it was."""

    def _fold(reduced, pending):
        pending = jax.tree_util.tree_map(
            lambda g: lax.pmean(g, axes), pending)
        return jax.tree_util.tree_map(jnp.add, reduced, pending)

    def step(train_state, batches, rng):
        params = train_state["params"]

        def body(carry, xs):
            reduced, pending, loss_acc = carry
            i, batch = xs
            # fold the PREVIOUS microbatch's unreduced grads into the
            # running sum before this microbatch's fwd/bwd: the pmean
            # has no data dependence on the compute below, so XLA
            # overlaps the wire time with it
            reduced = _fold(reduced, pending)
            rng_i = jax.random.fold_in(rng, i)

            @_maybe_remat
            def compute(p):
                return loss_fn(p, batch, rng_i)
            loss, grads = jax.value_and_grad(compute)(params)
            return (reduced, grads, loss_acc + loss), None

        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        (reduced, pending, loss_sum), _ = lax.scan(
            body,
            (zeros, jax.tree_util.tree_map(jnp.zeros_like, params),
             jnp.zeros((), jnp.float32)),
            (jnp.arange(accum_steps), batches), length=accum_steps)
        grad_sum = _fold(reduced, pending)  # the last microbatch's reduce
        grads = jax.tree_util.tree_map(lambda g: g / accum_steps,
                                       grad_sum)
        loss = lax.pmean(loss_sum / accum_steps, axes)
        updates, opt_state = tx.update(grads, train_state["opt_state"],
                                       params)
        return {
            "params": optax.apply_updates(params, updates),
            "opt_state": opt_state,
            "step": train_state["step"] + 1,
            "extra": train_state["extra"],
        }, loss

    from jax.sharding import PartitionSpec
    from edl_tpu.parallel.shard_map_compat import shard_map
    state_spec = PartitionSpec()
    batch_spec = PartitionSpec(None, axes)
    return shard_map(step, mesh=mesh,
                     in_specs=(state_spec, batch_spec, state_spec),
                     out_specs=(state_spec, state_spec),
                     check_rep=False)


def auto_grad_accum(per_device_batch, max_per_device_batch):
    """Smallest microbatch count k (dividing ``per_device_batch``) whose
    per-device microbatch fits ``max_per_device_batch``.

    The elastic memory policy: state the per-device activation budget
    once; each stop-resume restart computes the accumulation that keeps
    total_batch_size (and so convergence) constant at the new world
    size. k = per_device_batch is always feasible (microbatch 1)."""
    if max_per_device_batch <= 0:
        raise ValueError("max_per_device_batch must be positive")
    if per_device_batch < 1:
        raise ValueError("per_device_batch must be >= 1")
    for k in range(1, per_device_batch + 1):
        if per_device_batch % k == 0 \
                and per_device_batch // k <= max_per_device_batch:
            return k
    raise AssertionError("unreachable: k == per_device_batch always fits")


def enable_compilation_cache():
    """Persistent XLA compilation cache, keyed by program (incl. mesh
    shape). Cuts stop-resume resize recovery to O(restart) when the new
    world size was seen before (SURVEY.md §7 'resize vs XLA reality') —
    set EDL_TPU_COMPILE_CACHE to a shared directory to activate."""
    cache_dir = os.environ.get("EDL_TPU_COMPILE_CACHE")
    if cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        logger.info("compilation cache at %s", cache_dir)


def maybe_init_distributed(env=None):
    """Initialize jax.distributed from the launcher env contract (no-op for
    single-process runs)."""
    global _distributed_initialized
    env = env or TrainerEnv()
    enable_compilation_cache()
    if _distributed_initialized or env.world_size <= 1:
        return env
    # idempotent with external bootstrap (a test rig or launcher that
    # already called jax.distributed.initialize)
    state = getattr(getattr(jax, "_src", None), "distributed", None)
    if state is not None and getattr(getattr(state, "global_state", None),
                                     "client", None) is not None:
        _distributed_initialized = True
        return env
    jax.distributed.initialize(
        coordinator_address=env.coordinator,
        num_processes=env.world_size,
        process_id=env.global_rank)
    _distributed_initialized = True
    logger.info("jax.distributed up: process %d/%d coordinator=%s",
                env.global_rank, env.world_size, env.coordinator)
    return env


class ElasticTrainer(object):
    """Data-parallel elastic trainer.

    Args:
      loss_fn: (params, batch, rng) -> scalar loss, or with has_aux=True
        (params, extra, batch, rng) -> (loss, new_extra) where ``extra`` is
        non-differentiated model state updated each step (e.g. BatchNorm
        running stats) — kept inside the donated train_state.
      params: initial parameter pytree.
      tx: an optax GradientTransformation.
      total_batch_size: GLOBAL batch size; kept constant across resizes
        (per-host batch = total / world) per the reference's policy
        (train_with_fleet.py:360-361, edl_collective_design_doc.md:14-17).
      checkpoint_dir: shared directory for elastic resume ('' disables).
      mesh: optional prebuilt Mesh (default: 1-D dp mesh over all devices).
      grad_accum: microbatches accumulated per optimizer update
        (make_accum_step); total_batch_size stays the per-UPDATE global
        batch, so raising grad_accum after a scale-down keeps both the
        update size and the per-chip activation memory constant.
      zero1: ZeRO-1 / weight-update sharding — optimizer moments sharded
        over the dp axis (composes with tensor-parallel param_shardings);
        XLA turns the grad all-reduce + update into reduce-scatter +
        sharded update + param all-gather. 1/dp the optimizer memory at
        unchanged wire bytes.
      max_per_device_batch: declarative alternative to grad_accum — a
        per-device batch budget; each restart picks the smallest
        accumulation that fits it at the current world size
        (auto_grad_accum).
      step_fn: a custom train step (train_state, batch, rng) ->
        (train_state, loss) replacing the canonical make_train_step —
        the hook that puts engines owning their own backward (the 1F1B
        pipeline's pipeline_value_and_grad) inside the elastic harness:
        checkpoint/resume, preemption, sharded saves and placed
        restores all apply to the custom step's state. Mutually
        exclusive with the loss-level knobs (has_aux / grad_accum /
        remat_policy / max_per_device_batch); pass param_shardings
        (e.g. stages over "pp") for the layout, and build the step with
        the SAME ``tx`` object passed here (it initializes the
        opt_state the step updates).
      dp_overlap: with grad_accum > 1, run the delayed-reduction
        accumulation schedule (make_accum_step's overlap path): the
        gradient all-reduce for microbatch i overlaps microbatch i+1's
        fwd/bwd. Plain-DP only (replicated params/opt state — no zero1
        or param_shardings, whose leaf-wise shard_map specs this path
        does not build) and no has_aux. On a 1-device data axis there
        are no collectives to hide, so the eager accumulation step runs
        unchanged (logged no-op). At
        grad_accum == 1 there is no cross-microbatch edge to hide the
        reduce behind, so the knob is ignored (logged).
    """

    def __init__(self, loss_fn, params, tx, total_batch_size,
                 checkpoint_dir=None, mesh=None, env=None, coord=None,
                 keep_checkpoints=3, extra_state=None, has_aux=False,
                 async_save=False, remat_policy=None,
                 param_shardings=None, grad_accum=1, zero1=False,
                 max_per_device_batch=None, step_fn=None,
                 dp_overlap=False):
        if step_fn is not None and (has_aux or grad_accum != 1
                                    or remat_policy is not None
                                    or max_per_device_batch is not None
                                    or dp_overlap):
            raise ValueError(
                "step_fn owns the whole step: has_aux/grad_accum/"
                "remat_policy/max_per_device_batch/dp_overlap do not "
                "apply")
        if dp_overlap and has_aux:
            raise ValueError("dp_overlap is incompatible with has_aux "
                             "(see make_accum_step)")
        if dp_overlap and (zero1 or param_shardings is not None):
            raise ValueError(
                "dp_overlap requires replicated params/opt state "
                "(plain DP): zero1/param_shardings shard the state, and "
                "the overlap shard_map only builds replicated specs")
        self._dp_overlap = dp_overlap
        self._step_fn = step_fn
        self.env = env or TrainerEnv()
        maybe_init_distributed(self.env)
        if checkpoint_dir is None:
            # default to the launcher-provided shared checkpoint path
            checkpoint_dir = self.env.checkpoint_path
        self.total_batch_size = total_batch_size
        # _bind_mesh consumes _grad_accum; bind at 1 first, rebind after
        # the accumulation is resolved (auto_grad_accum needs the
        # per-device batch the first binding computes)
        self._grad_accum = 1
        self._bind_mesh(mesh if mesh is not None else make_mesh())

        self._loss_fn = loss_fn
        self._tx = tx
        self._has_aux = has_aux
        self._remat_policy = remat_policy
        # gradient accumulation: total_batch_size stays the rows per
        # OPTIMIZER UPDATE; each update scans grad_accum microbatches
        # (see make_accum_step — the past-the-memory-ceiling elastic lever)
        if grad_accum < 1:
            raise ValueError("grad_accum must be >= 1")
        if max_per_device_batch is not None:
            if grad_accum != 1:
                raise ValueError(
                    "pass either grad_accum or max_per_device_batch, not "
                    "both — the budget exists to CHOOSE the accumulation")
            # the declarative form: a per-device batch budget instead of
            # an explicit k — recomputed per world size on every restart
            grad_accum = auto_grad_accum(self.per_device_batch,
                                         max_per_device_batch)
        if grad_accum > 1:
            # rebind: the batch sharding becomes microbatch-major and
            # the divisibility checks run against the accumulation
            self._grad_accum = grad_accum
            self._bind_mesh(self.mesh)
        if extra_state is not None:
            for leaf in jax.tree_util.tree_leaves(extra_state):
                # only explicit numpy 64-bit leaves are dangerous; Python
                # scalars are weak-typed to 32-bit with no real truncation
                if not isinstance(leaf, (np.ndarray, np.generic)):
                    continue
                dt = leaf.dtype
                if dt.kind in "iuf" and dt.itemsize == 8 \
                        and not jax.config.jax_enable_x64:
                    raise ValueError(
                        "extra_state leaf has 64-bit dtype %s which JAX "
                        "would silently truncate to 32-bit on device; keep "
                        "host-side metadata (file offsets, loader positions) "
                        "in trainer.state.user_defined instead" % dt)
        self.state = state_mod.State(total_batch_size=total_batch_size)

        # model parallelism: partition rules (regex, PartitionSpec) or an
        # explicit sharding pytree for the params; optimizer-state
        # shardings are derived by running tx.init under jit so moments
        # inherit their param's layout (net-new vs the reference: elastic
        # stop-resume composes with tp — SURVEY.md §2.7)
        if isinstance(param_shardings, (list, tuple)):
            from edl_tpu.parallel.sharding import shard_params
            params, param_shardings = shard_params(params, self.mesh,
                                                   param_shardings)
        if param_shardings is None and not zero1:
            self.train_state = make_train_state(params, tx, extra_state)
            self._state_shardings = jax.tree_util.tree_map(
                lambda _: self._repl, self.train_state)
        else:
            from edl_tpu.parallel.sharding import opt_state_shardings
            if param_shardings is None:
                # ZeRO-1 with replicated params: only the optimizer
                # state is dp-sharded (weight-update sharding)
                param_shardings = jax.tree_util.tree_map(
                    lambda _: self._repl, params)
            params = jax.device_put(params, param_shardings)
            # zero1 shards over the full data-replica set — (dcn, dp) on
            # hybrid meshes, matching the batch axes
            zero_axes = (self._batch_sharding_early.spec[0]
                         if self._batch_sharding_early.spec else DATA_AXIS)
            opt_shardings = opt_state_shardings(
                tx, params, param_shardings, self._repl,
                zero1_mesh=self.mesh if zero1 else None,
                zero1_axis=zero_axes or DATA_AXIS)
            # init the optimizer state DIRECTLY into its sharded layout —
            # never materialize the full replicated moments (the zero1
            # startup-peak would defeat the steady-state memory win)
            self.train_state = {
                "params": params,
                "opt_state": jax.jit(
                    tx.init, out_shardings=opt_shardings)(params),
                "step": jnp.zeros((), jnp.int32),
                "extra": extra_state if extra_state is not None else {},
            }
            self._state_shardings = jax.tree_util.tree_map(
                lambda _: self._repl, self.train_state)
            self._state_shardings["params"] = param_shardings
            self._state_shardings["opt_state"] = opt_shardings
        self.train_state = jax.device_put(self.train_state,
                                          self._state_shardings)

        self._ckpt = (CheckpointManager(checkpoint_dir,
                                        keep=keep_checkpoints)
                      if checkpoint_dir else None)
        if self._ckpt is not None and jax.process_index() == 0:
            # crashed-attempt garbage (incl. stale sharded-save STARTED
            # sentinels that would mis-order a later same-version save)
            try:
                self._ckpt.clean_uncommitted()
            except Exception:
                logger.exception("uncommitted-checkpoint cleanup failed")
        self.coord = coord
        if self.coord is None and self.env.under_launcher:
            self.coord = CoordClient(self.env.store_endpoints,
                                     root=self.env.job_id)

        # peer-served restore plane (runtime/state_server.py): serve the
        # latest committed snapshot to restarting peers and prefer live
        # peers over the shared FS on our own resume. Opt-out with
        # EDL_TPU_PEER_RESTORE=0; needs both a checkpoint dir (the FS
        # fallback) and a coordination store (peer discovery).
        self._state_server = None
        # per-incarnation resize timing record (docs/elastic_resize.md):
        # absolute unix timestamps so measure_resize can align them with
        # its own kill/detect clock. live_resize() replaces the record
        # (mode "live") without a process restart.
        self._resize_timing = {"t_construct": time.time(),
                               "mode": "stop_resume"}
        if (self._ckpt is not None and self.coord is not None
                and os.environ.get("EDL_TPU_PEER_RESTORE", "1") != "0"):
            try:
                from edl_tpu.runtime.state_server import StateServer
                self._state_server = StateServer(
                    rank=self.env.global_rank,
                    host=os.environ.get("EDL_TPU_POD_IP", "0.0.0.0"))
                self._state_server.advertise(self.coord)
                # diskless redundancy tier (runtime/redundancy.py):
                # accept partners' erasure-coded snapshot shards and
                # push our own on every commit, so a pod loss rebuilds
                # from survivors with zero FS reads. Kill switch:
                # EDL_TPU_REDUNDANCY=0.
                from edl_tpu.runtime import redundancy as redundancy_mod
                if redundancy_mod.enabled():
                    self._state_server.advertise_redundancy(
                        self.coord, key=str(self.env.global_rank))
            except Exception:
                logger.exception("state server failed to start; peer "
                                 "restore disabled for this process")
                self._state_server = None

        self._jit_step = self._build_step()
        self._example_batch_sds = None  # captured at the first step
        # the step that next stamps compile_s/first_step_s into
        # _resize_timing: the first step of this incarnation, and the
        # first step after every live_resize() (same record semantics
        # as a restart, without the restart)
        self._stamp_first_step = True
        # live-resize protocol state (enable_live_resize)
        self._live_watcher = None
        self._live_register = None
        self._live_who = None
        self._prewarm_thread = None
        self._step_times = []
        # start-to-start wall intervals (NOT in-call durations: jit
        # dispatch returns in ~ms while the real cadence includes data
        # loading and device time) — the preemption stop margin must be
        # computed from the true step rate
        self._step_intervals = []
        self._last_step_start = None
        # host-side mirror of the step counter: seeds default rngs without
        # forcing a device sync on the donated step array every step
        self._host_step = 0
        # version this incarnation resumed from (-1 = fresh start): an
        # emergency checkpoint at or below it belongs to a PRIOR
        # preemption event, not the one being waited on
        self._resumed_version = -1
        # env override so launchers/benches can flip the save engine
        # without threading a flag through every example's CLI
        env_async = os.environ.get("EDL_TPU_ASYNC_SAVE")
        if env_async is not None:
            async_save = env_async not in ("0", "")
        self._async_save = async_save
        # flag-only SIGTERM handler + drain hook: every preemption exit
        # path drains the checkpoint engine's in-flight async persist
        from edl_tpu.runtime.preemption import PreemptionGuard
        self._guard = PreemptionGuard(drain=self.wait_for_save)
        self._preempt_armed = False
        self._coord_stop = None
        self._preempt_t0 = None
        self._coord_deadline = 15.0
        # non-daemon writer + atexit join: process exit must not lose the
        # final checkpoint mid-write (manifest-last keeps partials
        # invisible, but losing the last epoch silently is a regression).
        # Registered ONCE, via weakref: the atexit registry must not pin
        # discarded trainers (and their device state) for the process
        # lifetime when several are constructed (restarts, notebooks).
        import atexit
        import weakref
        ref = weakref.ref(self)
        atexit.register(lambda: (lambda t: t and t.wait_for_save())(ref()))

    # -- mesh binding --------------------------------------------------------

    def _bind_mesh(self, mesh):
        """Bind every mesh-derived attribute: batch shardings, the
        per-device/per-host batch math, host row spans, the replicated
        sharding. Called at construction and again by live_resize()
        with the new world's mesh. Validates before assigning anything,
        so a ValueError leaves the previous binding intact."""
        total = self.total_batch_size
        early = data_sharding(mesh)
        # batch divisibility is over the BATCH-SHARDED axes (dcn, dp) —
        # with model axes (tp/sp/pp) in the mesh, rows are replicated
        # across them, not split
        n_batch_shards = 1
        spec0 = early.spec[0] if early.spec else None
        for ax in ((spec0,) if isinstance(spec0, str)
                   else tuple(spec0 or ())):
            n_batch_shards *= mesh.shape[ax]
        if total % n_batch_shards != 0:
            raise ValueError(
                "total_batch_size %d not divisible by %d batch shards"
                % (total, n_batch_shards))
        per_device = total // n_batch_shards
        # rows THIS process must supply = the union of its devices' batch
        # spans (with cross-process model axes a process can own every
        # row; with pure dp it owns a contiguous slice)
        idx_map = early.addressable_devices_indices_map((total,))
        spans = sorted({(sl[0].start or 0,
                         total if sl[0].stop is None else sl[0].stop)
                        for sl in idx_map.values()})
        per_host = sum(b - a for a, b in spans)
        if self._grad_accum > 1:
            if per_host % self._grad_accum != 0:
                raise ValueError(
                    "per-host batch %d not divisible by grad_accum %d"
                    % (per_host, self._grad_accum))
            if per_device % self._grad_accum != 0:
                raise ValueError(
                    "per-device batch %d not divisible by grad_accum %d"
                    % (per_device, self._grad_accum))
        self.mesh = mesh
        self._batch_sharding_early = early
        self.per_device_batch = per_device
        self._host_row_spans = spans
        self.per_host_batch = per_host
        self._repl = NamedSharding(mesh, P())
        if self._grad_accum > 1:
            # microbatch-major [k, rows/k, ...]: scan axis replicated,
            # rows sharded over the same data axes as the flat layout
            row_axes = early.spec[0] if early.spec else None
            self._batch_sharding = NamedSharding(mesh, P(None, row_axes))
        else:
            self._batch_sharding = early

    # -- the compiled step ---------------------------------------------------

    def _raw_step(self):
        """The un-jitted step callable (shared by _build_step and the
        resize-prewarm AOT compiles)."""
        if self._step_fn is not None:
            return self._step_fn
        if self._grad_accum > 1:
            overlap_axis = None
            if self._dp_overlap:
                # the row axes of the microbatch-major layout — "dp",
                # or ("dcn", "dp") on hybrid meshes
                overlap_axis = (self._batch_sharding.spec[1]
                                or DATA_AXIS)
            return make_accum_step(self._loss_fn, self._tx,
                                   self._grad_accum, self._has_aux,
                                   remat_policy=self._remat_policy,
                                   overlap_axis=overlap_axis,
                                   mesh=self.mesh if overlap_axis
                                   else None)
        if self._dp_overlap:
            logger.info("dp_overlap ignored: grad_accum == 1 leaves no "
                        "next microbatch to overlap the gradient "
                        "all-reduce with")
        return make_train_step(self._loss_fn, self._tx, self._has_aux,
                               remat_policy=self._remat_policy)

    def _build_step(self):
        return jax.jit(
            self._raw_step(),
            in_shardings=(self._state_shardings, self._batch_sharding,
                          self._repl),
            out_shardings=(self._state_shardings, self._repl),
            donate_argnums=(0,))

    # -- resize prewarm (AOT executables across restarts) --------------------
    #
    # SURVEY §7 names restart latency as THE metric for elastic TPU
    # training: stop-resume pays tracing + XLA compile at every world-
    # size change, dominating recovery. A running job already holds the
    # devices any SMALLER world would use — so the step can be compiled
    # for that sub-mesh NOW and carried to the restarted process. The
    # persistent compilation cache cannot carry it (its key includes
    # the platform topology, which differs between an 8-device process
    # compiling for 4 devices and a genuine 4-device process — verified
    # empirically); AOT executable serialization
    # (jax.experimental.serialize_executable) can: the deserialized
    # executable runs in the smaller process directly, skipping compile
    # entirely. Staleness safety: files are keyed by a fingerprint of
    # the lowered computation + shapes + jaxlib version, recomputed by
    # the restarted process — a code or config change simply misses.

    def _aot_dir(self):
        base = os.environ.get("EDL_TPU_COMPILE_CACHE")
        return os.path.join(base, "aot_steps") if base else None

    def _step_lowered(self, world_n=None):
        """Lower the train step for ``world_n`` devices (None = the
        current mesh), returning (lowered, fingerprint)."""
        import hashlib

        if world_n is None:
            state_sh = self._state_shardings
            data_sh = self._batch_sharding
            repl = self._repl
        else:
            # _target_mesh uses the PROCESS device list, not the
            # current mesh's: a trainer running on a shrunken sub-mesh
            # can then prewarm the grow direction too (the 4→8 leg of
            # the live-resize arc). Model axes keep their sizes; dp
            # absorbs the world change — the same mesh live_resize
            # will build.
            mesh_n = self._target_mesh(world_n)
            repl = NamedSharding(mesh_n, P())
            data_sh = NamedSharding(mesh_n, self._batch_sharding.spec)
            state_sh, why = self._transplant_shardings(mesh_n)
            if state_sh is None:
                raise ValueError("world %d: uncomputable target "
                                 "spans: %s" % (world_n, why))
        lowered = jax.jit(
            self._raw_step(),
            in_shardings=(state_sh, data_sh, repl),
            out_shardings=(state_sh, repl),
            donate_argnums=(0,)).lower(
                jax.tree_util.tree_map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                    self.train_state),
                self._example_batch_sds,
                jax.ShapeDtypeStruct((2,), np.uint32))
        h = hashlib.sha256()
        h.update(jax.version.__version__.encode())
        h.update(lowered.as_text().encode())
        return lowered, h.hexdigest()[:24]

    def _prewarm_in_scope(self):
        """Same family as _live_scope_check: prewarm covers any mesh
        the in-place reshape can rebuild (model axes welcome — the AOT
        step is lowered with the transplanted state shardings); only
        multi-process worlds and unreproducible topologies are out."""
        if self._example_batch_sds is None:
            return "needs the batch structure (call after a train_step)"
        if jax.process_count() > 1:
            return "multi-process world"
        bad = [a for a in self.mesh.axis_names
               if a not in ("dp", "tp", "sp", "pp", "ep")]
        if bad:
            return ("mesh axes %s (hybrid/custom topology) cannot be "
                    "rebuilt in place" % (bad,))
        return None

    def prewarm_resize_compiles(self, world_sizes, block=True):
        """Compile the train step for OTHER world sizes and serialize
        the executables under EDL_TPU_COMPILE_CACHE/aot_steps, so the
        next resize restart LOADS its step instead of compiling it
        (picked up automatically at the restarted trainer's first
        train_step). Scope: single-process trainers on any
        make_mesh-shaped mesh — model axes keep their sizes and dp
        absorbs the world change, with state shardings transplanted
        (see _live_scope_check). Sizes out of range, not divisible by
        the model-parallel factor, or not dividing the batch are
        skipped with a log line. ``block=False`` runs on a background
        thread. Returns the target sizes (the compiled subset when
        blocking)."""
        import pickle

        why = self._prewarm_in_scope()
        if why is not None:
            logger.info("prewarm: %s — skipped", why)
            return []
        out_dir = self._aot_dir()
        if out_dir is None:
            logger.info("prewarm: EDL_TPU_COMPILE_CACHE unset — "
                        "nowhere to persist, skipped")
            return []
        devices = jax.devices()  # targets may exceed the CURRENT mesh
        current = len(list(self.mesh.devices.flat))
        # the DATA-SHARDED axis of the example batch (under grad
        # accumulation the leading axis is the microbatch count, and
        # the rows sit on axis 1 — follow the sharding spec, not a
        # hardcoded axis 0)
        spec = tuple(self._batch_sharding.spec)
        axis_index = 0
        for i, s in enumerate(spec):
            if s == DATA_AXIS or (isinstance(s, tuple) and DATA_AXIS in s):
                axis_index = i
                break
        batch_dim = jax.tree_util.tree_leaves(
            self._example_batch_sds)[0].shape[axis_index]
        # rows split over dp only; the model-parallel factor is fixed
        # across the resize, so world n implies dp = n / model_n
        model_n = 1
        for a in self.mesh.axis_names:
            if a != DATA_AXIS:
                model_n *= int(self.mesh.shape[a])
        targets = []
        for n in sorted(set(int(w) for w in world_sizes)):
            if n == current:
                continue
            if n < 1 or n > len(devices):
                logger.info("prewarm: world %d outside this process's "
                            "1..%d devices — skipped", n, len(devices))
                continue
            if n % model_n:
                logger.info("prewarm: world %d not divisible by the "
                            "model-parallel factor %d — skipped", n,
                            model_n)
                continue
            if batch_dim % (n // model_n):
                logger.info("prewarm: world %d (dp=%d) does not divide "
                            "the sharded batch dim %d — skipped", n,
                            n // model_n, batch_dim)
                continue
            targets.append(n)

        def compile_all():
            from jax.experimental import serialize_executable as se
            os.makedirs(out_dir, exist_ok=True)
            done = []
            for n in targets:
                try:
                    t0 = time.perf_counter()
                    lowered, fp = self._step_lowered(n)
                    payload, in_tree, out_tree = se.serialize(
                        lowered.compile())
                    path = os.path.join(out_dir,
                                        "step_w%d_%s.pkl" % (n, fp))
                    tmp = path + ".tmp.%d" % os.getpid()
                    with open(tmp, "wb") as f:
                        pickle.dump({"payload": payload,
                                     "in_tree": in_tree,
                                     "out_tree": out_tree}, f)
                    os.replace(tmp, path)
                    done.append(n)
                    logger.info(
                        "prewarm: world-%d step compiled + serialized "
                        "in %.1fs (%s)", n,
                        time.perf_counter() - t0, path)
                except Exception:
                    logger.exception("prewarm for world %d failed", n)
            return done

        if block:
            return compile_all()
        self._prewarm_thread = threading.Thread(
            target=compile_all, daemon=True, name="resize-prewarm")
        self._prewarm_thread.start()
        return targets

    def _try_load_prewarmed_step(self):
        """At the first train_step: if a prior incarnation serialized
        THIS world size's step executable, load it and skip the
        compile. Returns a jit_step-compatible callable or None."""
        import pickle

        if self._prewarm_in_scope() is not None:
            return None
        aot = self._aot_dir()
        if aot is None:
            return None
        # from here the cache is CONFIGURED: every early-out is a real
        # miss (full compile paid) and counts toward the doctor's
        # compile-cache-cold finding
        if not os.path.isdir(aot):
            _PREWARM_MISSES.inc()
            return None
        n = len(list(self.mesh.devices.flat))
        # any candidate for this world at all? — checked BEFORE paying
        # a trace+lower just to compute the fingerprint (a miss here is
        # the common case, e.g. a same-world restart)
        import glob as glob_mod
        if not glob_mod.glob(os.path.join(aot, "step_w%d_*.pkl" % n)):
            _PREWARM_MISSES.inc()
            return None
        try:
            _, fp = self._step_lowered()
        except Exception:
            logger.exception("prewarm load: lowering failed")
            _PREWARM_MISSES.inc()
            return None
        path = os.path.join(aot, "step_w%d_%s.pkl" % (n, fp))
        if not os.path.exists(path):
            _PREWARM_MISSES.inc()
            return None
        try:
            from jax.experimental import serialize_executable as se
            t0 = time.perf_counter()
            with open(path, "rb") as f:
                blob = pickle.load(f)
            loaded = se.deserialize_and_load(
                blob["payload"], blob["in_tree"], blob["out_tree"])
            repl = self._repl
            jit_fallback = self._jit_step

            def step(state, batch, rng):
                # loaded executables take committed inputs with the
                # EXACT compiled signature; jax.jit would transparently
                # recompile on a changed rng type or a ragged tail
                # batch — mirror that by reverting to the jit path on
                # an input mismatch (argument validation rejects before
                # any buffer is donated, so the retry is safe)
                try:
                    return loaded(state, batch,
                                  jax.device_put(rng, repl))
                except (TypeError, ValueError) as e:
                    # ONLY argument-validation failures are safe to
                    # retry: they reject before dispatch, so no buffer
                    # has been donated yet. A post-dispatch failure
                    # (XlaRuntimeError etc.) leaves state's donated
                    # buffers deleted — retrying would mask the real
                    # error with a use-after-donate; let it propagate.
                    logger.warning(
                        "AOT step input mismatch (%r); reverting to "
                        "the jit path for this and later steps", e)
                    self._jit_step = jit_fallback
                    return jit_fallback(state, batch, rng)

            logger.info("resize prewarm HIT: world-%d step loaded from "
                        "%s in %.2fs (compile skipped)", n, path,
                        time.perf_counter() - t0)
            _PREWARM_HITS.inc()
            return step
        except Exception:
            logger.exception("prewarm load failed (falling back to "
                             "the normal compile)")
            _PREWARM_MISSES.inc()
            return None

    def local_batch_slice(self, full_batch):
        """Slice a FULL global batch down to the rows this process must
        supply (the complement of shard_batch): contiguous lo:hi under
        pure dp; every row when a model axis (tp/sp) crosses hosts."""
        def cut(x):
            return np.concatenate([x[a:b] for a, b in
                                   self._host_row_spans], axis=0)
        return jax.tree_util.tree_map(cut, full_batch)

    def shard_batch(self, host_batch):
        """Turn per-host numpy arrays into a globally-sharded jax.Array over
        the dp axis (multi-host safe)."""
        if jax.process_count() > 1:
            return jax.tree_util.tree_map(
                lambda x: jax.make_array_from_process_local_data(
                    self._batch_sharding, x), host_batch)
        return jax.device_put(host_batch, self._batch_sharding)

    _STEP_WINDOW = 8  # intervals kept for the cadence estimate

    def train_step(self, host_batch, rng=None):
        t0 = time.perf_counter()
        if not self._stamp_first_step:
            # steady state: the step boundary re-claims the clock for
            # compute. After a resize the clock stays on resize_pause /
            # restore until the first step's result is READY (stamped
            # below) — the ledger's pause must agree with measure_resize,
            # which measures to first-step completion, not dispatch.
            obs_ledger.LEDGER.transition("compute")
        if self._last_step_start is not None:
            self._step_intervals.append(t0 - self._last_step_start)
            del self._step_intervals[:-self._STEP_WINDOW]
        self._last_step_start = t0
        if rng is None:
            rng = jax.random.PRNGKey(self._host_step)
        if self._grad_accum > 1:
            k = self._grad_accum
            host_batch = jax.tree_util.tree_map(
                lambda x: x.reshape((k, x.shape[0] // k) + x.shape[1:]),
                host_batch)
        batch = self.shard_batch(host_batch)
        first_step = self._example_batch_sds is None
        if first_step:
            self._example_batch_sds = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), batch)
            loaded = self._try_load_prewarmed_step()
            if loaded is not None:
                self._jit_step = loaded
        self.train_state, loss = self._jit_step(self.train_state, batch, rng)
        if self._stamp_first_step:
            self._stamp_first_step = False
            # resize downtime breakdown: the first dispatch wall is
            # (almost entirely) trace+compile; the extra wait to result
            # availability is the first real step. Once per incarnation
            # AND once per live_resize (which re-arms the flag), so the
            # block_until_ready costs nothing the caller would not pay
            # anyway.
            c1 = time.perf_counter()
            self._resize_timing["compile_s"] = c1 - t0
            jax.block_until_ready(loss)
            self._resize_timing["first_step_s"] = time.perf_counter() - c1
            self._resize_timing["t_first_step"] = time.time()
            # close the pause HERE so the published ledger snapshot
            # already carries the full resize_pause for this arc
            obs_ledger.LEDGER.transition("compute")
            self._publish_resize_timing()
            obs_events.emit("resize.first_step",
                            rank=self.env.global_rank,
                            compile_s=self._resize_timing["compile_s"],
                            first_step_s=self._resize_timing
                            ["first_step_s"])
        self._host_step += 1
        step_s = time.perf_counter() - t0
        self._step_times.append(step_s)
        _STEP_MS.observe(step_s * 1e3)
        if self._live_watcher is not None:
            # a published live-resize intent is handled HERE, at a step
            # boundary — the drain point of the drain/reshard/swap loop
            self._maybe_live_resize()
        if self._coord_stop is not None:
            if not self._coord_stop.started:
                # first boundary: the baseline is final (resume() ran
                # before any step), so stale keys are now rejectable
                self._coord_stop.min_step = max(self._coord_stop.min_step,
                                                self._host_step - 1)
                self._coord_stop.start()
            if self._preempted:
                self._coord_stop.request(self._host_step)
                if self._preempt_t0 is None:
                    self._preempt_t0 = time.monotonic()
            stop = self._coord_stop.stop_at
            if stop is not None and self._host_step >= stop:
                self._coordinated_save_and_raise(missed=self._host_step
                                                 > stop)
            elif self._preempted and (time.monotonic() - self._preempt_t0
                                      > self._coord_deadline):
                # no agreed stop within the deadline (store unreachable,
                # rank 0 dead): the local emergency path is strictly
                # better than training until SIGKILL with no checkpoint
                logger.warning("no coordinated stop within %.0fs; "
                               "falling back to the local emergency "
                               "save", self._coord_deadline)
                self._emergency_save()
        elif self._preempted:
            self._emergency_save()
        return loss

    # -- live resize (in-place reshard, no kill/respawn) ---------------------
    #
    # Stop-resume pays detect + kill + barrier + restore + compile per
    # membership change. A SURVIVING process holds the state on device,
    # a committed host snapshot on the peer plane, and (with prewarm)
    # the new world's AOT executable — so the only genuinely required
    # work is: drain to a step boundary, rebuild the mesh, reshard the
    # pytree, swap the step executable. Scope: single-process trainers
    # on a pure-dp mesh with replicated state (the JAX runtime cannot
    # re-run jax.distributed.initialize, so cross-process worlds keep
    # stop-resume). Protocol + the placed reshard engine live in
    # runtime/live_resize.py; docs/elastic_resize.md has the ladder.

    # everything the new mesh derives — snapshotted before a live
    # resize so ANY failure rolls back to a numerically untouched
    # trainer and the stop-resume ladder takes over
    _MESH_BOUND_ATTRS = ("mesh", "_batch_sharding_early",
                         "per_device_batch", "_host_row_spans",
                         "per_host_batch", "_repl", "_batch_sharding",
                         "_state_shardings", "_jit_step", "train_state")

    def _snapshot_bindings(self):
        return {a: getattr(self, a) for a in self._MESH_BOUND_ATTRS}

    def _restore_bindings(self, saved):
        for a, v in saved.items():
            setattr(self, a, v)

    def _target_mesh(self, n_devices, mesh_shape=None):
        """The live-resize target mesh over the first ``n_devices``
        process devices: ``mesh_shape`` ({axis: size} factors; dp may
        be omitted and fills the remainder) or, by default, the current
        mesh's model-parallel axes with dp rescaled. Raises ValueError
        when the factorization cannot be built (non-divisible,
        unknown axes, hybrid dcn topology)."""
        known = ("dp", "tp", "sp", "pp", "ep")
        if mesh_shape:
            bad = [a for a in mesh_shape if a not in known]
            if bad:
                raise ValueError("target mesh axes %s not buildable "
                                 "in place" % (bad,))
            kw = {a: int(s) for a, s in mesh_shape.items()
                  if a != DATA_AXIS}
            dp = mesh_shape.get(DATA_AXIS)
            if dp is not None:
                kw["dp"] = int(dp)
        else:
            bad = [a for a in self.mesh.axis_names if a not in known]
            if bad:
                raise ValueError(
                    "mesh axes %s (hybrid/custom topology) cannot be "
                    "rebuilt in place" % (bad,))
            kw = {a: int(self.mesh.shape[a])
                  for a in self.mesh.axis_names if a != DATA_AXIS}
        return make_mesh(devices=jax.devices()[:n_devices], **kw)

    def _transplant_shardings(self, new_mesh, shardings=None):
        """(shardings-on-new_mesh, reason): every state leaf's
        PartitionSpec re-rooted onto ``new_mesh``, or (None, why) when
        some leaf's target spans are not computable there — the reason
        names the leaf, the spec, and the failing axis/dim, and is what
        the fallback event journals."""
        from edl_tpu.parallel.sharding import spec_transplant_reason
        src = self._state_shardings if shardings is None else shardings
        reasons = []

        def move(path, sh, leaf):
            spec = getattr(sh, "spec", None)
            if spec is None:
                spec = P()
            why = spec_transplant_reason(spec, getattr(leaf, "shape",
                                                       ()), new_mesh)
            if why is not None:
                reasons.append("%s: %s"
                               % (checkpoint_mod._path_key(path), why))
            return NamedSharding(new_mesh, spec)

        out = jax.tree_util.tree_map_with_path(move, src,
                                               self.train_state)
        if reasons:
            return None, "; ".join(reasons[:3])
        return out, None

    def _live_scope_check(self, n_devices, mesh_shape=None):
        """Reason string when an in-place reshape to ``n_devices``
        (optionally a specific ``mesh_shape`` factorization) is
        impossible, else None. The same family as _prewarm_in_scope.
        The predicate is span computability, not replication: any state
        sharding whose PartitionSpecs transplant onto the target mesh
        (axes present, dims divisible) is in scope — a tp-degree
        change, a pp re-split, an expert re-balance all qualify; what
        does not (multi-process worlds, hybrid topologies, indivisible
        dims) degrades to stop-resume with the reason journaled."""
        if jax.process_count() > 1:
            return ("multi-process world (jax.distributed cannot "
                    "re-initialize in place)")
        n_all = len(jax.devices())
        if n_devices < 1 or n_devices > n_all:
            return ("target world %d outside this process's 1..%d "
                    "devices" % (n_devices, n_all))
        try:
            target = self._target_mesh(n_devices, mesh_shape)
        except ValueError as e:
            return str(e)
        n_rows = 1
        spec0 = data_sharding(target).spec
        spec0 = spec0[0] if spec0 else None
        for ax in ((spec0,) if isinstance(spec0, str)
                   else tuple(spec0 or ())):
            n_rows *= target.shape[ax]
        if self.total_batch_size % n_rows:
            return ("total batch %d not divisible by target dp=%d"
                    % (self.total_batch_size, n_rows))
        _, why = self._transplant_shardings(target)
        if why is not None:
            return "uncomputable target spans: %s" % why
        return None

    def _reshard_tree(self, tree, shardings):
        """Reshard the live pytree onto ``shardings``. Fully-addressable
        leaves (the single-process live scope) take the zero-wire fast
        path: jax.device_put lays the new placement out straight from
        the live device arrays. Anything else runs the placed ladder —
        local-span paste, peer range-reads at the committed version,
        per-span FS fill (live_resize.reshard_placed). Returns
        (new_tree, stats)."""
        leaves = jax.tree_util.tree_leaves(tree)
        if all(getattr(x, "is_fully_addressable", True) for x in leaves):
            out = jax.device_put(tree, shardings)
            jax.block_until_ready(out)
            nbytes = sum(int(getattr(x, "nbytes", 0)) for x in leaves)
            return out, {"source": "local", "local_bytes": nbytes,
                         "peer_bytes": 0, "peers": 0, "fs_keys": []}
        from edl_tpu.runtime import live_resize as live_mod
        version = (self._state_server.version
                   if self._state_server is not None else None)
        return live_mod.reshard_placed(
            tree, shardings, coord=self.coord, ckpt=self._ckpt,
            version=version,
            self_endpoint=(self._state_server.endpoint
                           if self._state_server is not None else None))

    def live_resize(self, n_devices, mesh_shape=None):
        """Reshape the mesh to ``n_devices`` IN PLACE: drain the save
        engine to a clean boundary, rebuild the mesh (``mesh_shape``
        picks a (dp, tp, pp, ep) factorization — e.g. the cluster
        generator's roofline choice — default: keep the current model
        axes and rescale dp), transplant every state PartitionSpec onto
        it, reshard params + optimizer state, rebuild the step (loading
        the prewarmed AOT executable when one exists), and resume — the
        process never exits, so the kill/barrier/restore stages of the
        stop-resume budget are eliminated. Stamps a fresh
        ``_resize_timing`` record (mode "live", with the new
        ``reshard_s`` stage); the next train_step stamps
        compile/first-step and republishes it.

        On ANY failure the trainer is rolled back to the old mesh —
        numerically untouched, still training — and LiveResizeError is
        raised; the caller (the intent ack path, or an operator) lets
        the stop-resume ladder handle the membership change instead.
        Chaos fault points: ``resize.live.drain`` (before the drain)
        and ``resize.live.reshard`` (after the new mesh is built,
        before any state moves)."""
        from edl_tpu.utils.errors import LiveResizeError

        n_devices = int(n_devices)
        t_start = time.time()
        old_n = len(list(self.mesh.devices.flat))
        start_id = obs_events.emit("resize.live.start",
                                   rank=self.env.global_rank,
                                   from_devices=old_n,
                                   to_devices=n_devices)
        why = self._live_scope_check(n_devices, mesh_shape)
        if why is not None:
            # scope=True marks "rejected before any state moved" (the
            # doctor's reshard_fallback finding), vs a mid-flight
            # rollback below
            obs_events.emit("resize.live.fallback", cause=start_id,
                            rank=self.env.global_rank, reason=why,
                            scope=True,
                            from_devices=old_n, to_devices=n_devices)
            raise LiveResizeError("live resize out of scope: %s" % why)
        same_shape = True
        if mesh_shape:
            same_shape = all(
                int(self.mesh.shape.get(a, 1)) == int(s)
                for a, s in mesh_shape.items())
        if n_devices == old_n and same_shape:
            obs_events.emit("resize.live.done", cause=start_id,
                            rank=self.env.global_rank, noop=True,
                            from_devices=old_n, to_devices=n_devices)
            return {"mode": "live", "noop": True,
                    "from_devices": old_n, "to_devices": n_devices}
        saved = self._snapshot_bindings()
        # training is paused from here until the first post-reshard
        # step result (train_step closes the pause when it stamps);
        # the drain below nests ckpt_block over this and returns here
        obs_ledger.LEDGER.transition("resize_pause")
        try:
            t0 = time.perf_counter()
            if faults.PLANE is not None:
                faults.PLANE.fire("resize.live.drain",
                                  from_devices=str(old_n),
                                  to_devices=str(n_devices))
            # drain: the in-flight async persist commits (and its peer
            # publish runs) BEFORE the reshape — peers keep a stable
            # version to read across our reshard
            self.wait_for_save()
            drain_s = time.perf_counter() - t0
            t1 = time.perf_counter()
            new_mesh = self._target_mesh(n_devices, mesh_shape)
            if faults.PLANE is not None:
                faults.PLANE.fire("resize.live.reshard",
                                  from_devices=str(old_n),
                                  to_devices=str(n_devices))
            new_shardings, why_t = self._transplant_shardings(
                new_mesh, saved["_state_shardings"])
            if new_shardings is None:
                raise LiveResizeError(
                    "uncomputable target spans: %s" % why_t)
            self._bind_mesh(new_mesh)
            self.train_state, reshard_stats = self._reshard_tree(
                self.train_state, new_shardings)
            self._state_shardings = new_shardings
            self._jit_step = self._build_step()
            prewarm = "n/a"
            if self._example_batch_sds is not None \
                    and self._aot_dir() is not None:
                loaded = self._try_load_prewarmed_step()
                if loaded is not None:
                    self._jit_step = loaded
                    prewarm = "hit"
                else:
                    prewarm = "miss"
            reshard_s = time.perf_counter() - t1
        except Exception as e:  # noqa: BLE001 — ANY failure rolls back
            self._restore_bindings(saved)
            # black-box the rollback: the evidence (drain/reshard spans,
            # fault firings) lives in rings this incarnation may not
            # survive once the stop-resume ladder takes over
            obs_flight.dump("live_resize_rollback", e)
            reason = "%s: %s" % (type(e).__name__, e)
            obs_events.emit("resize.live.fallback", cause=start_id,
                            rank=self.env.global_rank, reason=reason,
                            from_devices=old_n, to_devices=n_devices)
            logger.exception("live resize %d -> %d failed; rolled back "
                             "to the old mesh (stop-resume takes over)",
                             old_n, n_devices)
            if isinstance(e, LiveResizeError):
                raise
            raise LiveResizeError(
                "live resize %d -> %d failed (%s); rolled back"
                % (old_n, n_devices, reason)) from e
        # a live resize begins a new timing "incarnation": the record
        # carries the same stages measure_resize reads, with
        # t_construct = the moment training paused, so the driver's
        # after_ts filter works unchanged
        self._resize_timing = {
            "t_construct": t_start, "mode": "live",
            "t_resume_start": t_start,
            "drain_s": round(drain_s, 6),
            "reshard_s": round(reshard_s, 6),
            "from_devices": old_n, "to_devices": n_devices,
            "from_mesh": {str(a): int(s) for a, s in
                          zip(saved["mesh"].axis_names,
                              saved["mesh"].devices.shape)},
            "prewarm": prewarm,
            "restore_source": reshard_stats["source"],
            "restore_bytes": (reshard_stats["local_bytes"]
                              + reshard_stats["peer_bytes"]),
            "restore_peers": reshard_stats["peers"],
        }
        if self._state_server is not None \
                and self._state_server.version is not None:
            self._resize_timing["version"] = self._state_server.version
        self._stamp_first_step = True
        obs_events.emit("resize.live.done", cause=start_id,
                        rank=self.env.global_rank,
                        from_devices=old_n, to_devices=n_devices,
                        reshard_s=reshard_s, prewarm=prewarm,
                        source=reshard_stats["source"])
        logger.info("live resize %d -> %d: drain %.3fs reshard %.3fs "
                    "(%s, prewarm %s) — process stayed alive", old_n,
                    n_devices, drain_s, reshard_s,
                    reshard_stats["source"], prewarm)
        return dict(self._resize_timing)

    def enable_live_resize(self, who=None):
        """Join the live-resize protocol: advertise the TTL-leased
        capability key (only while in scope — a dummy or multi-process
        trainer never advertises, so the generator's eligibility check
        routes it to stop-resume) and watch for prepare intents
        addressed to this participant. train_step handles a pending
        intent at the next step boundary: drain → reshard → swap →
        ack. Returns self."""
        from edl_tpu.runtime import live_resize as live_mod
        if self.coord is None:
            raise ValueError("live resize needs a coordination store "
                             "(coord=)")
        self._live_who = (str(who) if who is not None
                          else (self.env.pod_id
                                or "r%d" % self.env.global_rank))
        why = self._live_scope_check(len(list(self.mesh.devices.flat)))
        if why is None:
            self._live_register = live_mod.advertise_capability(
                self.coord, self._live_who,
                info={"devices": len(jax.devices()),
                      "rank": self.env.global_rank})
        else:
            logger.info("live resize out of scope (%s); capability not "
                        "advertised — stop-resume only", why)
            self._live_register = None
        self._live_watcher = live_mod.LiveResizeWatcher(self.coord,
                                                        self._live_who)
        return self

    def _maybe_live_resize(self):
        """Handle a pending prepare intent at this step boundary:
        live_resize + ack ok, or roll back + nack (the coordinator then
        aborts and stop-resume runs). Never raises — a failed live
        resize leaves the trainer training on its old mesh until the
        launcher's kill arrives."""
        from edl_tpu.runtime import live_resize as live_mod
        from edl_tpu.utils.errors import LiveResizeError
        rec = self._live_watcher.pending()
        if rec is None:
            return
        intent_id = rec.get("id")
        target = rec.get("devices")
        if isinstance(target, dict):
            target = target.get(self._live_who)
        mesh_shape = rec.get("mesh")  # generator's factorization, opt.
        ok, reason, info = False, None, None
        try:
            if target is None:
                raise LiveResizeError(
                    "intent %s carries no device target for %s"
                    % (intent_id, self._live_who))
            stats = self.live_resize(int(target),
                                     mesh_shape=mesh_shape)
            ok = True
            info = {"world": stats.get("to_devices"),
                    "reshard_s": stats.get("reshard_s"),
                    "prewarm": stats.get("prewarm"),
                    "step": self._host_step}
        except LiveResizeError as e:
            reason = str(e)
        self._live_watcher.done(intent_id)
        try:
            live_mod.write_ack(self.coord, self._live_who, intent_id,
                               ok, reason=reason, info=info)
        except Exception:
            logger.exception("live resize: ack write failed")

    # -- the high-level loop -------------------------------------------------

    def fit(self, epochs, batches_fn, eval_fn=None, resume=True,
            preemption_exit_code=101, log_fn=None, signals=None,
            coordinated=None):
        """The full elastic training loop in one call: arm the
        preemption handler, resume from the newest checkpoint, iterate
        epochs (begin → train_step over ``batches_fn(epoch)`` → end +
        save), rank-0 eval, and the final SUCCEED status report.

        batches_fn(epoch) -> iterable of per-host batches (use
        local_batch_slice/an input pipeline shard for multi-host).
        eval_fn(trainer, epoch) runs on rank 0 after each epoch's save.
        On preemption the emergency checkpoint is already written; the
        process exits with ``preemption_exit_code`` (the exit-101
        restart convention) — pass None to get PreemptedError raised
        instead. ``signals``/``coordinated`` forward to
        install_preemption_handler; a handler the caller armed earlier
        is left untouched. Returns {"resumed", "steps", "final_loss",
        "world"}.
        """
        from edl_tpu.utils.errors import PreemptedError

        if not self._preempt_armed:
            self.install_preemption_handler(signals=signals,
                                            coordinated=coordinated)
        # arm the black box for this incarnation: any death path out of
        # fit() (preemption exit, unhandled exception via the chained
        # excepthook) leaves a blackbox/v1 artifact behind
        if obs_flight.RECORDER is None:
            obs_flight.install("trainer_r%d" % self.env.global_rank,
                               coord=self.coord)
        obs_flight.RECORDER.register_provider(
            "resize_timing", lambda: dict(self._resize_timing))
        # the fleet view is built from obs_* publications, and the
        # launcher's PodServer publisher only covers the supervisor
        # process — the ledger/step counters that make goodput live
        # HERE, so the training process ships its own registry
        publisher = None
        if self.coord is not None:
            from edl_tpu.obs.publisher import MetricsPublisher
            pod_key = ("%s_r%d" % (self.env.pod_id,
                                   self.env.global_rank)
                       if self.env.pod_id
                       else "trainer_r%d" % self.env.global_rank)
            publisher = MetricsPublisher(self.coord, pod_key).start()
        resumed = self.resume() if resume else False
        start_epoch = self.state.next_epoch() if resumed else 0
        say = log_fn or logger.info
        say("fit: rank=%d world=%d start_epoch=%d resumed=%s"
            % (self.env.global_rank, self.world_size, start_epoch,
               resumed))
        loss = None
        try:
            for epoch in range(start_epoch, epochs):
                self.begin_epoch(epoch)
                if epoch == epochs - 1:
                    # AFTER begin_epoch: it reports RUNNING, which would
                    # clobber the scale-out-stopping NEARTHEEND verdict
                    self.report_status(train_status_mod.TrainStatus
                                       .NEARTHEEND)
                for batch in batches_fn(epoch):
                    loss = self.train_step(batch)
                self.end_epoch(save=True)
                say("fit: epoch %d done step=%d loss=%s"
                    % (epoch, self.global_step,
                       "%.5f" % float(loss) if loss is not None
                       else "n/a"))
                if eval_fn is not None and self.env.global_rank == 0:
                    eval_fn(self, epoch)
        except PreemptedError as e:
            # the exit-101 path never reaches sys.excepthook (SystemExit
            # is special-cased), so the box must be dumped here
            obs_flight.dump("preempted", e)
            say("fit: preempted: %s" % e)
            if preemption_exit_code is None:
                raise
            import sys
            sys.exit(preemption_exit_code)
        finally:
            # whatever happens, the training thread's clock is no
            # longer compute; close the interval so the final publish
            # (or the black box) carries the full attribution
            obs_ledger.LEDGER.transition("idle")
            obs_ledger.LEDGER.flush()
            if publisher is not None:
                publisher.stop()  # final flush ships the full ledger
        self.report_status(train_status_mod.TrainStatus.SUCCEED)
        return {"resumed": resumed, "steps": self.global_step,
                "final_loss": None if loss is None else float(loss),
                "world": self.world_size}

    # -- preemption (grace-window emergency checkpoint) ----------------------

    def install_preemption_handler(self, signals=None, coordinated=None):
        """Arm the grace-window emergency checkpoint.

        The launcher's kill path is process-tree SIGTERM, then SIGKILL
        after a grace period (train_process.terminate_trainers; k8s pod
        deletion behaves the same). The handler only sets a flag —
        async-signal-safe, and a save cannot run mid-XLA-dispatch — and
        the next step/epoch boundary writes a checkpoint at the CURRENT
        step, then raises PreemptedError. The restart resumes the model
        at that step and RE-RUNS the interrupted epoch from its start
        (State.next_epoch): no optimizer progress is lost, but batches
        the interrupted epoch already consumed are replayed (epoch-
        granular loops; an ElasticReader loop resumes exactly instead,
        via State.data_checkpoint record ranges). Returns self so it
        chains after construction.

        Multi-host: ``coordinated`` (default: auto-on when multi-process
        AND a coordination store is attached) runs the CoordinatedStop
        protocol — a flagged rank publishes its preemption to the store,
        rank 0 publishes an agreed stop step a few steps ahead, and
        EVERY rank stops at that exact boundary, where the normal
        cooperative save (per-host sharded write) is safe even for
        cross-host tp/sp-sharded state. Without a store, preempted ranks
        cannot rendezvous (signals land at different step boundaries, so
        neither a gather nor the sharded-save barrier is safe): with
        replicated(-or-host-only) state, rank 0 alone writes a complete
        dense checkpoint from its local replicas; with cross-host
        SHARDED state the save is skipped and the restart falls back to
        the last epoch-end checkpoint.
        """
        self._guard.install(signals)
        self._preempt_armed = True
        if coordinated is None:
            coordinated = jax.process_count() > 1 and self.coord is not None
        if coordinated and self._coord_stop is None:
            if self.coord is None:
                raise ValueError("coordinated preemption needs a "
                                 "coordination store (coord=)")
            from edl_tpu.runtime.preemption import CoordinatedStop
            # created here, STARTED at the first step boundary — by then
            # any resume() has fixed the baseline step, so a stale
            # stop_at from a prior incarnation can never be accepted
            self._coord_stop = CoordinatedStop(
                self.coord, jax.process_index(),
                stage=self.env.cluster_stage or "default",
                current_step=lambda: self._host_step,
                min_step=self._host_step,
                step_time=self._recent_step_time)
        return self

    def _recent_step_time(self):
        """Mean of the recent start-to-start step intervals (0.0 when
        unknown) — the preemption leader converts watcher poll latency
        into steps. Start-to-start MEAN, not in-call time or a median:
        async jit dispatch returns in milliseconds, and a loop that
        syncs only every k steps shows k-1 tiny gaps plus one gap
        carrying the device time — the mean recovers the true per-step
        cadence where a median would collapse to the dispatch gap."""
        tail = self._step_intervals
        return sum(tail) / len(tail) if tail else 0.0

    def _on_preempt_signal(self, signum, frame):
        self._guard._on_signal(signum, frame)

    @property
    def _preempted(self):
        return self._guard.preempted

    @_preempted.setter
    def _preempted(self, value):
        self._guard.preempted = bool(value)

    @property
    def preempted(self):
        return self._preempted

    def _coordinated_save_and_raise(self, missed=False):
        """All ranks reached the agreed stop step: the normal cooperative
        save (per-host sharded write, or rank-0 dense) is safe here —
        every rank sits at the SAME boundary, so the fs barrier aligns
        and the version numbers match.

        ``missed`` (this rank observed stop_at only after passing it —
        extreme skew): the aligned save is impossible; raise WITHOUT
        saving so the stopped ranks' barrier times out rather than
        committing a mixed-step checkpoint. Any save failure still exits
        via PreemptedError — the restart falls back to the last
        epoch-end checkpoint."""
        from edl_tpu.utils.errors import PreemptedError

        # FIRST drain the in-flight async persist: every coordinated
        # exit below (including the non-saving "missed" one) must leave
        # the previously started version committed, not lost
        self._guard.drain()
        self._coord_stop.stop()
        if missed:
            logger.warning("coordinated stop step %s observed late at "
                           "step %d; skipping the aligned save",
                           self._coord_stop.stop_at, self._host_step)
            self._record_missed_stop_metric()
            raise PreemptedError(
                "preempted; missed the coordinated stop step (%s < %d) — "
                "no emergency save, restart resumes from the last epoch "
                "checkpoint" % (self._coord_stop.stop_at, self._host_step))
        logger.info("coordinated preemption stop at step %d",
                    self._host_step)
        obs_events.emit("resize.coordinated_stop",
                        rank=self.env.global_rank, step=self._host_step)
        self.state.global_step = self.global_step
        self.wait_for_save()
        was_async, self._async_save = self._async_save, False
        try:
            self.save()
        except Exception as e:  # noqa: BLE001
            logger.exception("coordinated emergency save failed")
            raise PreemptedError(
                "preempted; coordinated emergency save failed (%r) — "
                "restart resumes from the last epoch checkpoint" % (e,))
        finally:
            self._async_save = was_async
        raise PreemptedError(
            "preempted (coordinated stop); checkpoint saved at step %d"
            % self._host_step)

    def _record_missed_stop_metric(self):
        """Operators need to SEE when the best-effort coordinated save
        degraded to the epoch fallback (pathological skew — the rank
        overshot the agreed step): a per-rank counter under the metrics
        service, scraped by job_stats (VERDICT r3 weak #8)."""
        if self.coord is None:
            return
        try:
            from edl_tpu.controller import constants
            import json as _json
            key = "preempt_missed_r%d" % self.env.global_rank
            raw = self.coord.get_value(constants.SERVICE_METRICS, key)
            rec = {}
            if raw:
                try:
                    rec = _json.loads(raw)
                except ValueError:
                    rec = {}
            rec = {"count": int(rec.get("count", 0)) + 1,
                   "last_step": self._host_step,
                   "last_stop_at": self._coord_stop.stop_at,
                   "ts": round(time.time(), 1)}
            self.coord.set_server_permanent(constants.SERVICE_METRICS,
                                            key, _json.dumps(rec))
        except Exception:
            logger.exception("missed-stop metric write failed")

    def _state_locally_fetchable(self):
        """True when every state leaf can reach host memory WITHOUT a
        collective (the same predicate to_host_tree_local enforces)."""
        return all(checkpoint_mod.leaf_locally_fetchable(x)
                   for x in jax.tree_util.tree_leaves(self.train_state))

    def _emergency_save(self, already_saved=False):
        """Write the grace-window checkpoint and raise PreemptedError.

        Preempted ranks cannot rendezvous — signals land at different
        step boundaries — so NO cooperative path (collective gather or
        the sharded-save fs barrier) is allowed here. Single process:
        the normal dense save. Multi-process with replicated(-or-host)
        state: rank 0 alone writes a complete dense checkpoint from its
        local replicas. Multi-process with cross-host SHARDED state: no
        single rank holds the model — skip, and the restart falls back
        to the last epoch-end checkpoint."""
        from edl_tpu.utils.errors import PreemptedError

        # drain before ANY exit below — the no-save paths (no ckpt dir,
        # cross-host sharded skip, non-rank-0 wait) must still land the
        # in-flight async version before the process dies
        self._guard.drain()
        if self._ckpt is None:
            raise PreemptedError(
                "preempted at step %d; no checkpoint dir configured — "
                "nothing saved, restart begins fresh" % self._host_step)
        if already_saved:
            raise PreemptedError(
                "preempted; checkpoint saved at step %d" % self._host_step)
        self.state.global_step = self.global_step  # else stale since the
        # last end_epoch — the store/meta snapshot must show real progress
        if jax.process_count() <= 1:
            logger.info("preemption signal: emergency checkpoint at "
                        "step %d", self._host_step)
            self.wait_for_save()
            was_async, self._async_save = self._async_save, False
            try:
                self.save()
            finally:
                self._async_save = was_async
            raise PreemptedError(
                "preempted; checkpoint saved at step %d" % self._host_step)
        if not self._state_locally_fetchable():
            logger.warning("preempted with cross-host sharded state; "
                           "skipping the emergency save (no rank holds "
                           "the full model and ranks cannot rendezvous)")
            raise PreemptedError(
                "preempted at step %d; emergency save skipped (cross-"
                "host sharded state) — restart resumes from the last "
                "epoch checkpoint" % self._host_step)
        if jax.process_index() != 0:
            # best-effort: wait briefly for rank 0's manifest so a fast
            # per-process restart (liveft exit-101) cannot resume an
            # older version than rank 0 does. The launcher's stop-resume
            # path re-barriers the whole cluster and needs no wait.
            # Rank 0 tags its emergency save with meta["emergency"], so
            # the wait keys on THAT — a recent epoch-end checkpoint at a
            # nearby version cannot satisfy it, and a rank-0 commit that
            # landed before we started waiting still does (no burned
            # grace window). In a PARTIAL preemption rank 0 may never
            # have received SIGTERM: then the wait times out and the
            # save simply did not happen — say so.
            # an emergency version must be from THIS preemption event:
            # >= the floor AND newer than the version this incarnation
            # resumed from — a prior event's emergency checkpoint kept
            # by _gc sits exactly at the resumed version and must not
            # satisfy the wait for the current one
            target_floor = self._host_step - 3
            found = False
            try:
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline:
                    vs = self._ckpt.versions()
                    recent = [v for v in vs
                              if v >= target_floor
                              and v > self._resumed_version]
                    if any((self._ckpt.meta(v) or {}).get("emergency")
                           for v in recent):
                        found = True
                        break
                    time.sleep(0.25)
            except Exception:
                logger.exception("waiting for rank-0 emergency manifest "
                                 "failed")
            if found:
                raise PreemptedError(
                    "preempted at step %d; emergency checkpoint is rank "
                    "0's (replicated state) — this rank wrote nothing"
                    % self._host_step)
            raise PreemptedError(
                "preempted at step %d; no rank-0 emergency checkpoint "
                "observed within the grace wait (rank 0 may not have "
                "been preempted) — restart resumes from the latest "
                "committed checkpoint" % self._host_step)
        logger.info("preemption signal: rank-0 local emergency "
                    "checkpoint at step %d", self._host_step)
        self.wait_for_save()
        import json
        state_snapshot = json.loads(self.state.to_json())
        self._ckpt.save(self.global_step,
                        checkpoint_mod.to_host_tree_local(
                            dict(self.train_state)),
                        meta={"state": state_snapshot,
                              "emergency": True})
        self._save_state_to_store(state_snapshot)
        raise PreemptedError(
            "preempted; checkpoint saved at step %d" % self._host_step)

    @property
    def global_step(self):
        return int(self.train_state["step"])

    @property
    def world_size(self):
        return jax.process_count()

    # -- epochs / status -----------------------------------------------------

    def begin_epoch(self, epoch_no):
        if self._preempted:
            # SIGTERM landed between epochs (eval, data setup): save at
            # this boundary rather than silently swallowing the stop.
            # Coordinated mode only REQUESTS here — all ranks reach the
            # stop inside the next epoch's step loop together
            if self._coord_stop is not None:
                self._coord_stop.request(self._host_step)
            else:
                self._emergency_save()
        self.state.begin_epoch(epoch_no, self.world_size)
        self._step_times = []
        self.report_status(train_status_mod.TrainStatus.RUNNING)

    def end_epoch(self, save=True):
        n = len(self._step_times)
        avg = sum(self._step_times) / n if n else 0.0
        self.state.end_epoch(n, avg)
        self.state.global_step = self.global_step
        if save:
            self.save()
        if self._preempted:
            if self._coord_stop is not None:
                self._coord_stop.request(self._host_step)
            else:
                # the epoch-end save (if any) already covers this step
                self._emergency_save(already_saved=save)

    def report_status(self, status):
        if self.coord is not None and self.env.pod_id:
            try:
                train_status_mod.save_train_status(self.coord,
                                                   self.env.pod_id, status)
            except Exception:
                logger.exception("train status report failed")

    # -- checkpoint / resume -------------------------------------------------

    @property
    def extra_state(self):
        return self.train_state["extra"]

    def _state_fully_addressable(self):
        return all(getattr(x, "is_fully_addressable", True)
                   for x in jax.tree_util.tree_leaves(self.train_state))

    def save(self):
        """Write the versioned checkpoint + State (reference: rank0
        fleet.save_check_point per epoch, train_with_fleet.py:562).

        Fully-addressable state (single process): rank 0 writes the
        dense checkpoint. Any cross-process state (is_fully_addressable
        is False for every multi-host jax.Array, replicated included):
        EVERY process calls this and writes only the shards it owns
        replica 0 of (CheckpointManager.save_sharded) — no gather
        collective, write bandwidth scales with host count (the Orbax
        role), and synchronization is filesystem visibility on the
        shared store, not device collectives. For replicated leaves the
        replica-0 dedup means rank 0 writes them once.

        With ``async_save=True`` the write rides the checkpoint engine's
        two-phase path (save_async/save_sharded_async): a fast host-side
        snapshot into pooled buffers runs here — later steps may donate
        the originals — and a background writer pool streams the entries
        out, committing the manifest last so partial writes stay
        invisible. The engine's max_inflight=1 back-pressure drains the
        previous save first."""
        if self._ckpt is None:
            return
        version = self.global_step
        # deep-snapshot the control-plane state NOW — the background writer
        # must not see the live State's nested dicts mutating under it
        import json
        state_snapshot = json.loads(self.state.to_json())
        # the sharding record (PartitionSpec tree + mesh axes) rides
        # meta.json through every save path — restore never needs it
        # (span intersection works blind) but the resize planner reads
        # it to cost a target mesh before touching any data
        meta = {"state": state_snapshot,
                "sharding": checkpoint_mod.sharding_record(
                    self._state_shardings)}

        self.wait_for_save()
        # peer restore plane: capture SEPARATE host copies of this
        # process's shards NOW (the training thread — later steps may
        # donate the originals, and the engine's pooled staging buffers
        # are reused by the next save, so neither may be served) and
        # publish them only once the version COMMITS — a served version
        # is always also manifest-valid on the FS.
        publish = None
        if self._state_server is not None:
            from edl_tpu.runtime import redundancy as redundancy_mod
            from edl_tpu.runtime import state_server as state_server_mod
            entries, dtags = state_server_mod.snapshot_entries(
                dict(self.train_state))
            srv = self._state_server
            coord = self.coord
            owner = str(self.env.global_rank)

            def publish():
                srv.publish(version, entries, dtags, meta=meta)
                # commit-path hand-off to the redundancy tier: encode
                # the same committed host copies and push the shards
                # to this pod's partner ring. Runs on the persist
                # driver thread (never the training step) and is
                # strictly best-effort — the version is already
                # durable on the FS and served by the StateServer.
                if coord is not None and redundancy_mod.enabled():
                    try:
                        redundancy_mod.push_shards(
                            coord, owner, version, entries, dtags,
                            meta=meta, self_endpoint=srv.endpoint)
                    except Exception:
                        logger.exception(
                            "redundancy shard push for v%d failed; "
                            "this version has no parity cover", version)

        if not self._state_fully_addressable():
            # per-host sharded write; every rank participates
            rank = jax.process_index()
            nranks = jax.process_count()
            store_write = ((lambda: self._save_state_to_store(
                state_snapshot)) if rank == 0 else None)

            def on_commit(_store=store_write, _pub=publish):
                if _pub is not None:
                    _pub()
                if _store is not None:
                    _store()
            if self._async_save:
                self._ckpt.save_sharded_async(
                    version, dict(self.train_state), meta=meta,
                    rank=rank, nranks=nranks, on_commit=on_commit)
                return
            self._ckpt.save_sharded(version, dict(self.train_state),
                                    meta=meta, rank=rank, nranks=nranks)
            on_commit()
            return
        if self.env.global_rank != 0:
            return
        if self._async_save:
            def on_commit_dense(_pub=publish):
                if _pub is not None:
                    _pub()
                self._save_state_to_store(state_snapshot)
            self._ckpt.save_async(
                version, dict(self.train_state), meta=meta,
                on_commit=on_commit_dense)
            return
        self._ckpt.save(version,
                        checkpoint_mod.to_host_tree(
                            dict(self.train_state)), meta=meta)
        if publish is not None:
            publish()
        self._save_state_to_store(state_snapshot)

    def wait_for_save(self):
        """Block until any in-flight async checkpoint persist finishes
        (the engine's drain; a persist failure is logged there, and the
        manifest-last commit keeps the failed version invisible)."""
        if self._ckpt is not None:
            self._ckpt.drain()

    def close(self):
        """Release background resources: drain any in-flight async save,
        shut the checkpoint engine's writer pool down, and stop the
        preemption watcher thread and state server. Idempotent; the
        trainer remains usable for reads afterwards (notebooks
        constructing several trainers should close the ones they
        drop)."""
        self.wait_for_save()
        if self._live_register is not None:
            try:
                self._live_register.stop()
            except Exception:
                logger.exception("live-resize capability stop failed")
            self._live_register = None
        if self._live_watcher is not None:
            self._live_watcher.stop()
            self._live_watcher = None
        if self._state_server is not None:
            try:
                self._state_server.stop()
            except Exception:
                logger.exception("state server stop failed")
            self._state_server = None
        if self._ckpt is not None:
            self._ckpt.close()
        if self._coord_stop is not None:
            self._coord_stop.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _save_state_to_store(self, state_dict):
        if self.coord is not None:
            snap = state_mod.State()
            snap.from_dict(dict(state_dict))
            state_mod.save_to_store(self.coord, snap)

    def _restore_placed_any(self, version, target, shardings):
        """restore_placed walking the recovery ladder: live peer
        StateServers first (NIC bandwidth, host memory; the restorer
        itself decodes dead pods' parity shards for spans no peer
        serves), then a wholesale parity rebuild when NO peer serves
        the version at all, and only then the shared FS — the cold
        layer. MissingKeysError propagates either way — the caller's
        core-only retry must see it. Returns (version, tree, meta)."""
        if self._state_server is not None:
            from edl_tpu.runtime import redundancy as redundancy_mod
            from edl_tpu.runtime.state_server import PeerRestorer
            from edl_tpu.utils.errors import (PeerRestoreError,
                                              RedundancyError)
            restorer = PeerRestorer(
                self.coord, self._ckpt,
                self_endpoint=self._state_server.endpoint)
            try:
                v, tree, meta, stats = restorer.restore_placed(
                    version, target, shardings)
                self._resize_timing["restore_source"] = stats["source"]
                self._resize_timing["restore_bytes"] = \
                    stats["peer_bytes"]
                self._resize_timing["restore_peers"] = stats["peers"]
                logger.info("peer restore v%d: %.1f MB from %d peer(s)"
                            " (%s)", v, stats["peer_bytes"] / 1e6,
                            stats["peers"], stats["source"])
                return v, tree, meta or {}
            except MissingKeysError:
                raise
            except PeerRestoreError as e:
                logger.info("peer restore unavailable for v%d (%s); "
                            "trying the parity rung", version, e)
            except Exception:
                logger.exception("peer restore for v%d failed; "
                                 "trying the parity rung", version)
            if redundancy_mod.enabled() and self.coord is not None:
                try:
                    v, tree, meta, stats = redundancy_mod.restore_placed(
                        self.coord, version, target, shardings,
                        self_endpoint=self._state_server.endpoint)
                    self._resize_timing["restore_source"] = "parity"
                    self._resize_timing["restore_bytes"] = \
                        stats["parity_bytes"]
                    self._resize_timing["restore_peers"] = \
                        stats["holders"]
                    logger.info("parity restore v%d: %.1f MB decoded "
                                "from %d holder(s) (owners: %s)", v,
                                stats["parity_bytes"] / 1e6,
                                stats["holders"], stats["owners"])
                    return v, tree, meta or {}
                except MissingKeysError:
                    raise
                except RedundancyError as e:
                    logger.info("parity rung unavailable for v%d (%s);"
                                " restoring from the shared FS",
                                version, e)
                except Exception:
                    logger.exception("parity restore for v%d failed; "
                                     "restoring from the shared FS",
                                     version)
        out = self._ckpt.restore_placed(version, target, shardings)
        self._resize_timing["restore_source"] = "fs"
        return out

    def _publish_resize_timing(self):
        """Write this incarnation's per-stage resume timings to the
        coordination store (SERVICE_METRICS / resize_timing_r<rank>) so
        measure_resize can assemble the downtime breakdown without log
        scraping. Best-effort."""
        if self.coord is None:
            return
        import json as _json
        from edl_tpu.controller import constants
        # ride the ledger totals along: trainer subprocesses run no
        # MetricsPublisher, so this doc is how measure_resize (and the
        # pause-agreement test) reads the worker's time attribution
        doc = dict(self._resize_timing)
        # the CURRENT mesh factorization, so the driver can tell a
        # dp-only record from a dp x tp one without parsing shardings
        doc["mesh"] = {str(a): int(self.mesh.shape[a])
                       for a in self.mesh.axis_names}
        doc["ledger"] = {s: round(v, 6) for s, v
                        in obs_ledger.LEDGER.totals().items()}
        try:
            self.coord.set_server_permanent(
                constants.SERVICE_METRICS,
                "resize_timing_r%d" % self.env.global_rank,
                _json.dumps(doc))
        except Exception:
            logger.exception("resize timing publish failed")

    def resume(self):
        """Restore the newest valid checkpoint; apply resize adjust hooks if
        the world size changed. Returns True if something was restored."""
        if self._ckpt is None:
            return False
        # newest-first: per version, try the full state; when only the extra
        # keys are missing (legacy checkpoint), retry THAT version core-only
        # rather than falling back to an older checkpoint. The target is a
        # ShapeDtypeStruct tree — restore needs structure only, so no
        # gather of cross-host sharded leaves is ever required

        def _spec(x):
            a = x if hasattr(x, "shape") and hasattr(x, "dtype") \
                else np.asarray(x)
            return jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)

        # placed restore: each process reads only the shard entries its
        # devices need and assembles the sharded jax.Arrays directly —
        # host memory stays O(local shards), no full-model materialize
        target = jax.tree_util.tree_map(_spec, dict(self.train_state))
        restored = None
        self._resize_timing["t_resume_start"] = time.time()
        obs_ledger.LEDGER.transition("restore")
        obs_events.emit("resize.resume_start", rank=self.env.global_rank,
                        world_size=self.world_size)
        for version in reversed(self._ckpt.versions()):
            try:
                restored = self._restore_placed_any(
                    version, target, self._state_shardings)
                break
            except Exception as e:  # noqa: BLE001
                if isinstance(e, MissingKeysError) \
                        and jax.tree_util.tree_leaves(target["extra"]):
                    core = dict(target)
                    core.pop("extra")
                    core_sh = dict(self._state_shardings)
                    core_sh.pop("extra")
                    try:
                        restored = self._restore_placed_any(
                            version, core, core_sh)
                        logger.info("checkpoint v%d has no extra state; "
                                    "keeping the initial one", version)
                        # the live (initial) extra arrays, already laid
                        # out by self._state_shardings
                        restored[1]["extra"] = self.train_state["extra"]
                        break
                    except Exception as e2:  # noqa: BLE001
                        e = e2
                logger.warning("checkpoint v%d unusable (%r); trying older",
                               version, e)
        if restored is None:
            obs_ledger.LEDGER.transition("idle")
            return False
        version, tree, meta = restored
        self.train_state = tree
        if meta.get("state"):
            # hooks are process-local: carry them onto the restored state
            self.state = self.state.carry_hooks_to(
                state_mod.State().from_dict(meta["state"]))
            self.state.total_batch_size = self.total_batch_size
        prev_world = (self.state.epochs.get(str(self.state.epoch_no), {})
                      .get("world_size", self.world_size))
        if prev_world != self.world_size:
            logger.info("world resized %s -> %s; applying adjust hooks",
                        prev_world, self.world_size)
            self.state.adjust(self.world_size)
        self._host_step = self.global_step
        self._resumed_version = version
        # restore is done; the remainder of the pause (compile + first
        # dispatch) is charged to resize_pause until train_step stamps
        obs_ledger.LEDGER.transition("resize_pause")
        self._resize_timing["t_resume_end"] = time.time()
        self._resize_timing["restore_s"] = (
            self._resize_timing["t_resume_end"]
            - self._resize_timing["t_resume_start"])
        self._resize_timing["version"] = version
        obs_events.emit("resize.resumed", rank=self.env.global_rank,
                        version=version,
                        restore_s=self._resize_timing["restore_s"],
                        source=self._resize_timing.get("restore_source"))
        if self._coord_stop is not None:
            # preempt keys published by the incarnation that wrote this
            # checkpoint are at or below its final step: stale from here
            self._coord_stop.min_step = self._host_step
        logger.info("resumed from checkpoint v%d (epoch %d, step %d)",
                    version, self.state.epoch_no, self.global_step)
        return True
