"""Diskless fault tolerance: erasure-coded peer checkpoint shards.

The restore ladder used to bottom out on the shared filesystem: a pod
LOSS (as opposed to a survivor resharding) meant the dead pod's unique
spans existed nowhere but the FS, so every failure paid a
storage-bandwidth restore and at fleet scale the FS is both the
recovery bottleneck and the blast radius. This module adds the
redundancy tier that makes any f-pod loss recoverable entirely from
survivors (the Gemini/SOSP'23 argument, extended with erasure coding
so the host-RAM overhead is m/k of a replica):

- on each async-save COMMIT, every pod packs its committed snapshot
  spans (the same host copies the StateServer serves) into one blob,
  k-of-n erasure-codes it (GF(256) Cauchy parity; m == 1 degenerates
  to XOR, k == 1 to plain replication) and pushes one shard to each of
  n = k+m partner pods over ``state.shard_put``;
- partners hold shards in host RAM, versioned with the snapshot and
  served back via the ``state.shard`` range-read RPC (alongside
  ``state.read``), advertised through a SERVICE_REDUNDANCY lease;
- when a pod dies, any survivor rebuilds the dead pod's snapshot from
  any k of its n shards with ZERO FS reads — and pastes the decoded
  spans straight into a :class:`~edl_tpu.runtime.checkpoint.
  PlacedTarget`, so the rebuild lands directly in a NEW mesh
  factorization (the same span-overlap machinery the resize path
  uses; :func:`rebuild_plan` composes the decode with
  ``parallel.costmodel.device_spans``/``tree_reshard_bytes`` to price
  it analytically).

Partner ring rule (:func:`partner_ring`): a pod's partners are the
next n members after it in the SORTED cyclic order of the membership
set — a pure function of the set, like the relay tree's parent rule,
so every pod computes identical rings from the same cluster map and
the assignment survives any resize with zero negotiation.

Version fencing: a partner holds exactly ONE version per owner — the
newest pushed — and ``state.shard`` raises StaleStateError on any
mismatch; the rebuilder skips holders whose manifest shows a stale
version, so a stale shard is never decoded into a newer restore.

Ladder position (docs/elastic_resize.md "recovery ladder"): local
device spans → peer snapshot reads → THIS parity rung → the FS, now a
cold layer. The rung is strictly best-effort: every skip or failure
falls through losslessly and is recorded via the
``edl_redundancy_fs_fallbacks_total{reason}`` counter and a
``redundancy.fallback`` obs event (reason: stale_version,
insufficient_partners, fault, error) that job_doctor surfaces as a
``rebuild_fallback`` finding.

Kill switch: ``EDL_TPU_REDUNDANCY=0`` disables push, serve and rebuild
(the pre-PR ladder). ``EDL_TPU_REDUNDANCY_K``/``_M`` size the code
(default k=2, m=1).

Chaos fault points: ``redundancy.encode`` (pre-encode on the push
path; ctx: owner, version), ``redundancy.push`` (per shard send; ctx:
endpoint, owner, shard), ``redundancy.rebuild`` (per dead-owner
decode; ctx: owner, version) — see edl_tpu/robustness/faults.py.
"""

import json
import os
import struct
import time

import numpy as np

from edl_tpu.controller import constants
from edl_tpu.obs import events as obs_events
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.parallel import costmodel
from edl_tpu.robustness import faults
from edl_tpu.rpc.client import RpcClient
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger

_CHUNK = 4 << 20  # per range-read sub-fetch; matches the peer restorer

DEFAULT_K = 2  # data shards: partners hold 1/k of the blob each
DEFAULT_M = 1  # parity shards: tolerated partner losses per owner

_PUSH_MS = obs_metrics.histogram(
    "edl_redundancy_push_ms",
    "encode + partner-ring shard push wall time per commit")
_REBUILD_MS = obs_metrics.histogram(
    "edl_redundancy_rebuild_ms",
    "parity-rung rebuild wall time per restore attempt")
_FALLBACKS = obs_metrics.counter(
    "edl_redundancy_fs_fallbacks_total",
    "parity rung skipped or failed; restore fell through toward FS",
    labels=("reason",))
SHARDS_HELD = obs_metrics.gauge(
    "edl_redundancy_shards_held",
    "partner checkpoint shards currently held in host RAM")


def enabled():
    """The EDL_TPU_REDUNDANCY kill switch (default on)."""
    return os.environ.get("EDL_TPU_REDUNDANCY", "1") != "0"


def coding_params():
    """(k, m) from EDL_TPU_REDUNDANCY_K/_M, defaulting to (2, 1)."""
    k = max(1, int(os.environ.get("EDL_TPU_REDUNDANCY_K", DEFAULT_K)))
    m = max(0, int(os.environ.get("EDL_TPU_REDUNDANCY_M", DEFAULT_M)))
    if k + m > 256:
        raise ValueError("GF(256) code supports k+m <= 256, got %d"
                         % (k + m))
    return k, m


def _fallback(reason, **attrs):
    """Record why the parity rung was skipped/failed (counter + obs
    event); job_doctor quotes the reason in its rebuild_fallback
    finding."""
    _FALLBACKS.labels(reason).inc()
    obs_events.emit("redundancy.fallback", reason=reason, **attrs)


# -- GF(256) codec ----------------------------------------------------------
#
# Systematic k-of-n code over GF(2^8) with the AES/Rijndael-adjacent
# generator polynomial x^8+x^4+x^3+x^2+1 (0x11d, the classic
# Reed-Solomon choice). Generator matrix [I_k ; C] with C an m x k
# Cauchy block (C[i][j] = 1/(x_i ^ y_j), x_i = k+i, y_j = j): every
# k x k minor of a Cauchy-extended identity is invertible, so ANY k of
# the n = k+m shards decode. Vector math is numpy table lookups — no
# third-party codec dependency.

_GF_EXP = np.zeros(512, np.uint8)
_GF_LOG = np.zeros(256, np.int64)
_acc = 1
for _i in range(255):
    _GF_EXP[_i] = _acc
    _GF_LOG[_acc] = _i
    _acc <<= 1
    if _acc & 0x100:
        _acc ^= 0x11D
_GF_EXP[255:510] = _GF_EXP[:255]
del _acc, _i

_MUL_TABLES = {}  # coeff -> 256-entry product row (lazily built)


def _gf_mul(a, b):
    if a == 0 or b == 0:
        return 0
    return int(_GF_EXP[_GF_LOG[a] + _GF_LOG[b]])


def _gf_inv(a):
    if a == 0:
        raise ZeroDivisionError("GF(256) inverse of 0")
    return int(_GF_EXP[255 - _GF_LOG[a]])


def _mul_vec(c, v):
    """c * v elementwise over GF(256) for a uint8 vector ``v``."""
    if c == 0:
        return np.zeros_like(v)
    if c == 1:
        return v
    table = _MUL_TABLES.get(c)
    if table is None:
        table = np.zeros(256, np.uint8)
        table[1:] = _GF_EXP[int(_GF_LOG[c]) + _GF_LOG[1:256]]
        _MUL_TABLES[c] = table
    return table[v]


def _gf_dot(coeffs, vecs, chunk):
    """XOR-accumulate ``sum_i coeffs[i] * vecs[i]`` over GF(256).

    Zero terms are skipped and the accumulator is SEEDED from the
    first live term instead of a zeros+XOR pass — with the normalized
    parity rows (coefficient 1 everywhere in row 0) the m=1 path is a
    single copy plus plain ``^`` passes, no GF table gathers."""
    acc = None
    for c, v in zip(coeffs, vecs):
        if c == 0:
            continue
        t = _mul_vec(c, v)
        if acc is None:
            acc = t.copy() if t is v else t  # c==1 returns v itself
        else:
            acc ^= t
    if acc is None:
        return np.zeros(chunk, np.uint8)
    return acc


def _parity_rows(k, m):
    """The m x k Cauchy block below I_k in the generator matrix,
    column-scaled so row 0 is all ones. Diagonal column scaling
    preserves every mixed minor of [I_k ; C] (identity rows expand to
    a scaled Cauchy minor, still nonzero), so the code stays MDS —
    and the m=1 default becomes PLAIN XOR: encode and the
    single-loss decode run at numpy ^ speed instead of GF table
    gathers."""
    rows = [[_gf_inv((k + i) ^ j) for j in range(k)]
            for i in range(m)]
    if not rows:
        return rows
    scale = [_gf_inv(c) for c in rows[0]]
    return [[_gf_mul(c, s) for c, s in zip(row, scale)]
            for row in rows]


def _gf_matinv(a):
    """Invert a small k x k matrix over GF(256) (Gauss-Jordan)."""
    k = len(a)
    aug = [list(row) + [1 if r == c else 0 for c in range(k)]
           for r, row in enumerate(a)]
    for col in range(k):
        piv = next((r for r in range(col, k) if aug[r][col]), None)
        if piv is None:
            raise errors.RedundancyError("singular decode matrix")
        aug[col], aug[piv] = aug[piv], aug[col]
        inv = _gf_inv(aug[col][col])
        aug[col] = [_gf_mul(inv, v) for v in aug[col]]
        for r in range(k):
            if r == col or not aug[r][col]:
                continue
            f = aug[r][col]
            aug[r] = [v ^ _gf_mul(f, w)
                      for v, w in zip(aug[r], aug[col])]
    return [row[k:] for row in aug]


def encode(blob, k, m):
    """blob (bytes or uint8 array) -> n = k+m uint8 shards of equal
    ``chunk_len = ceil(len(blob)/k)``. Shards 0..k-1 are the data
    chunks verbatim (systematic: an all-data decode is a concat),
    k..n-1 the Cauchy parity."""
    if isinstance(blob, (bytes, bytearray, memoryview)):
        blob = np.frombuffer(blob, np.uint8)
    blob = np.ascontiguousarray(blob).view(np.uint8).reshape(-1)
    k, m = int(k), int(m)
    if k < 1 or m < 0 or k + m > 256:
        raise ValueError("bad code parameters k=%d m=%d" % (k, m))
    chunk = max(1, -(-blob.size // k))
    padded = np.zeros(k * chunk, np.uint8)
    padded[:blob.size] = blob
    data = [padded[i * chunk:(i + 1) * chunk] for i in range(k)]
    shards = list(data)
    for row in _parity_rows(k, m):
        acc = _gf_dot(row, data, chunk)
        shards.append(acc)
    return shards


def decode(shards, k, m, blob_len):
    """Rebuild the blob from any k of the n shards.

    ``shards``: {shard_index: uint8 array}. Raises RedundancyError
    when fewer than k shards are present (reason
    ``insufficient_partners``)."""
    k, m = int(k), int(m)
    have = {int(i): np.ascontiguousarray(v).view(np.uint8).reshape(-1)
            for i, v in shards.items()}
    if len(have) < k:
        e = errors.RedundancyError(
            "decode needs %d shards, have %d" % (k, len(have)))
        e.reason = "insufficient_partners"
        raise e
    # prefer data shards: every present one is a free (identity) row
    use = sorted(i for i in have if i < k)
    use += sorted(i for i in have if i >= k)
    use = use[:k]
    chunk = have[use[0]].size
    if any(have[i].size != chunk for i in use):
        raise errors.RedundancyError("shard length mismatch")
    if use == list(range(k)):  # all data shards survived
        out = np.concatenate([have[i] for i in use]) if use else \
            np.empty(0, np.uint8)
        return out[:int(blob_len)]
    rows = _parity_rows(k, m)
    mat = [([1 if c == i else 0 for c in range(k)] if i < k
            else rows[i - k]) for i in use]
    inv = _gf_matinv(mat)
    chunks = []
    for j in range(k):
        terms = [(c, i) for c, i in zip(inv[j], use) if c]
        if len(terms) == 1 and terms[0][0] == 1:
            # identity row (surviving data shard): concatenate below
            # is the only copy this chunk ever pays
            chunks.append(have[terms[0][1]])
            continue
        chunks.append(_gf_dot([c for c, _ in terms],
                              [have[i] for _, i in terms], chunk))
    return np.concatenate(chunks)[:int(blob_len)]


# -- snapshot blob ----------------------------------------------------------

def pack_snapshot(entries, dtypes, meta=None):
    """Pack a StateServer snapshot ({skey: host ndarray}, dtype tags,
    meta) into one contiguous uint8 blob: an 8-byte little-endian
    header length, a JSON header (schema redundancy_blob/v1 with
    per-entry dtype/shape/offset), then the raw entry bytes."""
    recs, bufs, off = [], [], 0
    for skey in sorted(entries):
        # asarray(order="C"), NOT ascontiguousarray: the latter
        # promotes 0-d scalars to shape (1,) and the header must
        # record the true shape
        arr = np.asarray(entries[skey], order="C")
        flat = (np.frombuffer(memoryview(arr).cast("B"), np.uint8)
                if arr.nbytes else np.empty(0, np.uint8))
        recs.append({"skey": skey, "dtype": arr.dtype.str,
                     "shape": list(arr.shape),
                     "nbytes": int(arr.nbytes), "offset": off})
        bufs.append(flat)
        off += int(arr.nbytes)
    head = json.dumps({"schema": "redundancy_blob/v1",
                       "dtypes": dict(dtypes), "meta": meta,
                       "entries": recs}).encode("utf-8")
    blob = np.empty(8 + len(head) + off, np.uint8)
    blob[:8] = np.frombuffer(struct.pack("<Q", len(head)), np.uint8)
    blob[8:8 + len(head)] = np.frombuffer(head, np.uint8)
    pos = 8 + len(head)
    for flat in bufs:
        blob[pos:pos + flat.size] = flat
        pos += flat.size
    return blob


def unpack_snapshot(blob):
    """Inverse of :func:`pack_snapshot` -> (entries, dtypes, meta)."""
    blob = np.ascontiguousarray(blob).view(np.uint8).reshape(-1)
    hlen = struct.unpack("<Q", blob[:8].tobytes())[0]
    head = json.loads(blob[8:8 + hlen].tobytes().decode("utf-8"))
    if head.get("schema") != "redundancy_blob/v1":
        raise errors.RedundancyError(
            "bad blob schema: %r" % (head.get("schema"),))
    base = 8 + hlen
    entries = {}
    for rec in head["entries"]:
        lo = base + int(rec["offset"])
        raw = blob[lo:lo + int(rec["nbytes"])]
        entries[rec["skey"]] = raw.view(
            np.dtype(rec["dtype"])).reshape(tuple(rec["shape"]))
    return entries, head.get("dtypes") or {}, head.get("meta")


# -- partner ring -----------------------------------------------------------

def partner_ring(members, self_id, n):
    """The next ``n`` members after ``self_id`` in the sorted cyclic
    order of the member-id set, self excluded. A pure function of the
    set — every pod computes identical rings from the same cluster
    map (the relay-tree trick), so partner assignment survives any
    resize with no negotiation and no tie-breaks."""
    ids = sorted({str(x) for x in members} | {str(self_id)})
    me = ids.index(str(self_id))
    others = [ids[(me + 1 + i) % len(ids)] for i in range(len(ids) - 1)]
    return others[:max(0, int(n))]


def _discover(coord, self_endpoint=None):
    """Sorted [(member_key, endpoint)] from SERVICE_REDUNDANCY leases
    (self excluded by endpoint)."""
    recs = coord.get_service(constants.SERVICE_REDUNDANCY)
    out = []
    for key, value in recs:
        try:
            rec = json.loads(value)
        except ValueError:
            continue
        endpoint = rec.get("endpoint")
        if not endpoint or endpoint == self_endpoint:
            continue
        out.append((str(key), endpoint))
    return sorted(out)


# -- push (the commit-path hand-off) ----------------------------------------

def push_shards(coord, owner, version, entries, dtypes, meta=None,
                self_endpoint=None, k=None, m=None, timeout=20.0):
    """Encode this pod's freshly committed snapshot and push one shard
    to each partner on its ring. Called from the async-save commit
    hand-off (the same driver-thread hook that publishes to the
    StateServer), so it never blocks a training step.

    Strictly best-effort: per-partner failures are logged and
    counted, never raised — a missing push only narrows the rebuild
    margin for THIS version. When fewer than k+m partners are alive
    the code shrinks (n = live partners, m' = min(m, n-1)); a single
    partner degenerates to one full replica shard.

    Returns {"partners", "pushed", "k", "m", "nbytes", "version"}."""
    t0 = time.perf_counter()
    if k is None or m is None:
        dk, dm = coding_params()
        k = dk if k is None else int(k)
        m = dm if m is None else int(m)
    try:
        live = dict(_discover(coord, self_endpoint))
    except errors.EdlError as e:
        logger.warning("redundancy: partner discovery failed (%r); "
                       "no shards pushed for v%s", e, version)
        return {"partners": 0, "pushed": 0, "k": 0, "m": 0,
                "nbytes": 0, "version": int(version)}
    ring = [(key, live[key]) for key in
            partner_ring(list(live) + [str(owner)], str(owner), k + m)
            if key in live]
    if not ring:
        logger.info("redundancy: no live partners; v%s not redundant",
                    version)
        return {"partners": 0, "pushed": 0, "k": 0, "m": 0,
                "nbytes": 0, "version": int(version)}
    n = min(k + m, len(ring))
    m_eff = min(m, n - 1)
    k_eff = n - m_eff
    if faults.PLANE is not None:
        faults.PLANE.fire("redundancy.encode", owner=str(owner),
                          version=str(version))
    blob = pack_snapshot(entries, dtypes, meta)
    shards = encode(blob, k_eff, m_eff)
    header = {"k": k_eff, "m": m_eff, "blob_len": int(blob.size),
              "chunk_len": int(shards[0].size)}
    inflight = []
    for idx, (pkey, endpoint) in enumerate(ring[:n]):
        client = None
        try:
            if faults.PLANE is not None:
                faults.PLANE.fire("redundancy.push", endpoint=endpoint,
                                  owner=str(owner), shard=str(idx))
            client = RpcClient(endpoint, timeout=timeout)
            fut = client.call_async("state.shard_put", str(owner),
                                    int(version), idx, header,
                                    shards[idx], timeout=timeout)
            inflight.append((endpoint, client, fut))
        except Exception as e:  # noqa: BLE001 — any partner may be gone
            logger.warning("redundancy: shard %d push to %s failed at "
                           "dial (%r)", idx, endpoint, e)
            if client is not None:
                client.close()
    pushed = 0
    for endpoint, client, fut in inflight:
        try:
            fut.result()
            pushed += 1
        except Exception as e:  # noqa: BLE001
            logger.warning("redundancy: shard push to %s failed (%r)",
                           endpoint, e)
        finally:
            client.close()
    _PUSH_MS.observe((time.perf_counter() - t0) * 1e3)
    obs_events.emit("redundancy.pushed", owner=str(owner),
                    version=int(version), pushed=pushed,
                    partners=len(ring), k=k_eff, m=m_eff)
    return {"partners": len(ring), "pushed": pushed, "k": k_eff,
            "m": m_eff, "nbytes": int(blob.size),
            "version": int(version)}


# -- rebuild (the diskless rung) --------------------------------------------

def _holders(coord, self_endpoint=None, timeout=20.0):
    """[(key, endpoint, client, shard_manifest)] for live redundancy
    peers; open clients are the caller's to close."""
    members = _discover(coord, self_endpoint)
    inflight = []
    for key, endpoint in members:
        client = None
        try:
            client = RpcClient(endpoint, timeout=timeout)
            fut = client.call_async("state.shard_manifest",
                                    timeout=timeout)
            inflight.append((key, endpoint, client, fut))
        except Exception as e:  # noqa: BLE001
            logger.warning("redundancy: holder %s unreachable (%r)",
                           endpoint, e)
            if client is not None:
                client.close()
    holders = []
    for key, endpoint, client, fut in inflight:
        try:
            manifest = fut.result()
        except Exception as e:  # noqa: BLE001
            logger.warning("redundancy: shard manifest from %s failed "
                           "(%r)", endpoint, e)
            client.close()
            continue
        holders.append((key, endpoint, client, manifest))
    return holders


def _issue_shard(client, owner, version, idx, nbytes, chunk, timeout):
    """Issue the pipelined chunked range-reads for one shard; returns
    the future list (join with :func:`_join_shard`). Issuing for every
    needed shard BEFORE joining any overlaps the transfers across
    holders — each holder is a distinct server, so the wall clock is
    the slowest single shard, not the sum."""
    if nbytes <= 0:
        return []
    return [client.call_async("state.shard", str(owner), int(version),
                              int(idx), off, min(chunk, nbytes - off),
                              timeout=timeout)
            for off in range(0, nbytes, chunk)]


def _join_shard(futs, owner, idx, nbytes):
    if nbytes <= 0:
        return np.empty(0, np.uint8)
    parts = [np.asarray(f.result()) for f in futs]
    data = parts[0] if len(parts) == 1 else np.concatenate(parts)
    data = np.ascontiguousarray(data).view(np.uint8).reshape(-1)
    if data.size != nbytes:
        raise IOError("shard %s/%d: got %d bytes, want %d"
                      % (owner, idx, data.size, nbytes))
    return data


def _fetch_shard(client, owner, version, idx, nbytes, chunk, timeout):
    """Blocking single-shard fetch (the sequential fallback path)."""
    futs = _issue_shard(client, owner, version, idx, nbytes, chunk,
                        timeout)
    return _join_shard(futs, owner, idx, nbytes)


def rebuild_owner(holders, owner, version, timeout=20.0, chunk=_CHUNK):
    """Decode one dead owner's snapshot at ``version`` from any k of
    its live shards -> (entries, dtypes, meta). Stale-versioned
    holders are skipped (never decoded); a holder dying mid-fetch is
    survived by falling to the remaining shard indices. Raises
    RedundancyError (with a ``reason`` attribute) when the surviving
    shard set is insufficient."""
    owner = str(owner)
    by_idx = {}  # shard index -> [(client, endpoint), ...]
    header = None
    stale = 0
    for _key, endpoint, client, manifest in holders:
        rec = (manifest.get("shards") or {}).get(owner)
        if not rec:
            continue
        if int(rec.get("version", -1)) != int(version):
            stale += 1
            continue
        header = rec
        for idx in rec.get("held") or []:
            by_idx.setdefault(int(idx), []).append((client, endpoint))
    if header is None or len(by_idx) < int(header["k"]):
        reason = "stale_version" if stale else "insufficient_partners"
        e = errors.RedundancyError(
            "owner %s@v%s: %d shard index(es) live (%d stale "
            "holder(s)), need k=%s" % (owner, version, len(by_idx),
                                       stale,
                                       header["k"] if header else "?"))
        e.reason = reason
        raise e
    k = int(header["k"])
    # data shards first (identity rows decode for free), then parity;
    # keep fetching past k failures until the indices run out
    order = sorted(by_idx, key=lambda i: (i >= k, i))
    nbytes = int(header["chunk_len"])
    got = {}
    # fast path: issue k+1 shards concurrently (one holder each) and
    # join in preference order, stopping at k — the +1 hedge means a
    # single holder dying mid-rebuild (the common failure while a dead
    # pod is being rebuilt) costs no serial refetch, at one shard of
    # extra transfer that overlaps the needed ones anyway
    inflight = []
    for idx in order[:k + 1]:
        client, endpoint = by_idx[idx][0]
        try:
            inflight.append((idx, endpoint, _issue_shard(
                client, owner, version, idx, nbytes, chunk, timeout)))
        except Exception as e:  # noqa: BLE001 — holder already gone
            logger.warning("redundancy: shard %s/%d issue to %s failed "
                           "(%r)", owner, idx, endpoint, e)
    for idx, endpoint, futs in inflight:
        if len(got) >= k:
            break
        try:
            got[idx] = _join_shard(futs, owner, idx, nbytes)
        except Exception as e:  # noqa: BLE001 — holder died mid-read
            logger.warning("redundancy: shard %s/%d from %s failed "
                           "(%r)", owner, idx, endpoint, e)
    # slow path: anything still short is retried sequentially over
    # every remaining (index, holder) alternative
    for idx in order:
        if len(got) >= k:
            break
        if idx in got:
            continue
        for client, endpoint in by_idx[idx]:
            try:
                got[idx] = _fetch_shard(client, owner, version, idx,
                                        nbytes, chunk, timeout)
                break
            except Exception as e:  # noqa: BLE001 — holder died mid-read
                logger.warning("redundancy: shard %s/%d from %s failed "
                               "(%r)", owner, idx, endpoint, e)
    if len(got) < k:
        e = errors.RedundancyError(
            "owner %s@v%s: fetched %d of k=%d shards"
            % (owner, version, len(got), k))
        e.reason = "insufficient_partners"
        raise e
    blob = decode(got, k, int(header["m"]), int(header["blob_len"]))
    return unpack_snapshot(blob)


def fill_from_parity(coord, version, pt, self_endpoint=None,
                     timeout=20.0):
    """Fill a PlacedTarget's still-missing spans by decoding dead
    owners' parity shards held by survivors — ZERO FS reads. The
    caller has already pasted everything it holds locally and (when
    live) everything peers serve; what remains is exactly the dead
    pods' unique spans.

    Returns {"parity_bytes", "owners", "holders", "meta", "reason"}
    (reason set when some rebuild was skipped). Raises
    RedundancyError only when no holder is reachable at all. Never
    raises on per-owner failure: the FS rung below stays the lossless
    backstop."""
    from edl_tpu.runtime.checkpoint import _parse_spans, _untag_array
    t0 = time.perf_counter()
    holders = _holders(coord, self_endpoint, timeout)
    if not holders:
        _fallback("insufficient_partners", version=int(version))
        raise errors.RedundancyError(
            "no redundancy holders alive for v%s" % (version,))
    try:
        owners = sorted({o for _k, _e, _c, man in holders
                         for o in (man.get("shards") or {})})
        parity_bytes = 0
        rebuilt = []
        meta = None
        reason = None
        for owner in owners:
            missing = pt.missing()
            if not missing:
                break
            try:
                if faults.PLANE is not None:
                    faults.PLANE.fire("redundancy.rebuild",
                                      owner=str(owner),
                                      version=str(version))
            except Exception:  # noqa: BLE001 — injected chaos
                reason = "fault"
                _fallback("fault", owner=str(owner),
                          version=int(version))
                continue
            try:
                entries, dtypes, meta_o = rebuild_owner(
                    holders, owner, version, timeout)
            except errors.RedundancyError as e:
                reason = getattr(e, "reason", "error")
                _fallback(reason, owner=str(owner),
                          version=int(version))
                logger.info("redundancy: rebuild of %s skipped (%r)",
                            owner, e)
                continue
            except Exception as e:  # noqa: BLE001
                reason = "error"
                _fallback("error", owner=str(owner),
                          version=int(version))
                logger.warning("redundancy: rebuild of %s failed (%r)",
                               owner, e)
                continue
            pasted = 0
            for skey, arr in entries.items():
                key, _, spans_s = skey.rpartition("@")
                if key not in missing:
                    continue
                entry_spans = _parse_spans(spans_s)
                pt.check_bounds(key, entry_spans)
                if not pt.overlaps_local(key, entry_spans):
                    continue
                pt.paste(key, entry_spans,
                         _untag_array(np.ascontiguousarray(arr),
                                      dtypes.get(key)))
                pasted += arr.nbytes
            if pasted:
                rebuilt.append(str(owner))
                parity_bytes += pasted
            if meta is None:
                meta = meta_o
        _REBUILD_MS.observe((time.perf_counter() - t0) * 1e3)
        if rebuilt:
            obs_events.emit("redundancy.rebuilt", version=int(version),
                            owners=",".join(rebuilt),
                            nbytes=int(parity_bytes))
        return {"parity_bytes": int(parity_bytes), "owners": rebuilt,
                "holders": len(holders), "meta": meta,
                "reason": reason}
    finally:
        for _key, _endpoint, client, _manifest in holders:
            client.close()


def restore_placed(coord, version, target, shardings,
                   self_endpoint=None, timeout=20.0):
    """Wholesale placed restore decoded purely from partner shards —
    the rung the trainer tries when NO live peer serves the version
    (every data-holding pod of the old world is gone) before paying
    the cold FS restore. Returns (version, tree, meta, stats); raises
    RedundancyError when spans remain missing (the caller falls to
    FS)."""
    from edl_tpu.runtime.checkpoint import PlacedTarget
    pt = PlacedTarget(target, shardings)
    stats = fill_from_parity(coord, version, pt,
                             self_endpoint=self_endpoint,
                             timeout=timeout)
    missing = pt.missing()
    if missing:
        raise errors.RedundancyError(
            "parity rebuild left %d key(s) missing: %s"
            % (len(missing), sorted(missing)[:5]))
    meta = stats.pop("meta", None)
    out = {"source": "parity", "parity_bytes": stats["parity_bytes"],
           "owners": stats["owners"], "holders": stats["holders"]}
    return int(version), pt.assemble(), meta, out


# -- analytic plan (costmodel composition) ----------------------------------

def rebuild_plan(leaves, src_axes, dst_axes, lost_devices):
    """Price a rebuild-into-a-new-factorization after losing
    ``lost_devices`` (source-mesh device indices): compose the parity
    decode with the costmodel's span addressing.

    leaves: [(shape, itemsize, src_spec, dst_spec)] — the same record
    ``costmodel.tree_reshard_bytes`` takes. For every distinct block
    of the destination placement, the bytes are classed by where they
    can come from: a surviving source device that holds them
    (``survivor_bytes``, plain ``state.read`` peer traffic) or ONLY
    lost devices (``parity_bytes``, must come out of the decode).
    ``reshard_bytes`` is ``tree_reshard_bytes``' wire total for the
    same move, so callers can report the parity fraction of the
    resize."""
    lost = {int(d) for d in lost_devices}
    parity = survivor = 0
    for shape, itemsize, src_spec, dst_spec in leaves:
        src = costmodel.device_spans(shape, src_spec, src_axes)
        dst = costmodel.device_spans(shape, dst_spec, dst_axes)
        src_boxes = {}  # distinct source block -> holder device set
        for dev, spans in src.items():
            src_boxes.setdefault(tuple(spans), set()).add(dev)
        seen = set()
        for _dev, spans in dst.items():
            box = tuple(spans)
            if box in seen:  # dst replicas fan out after one fetch
                continue
            seen.add(box)
            for sbox, devs in src_boxes.items():
                vol = costmodel._overlap_volume(box, sbox) * itemsize
                if not vol:
                    continue
                if devs - lost:
                    survivor += vol
                else:
                    parity += vol
    moved, needed = costmodel.tree_reshard_bytes(leaves, src_axes,
                                                 dst_axes)
    return {"parity_bytes": int(parity),
            "survivor_bytes": int(survivor),
            "reshard_bytes": int(moved),
            "needed_bytes": int(needed)}
