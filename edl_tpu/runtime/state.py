"""Elastic training state: epoch/step bookkeeping, data checkpoint, and
resize-time adjustment hooks, persisted in the coordination store.

Reference parity: edl/utils/state.py — DataCheckpoint (:25-31), EpochAttr
(:34-39), TrainStatus epoch map + global step (:61-111), State with
register_adjust_function (:142) and leader-guarded store save (:186-200).
The model/optimizer tensors themselves go through
edl_tpu.runtime.checkpoint; this is the small metadata the control plane
needs to reason about progress and resizes.
"""

from edl_tpu.controller import constants
from edl_tpu.utils.json_serializable import Serializable

STATE_SERVER = "state"


class DataCheckpoint(Serializable):
    """Which input files exist and which record ranges are consumed —
    enables data-aware resume without re-reading finished shards."""

    def __init__(self):
        self.file_list = []
        self.processed = {}  # file_name -> [[begin, end], ...]

    def mark_processed(self, file_name, begin, end):
        spans = self.processed.setdefault(file_name, [])
        spans.append([begin, end])
        spans.sort()
        merged = []
        for b, e in spans:
            if merged and b <= merged[-1][1] + 1:
                merged[-1][1] = max(merged[-1][1], e)
            else:
                merged.append([b, e])
        self.processed[file_name] = merged

    def is_processed(self, file_name, idx):
        return any(b <= idx <= e
                   for b, e in self.processed.get(file_name, []))


class EpochAttr(Serializable):
    def __init__(self):
        self.epoch_no = -1
        self.world_size = 0
        self.step_num = 0
        self.avg_step_time = 0.0
        self.ended = False


class State(Serializable):
    _json_types = {"data_checkpoint": DataCheckpoint}

    def __init__(self, total_batch_size=0, user_defined=None):
        self.total_batch_size = total_batch_size
        self.global_step = 0
        self.epoch_no = -1
        self.epochs = {}  # str(epoch_no) -> EpochAttr dict
        self.data_checkpoint = DataCheckpoint()
        self.user_defined = user_defined or {}
        self._adjust_fns = []  # not serialized (leading underscore skipped)

    # -- epochs --------------------------------------------------------------

    def begin_epoch(self, epoch_no, world_size):
        self.epoch_no = epoch_no
        attr = EpochAttr()
        attr.epoch_no = epoch_no
        attr.world_size = world_size
        self.epochs[str(epoch_no)] = attr.to_dict()

    def end_epoch(self, step_num, avg_step_time):
        attr = self.epochs.get(str(self.epoch_no), {})
        attr["step_num"] = step_num
        attr["avg_step_time"] = avg_step_time
        attr["ended"] = True
        self.epochs[str(self.epoch_no)] = attr

    def next_epoch(self):
        """The epoch a restart should run: the interrupted epoch itself
        when the newest checkpoint was written mid-epoch (emergency
        preemption save) — the epoch is re-run from its start so none of
        its data is skipped (already-consumed batches are replayed;
        exactly-once resume is the ElasticReader/data_checkpoint path) —
        else the one after the last completed epoch. Older checkpoints
        lack the ``ended`` flag but were only ever written at epoch end,
        so the compat default is True."""
        attr = self.epochs.get(str(self.epoch_no))
        if attr is not None and not attr.get("ended", True):
            return self.epoch_no
        return self.epoch_no + 1

    # -- resize hooks --------------------------------------------------------

    def register_adjust_function(self, fn):
        """fn(state, new_world_size) called when the world resizes —
        the hyperparameter re-adjustment hook of the reference
        (state.py:142; doc/edl_collective_design_doc.md:15-17)."""
        self._adjust_fns.append(fn)

    def adjust(self, new_world_size):
        for fn in self._adjust_fns:
            fn(self, new_world_size)

    def carry_hooks_to(self, other):
        """Transfer registered adjust hooks onto ``other`` (a State
        deserialized from a checkpoint — hooks are process-local and never
        serialized). Returns ``other``."""
        other._adjust_fns = list(self._adjust_fns)
        return other

    # -- serialization (skip private attrs) ----------------------------------

    def to_dict(self):
        return {k: (v.to_dict() if isinstance(v, Serializable) else v)
                for k, v in self.__dict__.items() if not k.startswith("_")}


def save_to_store(coord, state, leader_pod_id=None):
    """Persist; when ``leader_pod_id`` is given the write is guarded on that
    pod still holding leadership (reference state.py:186-200)."""
    value = state.to_json()
    if leader_pod_id is None:
        coord.set_server_permanent(constants.SERVICE_STATE, STATE_SERVER,
                                   value)
        return True
    key = coord.service_prefix(constants.SERVICE_STATE) + STATE_SERVER
    return coord.put_if_leader(constants.SERVICE_LEADER,
                               constants.LEADER_SERVER, leader_pod_id,
                               [(key, value)])


def load_from_store(coord):
    value = coord.get_value(constants.SERVICE_STATE, STATE_SERVER)
    if value is None:
        return None
    return State().from_json(value)
