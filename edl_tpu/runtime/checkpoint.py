"""Atomic, versioned pytree checkpointing with manifest-last commit.

Reference parity: Paddle Fleet's save/load_check_point with
write-temp-then-rename and version numbers (doc/fault_tolerance.md:20-25;
train_with_fleet.py:426-434,562-570). TPU twist: the commit protocol is
manifest-last (a version directory is valid iff its MANIFEST file exists and
checksums match), which also works on stores without atomic rename (GCS).

Layout (dense, the default):
    <dir>/v_00000012/arrays.npz   flat {path: ndarray} of the pytree leaves
    <dir>/v_00000012/meta.json    user metadata + dtype tags (bfloat16)
    <dir>/v_00000012/MANIFEST     written last: {"version", "crc"}

Layout (sharded — save_sharded/restore with a target):
    <dir>/v_00000012/STARTED             rank 0's go sentinel (dir reset
                                         done; other ranks may write)
    <dir>/v_00000012/arrays.r<k>.npz     rank k's owned array shards,
                                         keys "path@s0:e0;s1:e1;..."
    <dir>/v_00000012/shardmeta.r<k>.json rank k's crc + dtype tags
    <dir>/v_00000012/done.r<k>           rank k's publish marker, written
                                         after its data files close
    <dir>/v_00000012/meta.json, MANIFEST rank 0, after every rank's
                                         done marker is visible

Sharded mode is the scalable path: every host writes only its
addressable shards (no rank-0 gather, write bandwidth scales with host
count — the Orbax role); the commit stays manifest-last, with the
manifest recording every rank file's crc. Rank synchronization is by
filesystem visibility on the shared store (no device collectives — the
write may run from a background thread).

Layout (stream — the async snapshot-then-persist engine):
    <dir>/v_00000012/a0000.bin        one raw chunk-streamed file per
                                      array entry (r<k>_a<j>.bin sharded)
    <dir>/v_00000012/meta.json        user metadata + dtype tags
    <dir>/v_00000012/MANIFEST         written last: per-entry spans,
                                      files, crcs ("format": "stream")

The stream layout exists for the ASYNC save path (save_async /
save_sharded_async): phase 1 ("snapshot", on the training thread)
starts non-blocking device->host transfers for every owned shard and
copies them into reused host buffers, then returns a SaveHandle; phase
2 ("persist", a background writer pool) streams each entry straight to
its own file in fixed-size chunks — no monolithic npz BytesIO double
copy — computing crc32 incrementally over the stream, and commits the
MANIFEST only after every writer finishes. max_inflight is 1: a new
save first drains the previous one (which also makes the host-buffer
reuse safe). Crashed async attempts leave no MANIFEST and are removed
by clean_uncommitted() like any other uncommitted dir.
"""

import io
import json
import threading
import time
import uuid
import zlib
from concurrent.futures import ThreadPoolExecutor

import jax
import numpy as np

from edl_tpu.obs import events as obs_events
from edl_tpu.obs import ledger as obs_ledger
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.runtime.fs import get_fs
from edl_tpu.utils.logger import logger

_SAVE_MS = obs_metrics.histogram(
    "edl_ckpt_save_ms", "checkpoint save wall time to manifest commit",
    labels=("mode",))
_RESTORE_MS = obs_metrics.histogram(
    "edl_ckpt_restore_ms", "checkpoint restore wall time")

try:
    import ml_dtypes
    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BFLOAT16 = None

_SEP = "/"


class MissingKeysError(IOError):
    """The checkpoint is valid but lacks keys the restore target needs
    (e.g. a legacy checkpoint without the model's extra state)."""

    def __init__(self, keys):
        super().__init__("checkpoint missing keys: %s" % sorted(keys))
        self.keys = frozenset(keys)


def _path_key(path):
    return _SEP.join(str(getattr(p, "key", getattr(p, "idx", p)))
                     for p in path)


def _flatten(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return {_path_key(p): np.asarray(leaf) for p, leaf in flat}, treedef


def to_host_tree(tree):
    """Fetch a (possibly sharded) device pytree to host numpy, multi-host
    safe: leaves that are not fully addressable from this process (e.g.
    tp-sharded across hosts) are all-gathered over jax.distributed first
    — the shared-FS checkpoint write needs the GLOBAL array (reference
    role: rank-0 fleet.save_check_point of the full model)."""
    def fetch(x):
        if getattr(x, "is_fully_addressable", True):
            return jax.device_get(x)
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return jax.tree_util.tree_map(fetch, tree)


def leaf_locally_fetchable(x):
    """True when ``x`` can reach host memory WITHOUT a collective: host
    data, fully addressable, or fully replicated (a complete local
    replica exists). The single predicate behind to_host_tree_local and
    the trainer's emergency-save eligibility check — they must agree."""
    return (not hasattr(x, "addressable_shards")
            or getattr(x, "is_fully_addressable", True)
            or getattr(x, "is_fully_replicated", False))


def to_host_tree_local(tree):
    """Fetch a device pytree to host numpy WITHOUT any collective: every
    leaf must satisfy leaf_locally_fetchable. This is the emergency-
    checkpoint fetch — preempted ranks cannot rendezvous, so a gather is
    off the table; raises ValueError on cross-host *sharded* leaves."""
    def fetch(x):
        if not leaf_locally_fetchable(x):
            raise ValueError("cross-host sharded leaf: no local replica "
                             "to fetch without a collective")
        if not hasattr(x, "addressable_shards"):
            return np.asarray(x)
        if getattr(x, "is_fully_addressable", True):
            return jax.device_get(x)
        return np.asarray(x.addressable_data(0))
    return jax.tree_util.tree_map(fetch, tree)


def _paths(tree):
    """Flat path keys + treedef without materializing leaves (target may
    hold ShapeDtypeStructs)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return [_path_key(p) for p, _ in flat], treedef


# -- shard-span codec: the ONE encode/decode pair for "s0:e0;s1:e1;..." --

def _concrete_spans(index, shape):
    """Slices -> ((start, stop), ...) with shape-resolved bounds."""
    return tuple((0 if sl.start is None else int(sl.start),
                  dim if sl.stop is None else int(sl.stop))
                 for sl, dim in zip(index, shape))


def _spans_str(spans):
    return ";".join("%d:%d" % ab for ab in spans)


def _parse_spans(s):
    return tuple((int(a), int(b))
                 for part in s.split(";") if part
                 for a, b in [part.split(":")])


# -- sharding record: the saved PartitionSpec tree + mesh axes ------------
#
# A checkpoint's entry spans say WHERE each saved block lives; the
# sharding record says WHY — the mesh axis names/sizes and the per-leaf
# PartitionSpec that produced those spans. Restore never needs it
# (PlacedTarget intersects spans against whatever target sharding the
# caller asks for), but the resize planner does: with the record, a
# target mesh's reshard cost and live-eligibility are computable from
# metadata alone, before any data is read. It rides the existing
# meta.json ("sharding" key), so legacy checkpoints simply lack it.


def sharding_record(shardings):
    """JSON-able record of a sharding pytree: the mesh axis names and
    sizes plus per-leaf PartitionSpec entries keyed by path. Leaves
    without a NamedSharding (single-device, callables) record None and
    read back as replicated."""
    flat, _ = jax.tree_util.tree_flatten_with_path(shardings)
    mesh = None
    specs = {}
    for path, sh in flat:
        key = _path_key(path)
        spec = getattr(sh, "spec", None)
        m = getattr(sh, "mesh", None)
        if spec is None or m is None:
            specs[key] = None
            continue
        if mesh is None:
            mesh = {"axes": [str(a) for a in m.axis_names],
                    "shape": {str(a): int(m.shape[a])
                              for a in m.axis_names}}
        specs[key] = [list(e) if isinstance(e, (tuple, list)) else e
                      for e in spec]
    return {"mesh": mesh, "specs": specs}


def spec_from_record(entry):
    """PartitionSpec from one ``sharding_record`` specs entry (None or
    missing -> fully replicated)."""
    from jax.sharding import PartitionSpec
    if not entry:
        return PartitionSpec()
    return PartitionSpec(*[tuple(e) if isinstance(e, list) else e
                           for e in entry])


# -- stream-format plumbing (the async snapshot/persist engine) -----------

_CHUNK = 4 << 20  # fixed-size streaming chunk for entry files


def _wire_entry(arr):
    """(wire_array, dtype_tag|None): dtypes without the buffer protocol
    ship as a POD view — bfloat16 as uint16, datetime/timedelta as
    int64 — and the tag restores the view on read."""
    if _BFLOAT16 is not None and arr.dtype == _BFLOAT16:
        return arr.view(np.uint16), "bfloat16"
    if arr.dtype.kind in "mM":
        return arr.view(np.int64), arr.dtype.str
    return arr, None


def _untag_array(arr, tag):
    """Inverse of _wire_entry's tagging (also decodes the legacy npz
    layout's bfloat16 tag)."""
    if not tag:
        return arr
    if tag == "bfloat16":
        if _BFLOAT16 is None:  # pragma: no cover
            raise IOError("bfloat16 checkpoint needs ml_dtypes")
        return arr.view(_BFLOAT16)
    return arr.view(np.dtype(tag))


def _start_host_transfers(tree):
    """Kick off non-blocking device->host DMAs for every addressable
    shard of every jax leaf, so the per-shard np.asarray fetches that
    follow overlap instead of serializing (phase 1 of the async save)."""
    for leaf in jax.tree_util.tree_leaves(tree):
        for s in getattr(leaf, "addressable_shards", ()):
            start = getattr(s.data, "copy_to_host_async", None)
            if start is not None:
                try:
                    start()
                except Exception:  # pragma: no cover — best-effort
                    return


class _HostBufferPool(object):
    """Reusable host staging buffers for snapshots, keyed by entry key.
    Reuse across versions avoids a fresh multi-GB allocation per save;
    it is safe exactly because max_inflight=1 — the previous persist is
    drained before a new snapshot touches the buffers."""

    def __init__(self):
        self._bufs = {}

    def copy_in(self, key, arr):
        arr = np.asarray(arr)
        buf = self._bufs.get(key)
        if buf is None or buf.shape != arr.shape or buf.dtype != arr.dtype:
            buf = np.empty(arr.shape, arr.dtype)
            self._bufs[key] = buf
        np.copyto(buf, arr)
        return buf


class SaveHandle(object):
    """Completion handle for an async checkpoint save.

    ``blocked_s`` is the training-thread (snapshot) time; ``persist_s``
    the background write time, set once the persist finishes. wait()
    blocks without raising; result() re-raises any persist failure."""

    def __init__(self, version):
        self.version = version
        self.blocked_s = 0.0
        self.persist_s = None
        self._evt = threading.Event()
        self._vdir = None
        self._exc = None

    def done(self):
        return self._evt.is_set()

    def wait(self, timeout=None):
        return self._evt.wait(timeout)

    def exception(self):
        return self._exc

    def result(self, timeout=None):
        if not self._evt.wait(timeout):
            raise TimeoutError("checkpoint v%d persist still running"
                               % self.version)
        if self._exc is not None:
            raise self._exc
        return self._vdir

    def _finish(self, vdir, exc=None, persist_s=None):
        self._vdir = vdir
        self._exc = exc
        self.persist_s = persist_s
        self._evt.set()


class PlacedTarget(object):
    """The per-process fill plan of a placed (locality-aware) restore.

    Built from (target, shardings); holds, per leaf, the UNIQUE device
    blocks this process must fill (replicated leaves map every device to
    the same span — one shared host buffer, not one per device) plus the
    device -> span mapping for final assembly. Both the shared-FS path
    (CheckpointManager.restore_placed / fill_placed_from_fs) and the
    peer restore plane (runtime/state_server.PeerRestorer) paste saved
    extents into the SAME instance, which is what lets a partial peer
    fetch be completed span-by-span from the FS instead of starting
    over. Callers untag wire dtypes before paste()."""

    def __init__(self, target, shardings):
        flat_t, treedef = jax.tree_util.tree_flatten_with_path(target)
        flat_s = jax.tree_util.tree_leaves(shardings)
        if len(flat_s) != len(flat_t):
            raise ValueError("shardings tree does not match target")
        self._flat_t = flat_t
        self._treedef = treedef
        # key -> (shape, dtype, sharding, {spans: [buffer, filled]},
        #         {device: spans})
        self.need = {}
        for (path, leaf), sharding in zip(flat_t, flat_s):
            key = _path_key(path)
            shape = tuple(leaf.shape)
            dtype = np.dtype(leaf.dtype)
            dev_map = sharding.addressable_devices_indices_map(shape)
            blocks = {}
            dev_spans = {}
            for dev, index in dev_map.items():
                spans = _concrete_spans(index, shape)
                dev_spans[dev] = spans
                if spans not in blocks:
                    bshape = tuple(e - s for s, e in spans)
                    blocks[spans] = [np.zeros(bshape, dtype), 0]
            self.need[key] = (shape, dtype, sharding, blocks, dev_spans)

    def check_bounds(self, key, entry_spans):
        """A saved extent beyond the target shape must raise, even when
        the offending entry overlaps none of our blocks — otherwise
        in-bounds entries can complete coverage and the restore silently
        truncates the stored tensor."""
        shape = self.need[key][0]
        if len(entry_spans) != len(shape) or any(
                b > dim or a < 0
                for (a, b), dim in zip(entry_spans, shape)):
            raise IOError(
                "checkpoint shape mismatch for %r: saved spans %s "
                "vs target shape %s" % (key, entry_spans, shape))

    def overlaps_local(self, key, entry_spans):
        blocks = self.need[key][3]
        return any(
            all(max(a, c) < min(b, d)
                for (a, b), (c, d) in zip(entry_spans, spans))
            for spans in blocks)

    def needed_rows(self, key, entry_spans):
        """The entry-local contiguous leading-axis row hull [r0, r1)
        this process needs from an entry saved at ``entry_spans``, or
        None when the entry overlaps no local block. The hull may cover
        rows between disjoint blocks — over-read, never under-read.
        Scalars (rank-0 entries) return (0, 1): whole-entry reads."""
        blocks = self.need[key][3]
        lo = hi = None
        for spans in blocks:
            if not all(max(a, c) < min(b, d)
                       for (a, b), (c, d) in zip(entry_spans, spans)):
                continue
            if not entry_spans:
                return (0, 1)
            (a0, b0), (c0, d0) = entry_spans[0], spans[0]
            lo0, hi0 = max(a0, c0) - a0, min(b0, d0) - a0
            lo = lo0 if lo is None else min(lo, lo0)
            hi = hi0 if hi is None else max(hi, hi0)
        return None if lo is None else (lo, hi)

    def paste(self, key, entry_spans, arr):
        """Paste an (already untagged) saved extent into every
        overlapping local block (scalars: all spans empty -> full
        overlap)."""
        _, dtype, _, blocks, _ = self.need[key]
        for spans, blk in blocks.items():
            buf = blk[0]
            # intersect the saved entry with this device block
            lo = [max(a, c) for (a, _), (c, _) in
                  zip(entry_spans, spans)]
            hi = [min(b, d) for (_, b), (_, d) in
                  zip(entry_spans, spans)]
            if any(x >= y for x, y in zip(lo, hi)):
                continue
            src = tuple(slice(x - a, y - a) for (a, _), x, y in
                        zip(entry_spans, lo, hi))
            dst = tuple(slice(x - c, y - c) for (c, _), x, y in
                        zip(spans, lo, hi))
            buf[dst] = np.asarray(arr[src], dtype)
            blk[1] += int(np.prod([y - x for x, y in zip(lo, hi)],
                                  dtype=np.int64))

    def reset_key(self, key):
        """Zero a key's fill counters (buffers are simply overwritten):
        call before re-filling a key from a DIFFERENT source, so
        coverage accounting never double-counts overlapping pastes."""
        for blk in self.need[key][3].values():
            blk[1] = 0

    def missing(self):
        """Keys whose local blocks are not fully covered yet."""
        return {key for key, (_, _, _, blocks, _) in self.need.items()
                if any(blk[1] < blk[0].size for blk in blocks.values())}

    def filled_nbytes(self):
        """Bytes pasted so far (restore-size metric for timing logs)."""
        return sum(blk[1] * spec[1].itemsize
                   for key, spec in self.need.items()
                   for blk in spec[3].values())

    def assemble(self):
        """device_put every block and build the sharded jax.Arrays in
        the target's tree structure."""
        leaves = []
        for path, _ in self._flat_t:
            shape, _, sharding, blocks, dev_spans = \
                self.need[_path_key(path)]
            bufs = [jax.device_put(blocks[spans][0], dev)
                    for dev, spans in dev_spans.items()]
            leaves.append(jax.make_array_from_single_device_arrays(
                shape, sharding, bufs))
        return jax.tree_util.tree_unflatten(self._treedef, leaves)


class CheckpointManager(object):
    def __init__(self, directory, keep=3, fs=None, workers=4):
        self._dir = str(directory)
        self._fs = fs or get_fs(directory)
        self._keep = keep
        self._workers = max(1, int(workers))
        self._pool = None           # lazy writer/reader thread pool
        self._host_bufs = _HostBufferPool()
        self._inflight = None       # the (single) in-flight SaveHandle
        self._async_lock = threading.Lock()

    # -- helpers -------------------------------------------------------------

    def _io_pool(self):
        """The shared writer/reader pool: persist fan-out AND the
        parallel restore reads ride the same threads."""
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self._workers, thread_name_prefix="ckpt-io")
        return self._pool

    def drain(self):
        """Block until the in-flight async save (if any) finishes;
        returns its SaveHandle or None. A persist failure is logged, not
        raised (the manifest-last invariant already keeps the failed
        version invisible) — callers that must see the exception hold
        the handle and call result()."""
        with self._async_lock:
            h, self._inflight = self._inflight, None
        if h is not None:
            # drain() runs on the TRAINING thread (step boundary, resize
            # drain): the wait is attributed checkpoint-blocked time.
            # The writer pool's own concurrency is never ledgered.
            with obs_ledger.LEDGER.state("ckpt_block"):
                h.wait()
            if h.exception() is not None:
                logger.error("async checkpoint v%d failed: %r",
                             h.version, h.exception())
        return h

    def close(self):
        """Drain the in-flight save and shut the writer pool down."""
        self.drain()
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def _vdir(self, version):
        return "%s/v_%08d" % (self._dir, version)

    def versions(self):
        """Committed (manifest-valid) versions, ascending."""
        out = []
        for name in self._fs.listdir(self._dir):
            if name.startswith("v_"):
                try:
                    v = int(name[2:])
                except ValueError:
                    continue
                if self._fs.exists("%s/%s/MANIFEST" % (self._dir, name)):
                    out.append(v)
        return sorted(out)

    def meta(self, version):
        """User metadata of a committed version (the ``meta=`` blob the
        saver passed), or None when the version/meta is unreadable."""
        try:
            with self._fs.open(self._vdir(version) + "/meta.json",
                               "r") as f:
                return json.load(f).get("meta")
        except (IOError, OSError, ValueError):
            return None

    def saved_sharding(self, version):
        """The :func:`sharding_record` saved with ``version`` (meta key
        ``"sharding"``), or None for legacy/recordless checkpoints —
        which restore as "everything replicated" for planning purposes,
        matching what they actually were."""
        m = self.meta(version)
        return m.get("sharding") if isinstance(m, dict) else None

    def clean_uncommitted(self):
        """Delete version dirs without a MANIFEST — garbage from crashed
        save attempts (the manifest-last invariant makes them invisible
        to restore, but a stale STARTED sentinel inside one could let a
        later sharded save at the SAME version mis-order its barrier).
        Call at process start, before any save; in multi-host jobs only
        rank 0 should call it (concurrent deletes race)."""
        removed = []
        for name in self._fs.listdir(self._dir):
            if not name.startswith("v_"):
                continue
            try:
                int(name[2:])
            except ValueError:
                continue
            if not self._fs.exists("%s/%s/MANIFEST" % (self._dir, name)):
                self._fs.delete_tree("%s/%s" % (self._dir, name))
                removed.append(name)
        if removed:
            logger.info("cleaned %d uncommitted checkpoint dir(s): %s",
                        len(removed), removed)
        return removed

    # -- save ---------------------------------------------------------------

    def save(self, version, tree, meta=None):
        """Write checkpoint ``version``; commit is the MANIFEST write."""
        with obs_ledger.LEDGER.state("ckpt_block"):
            return self._save(version, tree, meta=meta)

    def _save(self, version, tree, meta=None):
        t0 = time.monotonic()
        vdir = self._vdir(version)
        self._fs.delete_tree(vdir)  # clear any half-written attempt
        self._fs.makedirs(vdir)

        arrays, _ = _flatten(tree)
        dtypes = {}
        to_save = {}
        for key, arr in arrays.items():
            if _BFLOAT16 is not None and arr.dtype == _BFLOAT16:
                dtypes[key] = "bfloat16"
                arr = arr.view(np.uint16)
            to_save[key] = arr
        buf = io.BytesIO()
        np.savez(buf, **to_save)
        payload = buf.getvalue()
        crc = zlib.crc32(payload)
        with self._fs.open(vdir + "/arrays.npz", "wb") as f:
            f.write(payload)
        with self._fs.open(vdir + "/meta.json", "w") as f:
            json.dump({"meta": meta or {}, "dtypes": dtypes}, f)
        # the commit point:
        with self._fs.open(vdir + "/MANIFEST", "w") as f:
            json.dump({"version": version, "crc": crc,
                       "nbytes": len(payload)}, f)
        logger.info("checkpoint v%d committed (%d arrays, %.1f MB)", version,
                    len(to_save), len(payload) / 1e6)
        _SAVE_MS.labels("sync").observe((time.monotonic() - t0) * 1e3)
        obs_events.emit("ckpt.saved", version=version, mode="sync",
                        nbytes=len(payload))
        self._gc()
        return vdir

    def _gc(self):
        versions = self.versions()
        for v in versions[:-self._keep] if self._keep else []:
            self._fs.delete_tree(self._vdir(v))

    # -- async save: snapshot phase ------------------------------------------

    def _snapshot_dense(self, tree):
        """Phase-1 snapshot of a full tree: {span_key: host ndarray}
        (wire dtypes) + dtype tags, copied into the reused buffer pool
        so later steps may donate/mutate the originals."""
        _start_host_transfers(tree)
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        entries = {}
        dtypes = {}
        for path, leaf in flat:
            key = _path_key(path)
            if not getattr(leaf, "is_fully_addressable", True):
                from jax.experimental import multihost_utils
                leaf = multihost_utils.process_allgather(leaf, tiled=True)
            arr, tag = _wire_entry(np.asarray(leaf))
            if tag:
                dtypes[key] = tag
            skey = self._shard_key(key, tuple(slice(0, d)
                                              for d in arr.shape),
                                   arr.shape)
            entries[skey] = self._host_bufs.copy_in(skey, arr)
        return entries, dtypes

    def _snapshot_sharded(self, tree, rank):
        """Phase-1 snapshot of this rank's OWNED shards (replica_id 0
        dedup; host/replicated-only leaves land on rank 0), mirroring
        what the sync sharded writer persists."""
        _start_host_transfers(tree)
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        entries = {}
        dtypes = {}

        def add(key, index, shape, arr):
            arr, tag = _wire_entry(np.asarray(arr))
            if tag:
                dtypes[key] = tag
            skey = self._shard_key(key, index, shape)
            entries[skey] = self._host_bufs.copy_in(skey, arr)

        for path, leaf in flat:
            key = _path_key(path)
            if hasattr(leaf, "addressable_shards") \
                    and hasattr(leaf, "sharding"):
                for s in leaf.addressable_shards:
                    if s.replica_id == 0:
                        add(key, s.index, leaf.shape, s.data)
            elif rank == 0:
                arr = np.asarray(leaf)
                add(key, tuple(slice(0, d) for d in arr.shape),
                    arr.shape, arr)
        return entries, dtypes

    # -- async save: persist phase -------------------------------------------

    def _write_entry_file(self, path, arr):
        """Stream one (contiguous, wire-dtype) array to its own file in
        fixed-size chunks with an incremental crc — no whole-payload
        BytesIO staging. Returns (nbytes, crc, chunk_crcs): the
        per-chunk crc list lands in the manifest so range reads (the
        placed restore / peer-restore FS fallback) can verify just the
        chunks they touch instead of the whole file."""
        arr = np.ascontiguousarray(arr)
        chunk_crcs = []
        if arr.nbytes == 0:
            nbytes, crc = self._fs.write_chunks(path, ())
            return nbytes, crc, chunk_crcs

        def chunks():
            view = memoryview(arr).cast("B")
            for off in range(0, len(view), _CHUNK):
                chunk = view[off:off + _CHUNK]
                chunk_crcs.append(zlib.crc32(chunk))
                yield chunk

        nbytes, crc = self._fs.write_chunks(path, chunks())
        return nbytes, crc, chunk_crcs

    def _read_entry_file(self, path, entry):
        """Read one stream entry back (chunked, incremental crc check),
        returning the wire-dtype array."""
        dtype = np.dtype(entry["dtype"])
        arr = np.empty(tuple(entry["shape"]), dtype)
        nbytes = int(entry["nbytes"])
        if arr.nbytes != nbytes:
            raise IOError("entry %s: %d bytes recorded vs %d expected"
                          % (path, nbytes, arr.nbytes))
        crc = 0
        got = 0
        view = memoryview(arr).cast("B") if nbytes else None
        with self._fs.open(path, "rb") as f:
            while got < nbytes:
                chunk = f.read(min(_CHUNK, nbytes - got))
                if not chunk:
                    raise IOError("entry %s truncated at %d/%d bytes"
                                  % (path, got, nbytes))
                view[got:got + len(chunk)] = chunk
                crc = zlib.crc32(chunk, crc)
                got += len(chunk)
        if crc != int(entry["crc"]):
            raise IOError("checksum mismatch in %s" % path)
        return arr

    def _read_entry_rows(self, path, entry, r0, r1):
        """Range-read rows [r0, r1) of a stream entry's LEADING axis via
        fs.read_range, chunk-aligned so the per-chunk crcs recorded at
        write time still verify (manifests from before the range-read
        extension lack chunk_crcs — callers route those through the
        whole-file _read_entry_file). Returns the wire-dtype array of
        shape (r1-r0,) + shape[1:]."""
        dtype = np.dtype(entry["dtype"])
        shape = tuple(entry["shape"])
        chunk_crcs = entry["chunk_crcs"]
        csize = int(entry.get("chunk", _CHUNK))
        nbytes = int(entry["nbytes"])
        rowbytes = (int(np.prod(shape[1:], dtype=np.int64))
                    * dtype.itemsize)
        b0, b1 = r0 * rowbytes, r1 * rowbytes
        c0 = b0 // csize
        c1 = min((b1 + csize - 1) // csize, len(chunk_crcs))
        off = c0 * csize
        want = min(c1 * csize, nbytes) - off
        data = self._fs.read_range(path, off, want) if want > 0 else b""
        if len(data) != want:
            raise IOError("entry %s: range read returned %d/%d bytes "
                          "at offset %d" % (path, len(data), want, off))
        for i in range(c0, c1):
            lo = i * csize - off
            hi = min((i + 1) * csize, nbytes) - off
            if zlib.crc32(data[lo:hi]) != int(chunk_crcs[i]):
                raise IOError("chunk %d checksum mismatch in %s"
                              % (i, path))
        out = np.frombuffer(data, np.uint8)[b0 - off:b1 - off]
        return out.view(dtype).reshape((r1 - r0,) + shape[1:])

    def _write_entries(self, vdir, prefix, entries):
        """Fan the entry files out across the writer pool; returns the
        manifest entry table {span_key: {file, dtype, shape, crc,
        nbytes, chunk, chunk_crcs}} and the total byte count."""
        pool = self._io_pool()
        futs = []
        for i, skey in enumerate(sorted(entries)):
            fname = "%sa%04d.bin" % (prefix, i)
            arr = entries[skey]
            futs.append((skey, fname, arr,
                         pool.submit(self._write_entry_file,
                                     "%s/%s" % (vdir, fname), arr)))
        table = {}
        total = 0
        for skey, fname, arr, fut in futs:
            nbytes, crc, chunk_crcs = fut.result()
            table[skey] = {"file": fname, "dtype": arr.dtype.str,
                           "shape": list(arr.shape), "crc": crc,
                           "nbytes": nbytes, "chunk": _CHUNK,
                           "chunk_crcs": chunk_crcs}
            total += nbytes
        return table, total

    def save_async(self, version, tree, meta=None, on_commit=None):
        """Two-phase async save. Snapshot runs HERE (fast device->host
        copies into pooled buffers), then control returns while a
        background driver streams the entries to per-array files and
        commits the MANIFEST last. Returns a SaveHandle; max_inflight
        is 1 — this call first drains any previous async save.
        ``on_commit`` (optional) runs on the driver thread right after
        the manifest commit — the hand-off point where the trainer
        publishes the committed snapshot to its StateServer and pushes
        erasure-coded shards to its redundancy partner ring
        (runtime/redundancy.py). Callbacks are best-effort observers
        of an already-durable commit: an on_commit failure is logged,
        never surfaced as a save failure."""
        self.drain()
        t0 = time.perf_counter()
        # the snapshot is the async save's only training-thread cost
        with obs_ledger.LEDGER.state("ckpt_block"):
            entries, dtypes = self._snapshot_dense(tree)
        handle = SaveHandle(version)
        handle.blocked_s = time.perf_counter() - t0

        def persist():
            p0 = time.perf_counter()
            try:
                vdir = self._vdir(version)
                self._fs.delete_tree(vdir)
                self._fs.makedirs(vdir)
                table, total = self._write_entries(vdir, "", entries)
                with self._fs.open(vdir + "/meta.json", "w") as f:
                    json.dump({"meta": meta or {}, "dtypes": dtypes}, f)
                # the commit point:
                with self._fs.open(vdir + "/MANIFEST", "w") as f:
                    json.dump({"version": version, "format": "stream",
                               "entries": table, "nbytes": total}, f)
                logger.info("checkpoint v%d committed async (%d entries,"
                            " %.1f MB)", version, len(table),
                            total / 1e6)
                _SAVE_MS.labels("async").observe(
                    (time.perf_counter() - p0) * 1e3)
                obs_events.emit("ckpt.saved", version=version,
                                mode="async", nbytes=total)
                self._gc()
                if on_commit is not None:
                    # the manifest is already durable: a failing
                    # commit observer (state publish, redundancy
                    # shard push) must not mark the save failed
                    try:
                        on_commit()
                    except Exception:
                        logger.exception(
                            "on_commit callback for v%d failed",
                            version)
                handle._finish(vdir,
                               persist_s=time.perf_counter() - p0)
            except BaseException as e:  # noqa: BLE001 — surfaces via result()
                handle._finish(None, exc=e,
                               persist_s=time.perf_counter() - p0)

        with self._async_lock:
            self._inflight = handle
        threading.Thread(target=persist, daemon=False,
                         name="ckpt-persist-%d" % version).start()
        return handle

    # -- sharded save --------------------------------------------------------

    @staticmethod
    def _owned_shards(leaf):
        """(index, ndarray) pairs this process must write: one entry per
        distinct shard (replica_id 0 de-duplicates replicas), or the
        whole array for host values / fully-replicated leaves on rank 0
        handled by the caller."""
        out = []
        for s in leaf.addressable_shards:
            if s.replica_id == 0:
                out.append((s.index, np.asarray(s.data)))
        return out

    @staticmethod
    def _shard_key(key, index, shape):
        return "%s@%s" % (key, _spans_str(_concrete_spans(index, shape)))

    def _fs_wait(self, predicate, what, timeout):
        deadline = time.monotonic() + timeout
        delay = 0.02
        while not predicate():
            if time.monotonic() > deadline:
                raise IOError("sharded save: timed out waiting for %s"
                              % what)
            time.sleep(delay)
            delay = min(delay * 1.5, 0.5)

    def save_sharded(self, version, tree, meta=None, rank=0, nranks=1,
                     barrier=None, timeout=120.0):
        """Cooperative sharded save: EVERY rank calls this with the same
        ``version``/``tree``; each writes only the shards it owns; rank 0
        commits the MANIFEST recording all rank files + crcs. Returns the
        version dir (all ranks).

        Synchronization is by FILESYSTEM VISIBILITY on the shared store
        (the premise of elastic checkpoints), not device collectives:
        rank 0 resets the version dir and drops a STARTED sentinel;
        other ranks wait for it before writing; each rank publishes a
        done.r<k> marker strictly after its data files close, and rank 0
        waits for every done marker before committing. This keeps the
        save legal from background writer threads (no collective may run
        off the main stream) and identical on GCS (no rename needed). An
        explicit ``barrier`` callable replaces the sentinel protocol
        when the caller already has a rendezvous (tests, jax.distributed
        sync points).

        The STARTED sentinel carries a per-attempt NONCE: ranks echo it
        in their done markers and rank 0 only accepts markers from the
        current attempt, so a sentinel left by a crashed or older
        attempt at the same version (restore fell back to an older
        version, zero-step epoch re-save) cannot mis-pair two attempts.
        A non-rank-0 rank that wrote against a stale nonce detects the
        mismatch after publishing and rewrites its files under the new
        nonce instead of silently losing them to rank 0's reset. The
        sentinel and done markers are removed at commit so committed
        version dirs never carry live protocol state; trainers still
        call clean_uncommitted() at process start for crashed attempts."""
        vdir = self._vdir(version)

        def write_rank_files():
            flat, _ = jax.tree_util.tree_flatten_with_path(tree)
            dtypes = {}
            to_save = {}
            for path, leaf in flat:
                key = _path_key(path)
                if hasattr(leaf, "addressable_shards") \
                        and hasattr(leaf, "sharding"):
                    shards = self._owned_shards(leaf)
                    # fully-replicated leaves land on every process with
                    # replica_id spread; only write replica 0's copy
                    for index, arr in shards:
                        to_save[self._shard_key(key, index, leaf.shape)] \
                            = arr
                        if _BFLOAT16 is not None \
                                and arr.dtype == _BFLOAT16:
                            dtypes[key] = "bfloat16"
                elif rank == 0:
                    arr = np.asarray(leaf)
                    index = tuple(slice(0, d) for d in arr.shape)
                    to_save[self._shard_key(key, index, arr.shape)] = arr
                    if _BFLOAT16 is not None and arr.dtype == _BFLOAT16:
                        dtypes[key] = "bfloat16"
            packed = {}
            for k, arr in to_save.items():
                if _BFLOAT16 is not None and arr.dtype == _BFLOAT16:
                    arr = arr.view(np.uint16)
                packed[k] = arr
            buf = io.BytesIO()
            np.savez(buf, **packed)
            payload = buf.getvalue()
            with self._fs.open("%s/arrays.r%d.npz" % (vdir, rank),
                               "wb") as f:
                f.write(payload)
            with self._fs.open("%s/shardmeta.r%d.json" % (vdir, rank),
                               "w") as f:
                json.dump({"crc": zlib.crc32(payload), "dtypes": dtypes,
                           "nbytes": len(payload)}, f)

        def commit(nonce):
            crcs = {}
            dtypes_all = {}
            for r in range(nranks):
                with self._fs.open("%s/shardmeta.r%d.json" % (vdir, r),
                                   "r") as f:
                    sm = json.load(f)
                crcs[str(r)] = sm["crc"]
                dtypes_all.update(sm["dtypes"])
            with self._fs.open(vdir + "/meta.json", "w") as f:
                json.dump({"meta": meta or {}, "dtypes": dtypes_all}, f)
            with self._fs.open(vdir + "/MANIFEST", "w") as f:
                json.dump({"version": version, "sharded": True,
                           "ranks": nranks, "crcs": crcs,
                           "attempt": nonce}, f)

        return self._sharded_protocol(version, rank, nranks, barrier,
                                      timeout, write_rank_files, commit)

    def _sharded_protocol(self, version, rank, nranks, barrier, timeout,
                          write_rank_files, commit):
        """The sentinel/nonce commit protocol shared by the npz (sync)
        and stream (async) sharded writers. ``write_rank_files()``
        writes this rank's data + shardmeta files (idempotent: it may
        run again under a fresh nonce after a stale-attempt reset);
        ``commit(nonce)`` is rank 0's manifest assembly, run only once
        every done marker carries the current nonce. The MANIFEST the
        commit writes MUST record ``attempt: nonce`` — the non-rank-0
        resolution loop keys on it."""
        t0 = time.monotonic()
        vdir = self._vdir(version)
        use_sentinel = barrier is None and nranks > 1
        nonce = None
        if rank == 0:
            self._fs.delete_tree(vdir)
            self._fs.makedirs(vdir)
            if use_sentinel:
                nonce = uuid.uuid4().hex
                with self._fs.open(vdir + "/STARTED", "w") as f:
                    f.write(nonce)
        if barrier is not None:
            barrier()  # rank0's directory reset must precede any write

        def read_sentinel():
            try:
                with self._fs.open(vdir + "/STARTED", "r") as f:
                    return f.read() or None
            except (IOError, OSError):
                return None

        if rank == 0 or not use_sentinel:
            write_rank_files()
            if use_sentinel:
                with self._fs.open("%s/done.r%d" % (vdir, rank),
                                   "w") as f:
                    f.write(nonce)
        else:
            # Write-then-wait-for-resolution loop. A rank cannot tell a
            # stale sentinel (crashed/older attempt) from rank 0 merely
            # being slow, so after publishing against nonce N it waits
            # until either the MANIFEST commits with attempt == N (rank
            # 0 only commits once every done marker carries its nonce,
            # so a matching commit proves our files belong to it) or the
            # sentinel's nonce changes (rank 0 reset the attempt we had
            # joined and deleted our files — rewrite under the new one).

            def manifest_attempt():
                try:
                    with self._fs.open(vdir + "/MANIFEST", "r") as f:
                        return json.load(f).get("attempt")
                except (IOError, OSError, ValueError):
                    return None

            deadline = time.monotonic() + timeout
            committed = False
            while not committed:
                self._fs_wait(
                    lambda: read_sentinel() is not None,
                    "rank 0 STARTED sentinel (v%d)" % version,
                    max(0.01, deadline - time.monotonic()))
                nonce = read_sentinel()
                if nonce is None:
                    continue
                try:
                    write_rank_files()
                    # done marker is written (and closed) strictly
                    # AFTER the data files: rank 0 never json.loads a
                    # shardmeta that is still streaming to disk
                    with self._fs.open("%s/done.r%d" % (vdir, rank),
                                       "w") as f:
                        f.write(nonce)
                except (IOError, OSError):
                    # rank 0's delete_tree reset the dir under our open
                    # writes (we had joined a stale attempt): re-enter
                    # the loop and rewrite under the fresh nonce
                    if time.monotonic() > deadline:
                        raise
                    continue
                delay = 0.02
                while True:
                    if manifest_attempt() == nonce:
                        committed = True
                        break
                    cur = read_sentinel()
                    if cur is not None and cur != nonce:
                        break  # superseded: retry under the new nonce
                    if time.monotonic() > deadline:
                        raise IOError(
                            "sharded save v%d rank %d: no commit or "
                            "supersession for attempt %s"
                            % (version, rank, nonce))
                    time.sleep(delay)
                    delay = min(delay * 1.5, 0.25)

        if barrier is not None:
            barrier()  # every rank's file must exist before the commit
        if rank == 0:
            if use_sentinel:
                def done_current(r):
                    try:
                        with self._fs.open("%s/done.r%d" % (vdir, r),
                                           "r") as f:
                            return f.read() == nonce
                    except (IOError, OSError):
                        return False
                self._fs_wait(
                    lambda: all(done_current(r) for r in range(nranks)),
                    "all %d rank done markers (v%d, attempt %s)"
                    % (nranks, version, nonce), timeout)
            commit(nonce)
            if use_sentinel:
                # retire the attempt's protocol state so a later save
                # at this version can never pair with this one
                for name in (["STARTED"]
                             + ["done.r%d" % r for r in range(nranks)]):
                    try:
                        self._fs.delete("%s/%s" % (vdir, name))
                    except (IOError, OSError):
                        pass
            logger.info("sharded checkpoint v%d committed (%d ranks)",
                        version, nranks)
            obs_events.emit("ckpt.saved", version=version,
                            mode="sharded", ranks=nranks)
            self._gc()
        _SAVE_MS.labels("sharded").observe((time.monotonic() - t0) * 1e3)
        return vdir

    def save_sharded_async(self, version, tree, meta=None, rank=0,
                           nranks=1, barrier=None, timeout=120.0,
                           on_commit=None):
        """Async sharded save: phase-1 snapshot of this rank's owned
        shards runs here, then the whole sentinel/nonce protocol —
        including rank 0's directory reset and manifest commit — runs on
        a background driver, streaming per-shard entry files through the
        writer pool. Same visibility rules as save_sharded; the stream
        shardmeta/MANIFEST carry ``format: "stream"`` with the per-file
        entry tables instead of per-rank npz crcs."""
        self.drain()
        t0 = time.perf_counter()
        entries, dtypes = self._snapshot_sharded(tree, rank)
        handle = SaveHandle(version)
        handle.blocked_s = time.perf_counter() - t0
        vdir = self._vdir(version)

        def write_rank_files():
            table, total = self._write_entries(vdir, "r%d_" % rank,
                                               entries)
            with self._fs.open("%s/shardmeta.r%d.json" % (vdir, rank),
                               "w") as f:
                json.dump({"format": "stream", "entries": table,
                           "dtypes": dtypes, "nbytes": total}, f)

        def commit(nonce):
            entries_all = {}
            dtypes_all = {}
            total = 0
            for r in range(nranks):
                with self._fs.open("%s/shardmeta.r%d.json" % (vdir, r),
                                   "r") as f:
                    sm = json.load(f)
                entries_all.update(sm["entries"])
                dtypes_all.update(sm["dtypes"])
                total += sm["nbytes"]
            with self._fs.open(vdir + "/meta.json", "w") as f:
                json.dump({"meta": meta or {}, "dtypes": dtypes_all}, f)
            with self._fs.open(vdir + "/MANIFEST", "w") as f:
                json.dump({"version": version, "sharded": True,
                           "format": "stream", "ranks": nranks,
                           "entries": entries_all, "nbytes": total,
                           "attempt": nonce}, f)

        def persist():
            p0 = time.perf_counter()
            try:
                out = self._sharded_protocol(version, rank, nranks,
                                             barrier, timeout,
                                             write_rank_files, commit)
                if on_commit is not None:
                    # same contract as save_async: commit observers
                    # are best-effort once the protocol completed
                    try:
                        on_commit()
                    except Exception:
                        logger.exception(
                            "on_commit callback for v%d failed",
                            version)
                handle._finish(out, persist_s=time.perf_counter() - p0)
            except BaseException as e:  # noqa: BLE001 — surfaces via result()
                handle._finish(None, exc=e,
                               persist_s=time.perf_counter() - p0)

        with self._async_lock:
            self._inflight = handle
        threading.Thread(target=persist, daemon=False,
                         name="ckpt-persist-%d.r%d" % (version, rank)
                         ).start()
        return handle

    def _restore_sharded(self, vdir, manifest, meta_blob, target):
        if target is None:
            raise IOError("sharded checkpoint restore needs a target "
                          "structure (shapes/dtypes)")
        flat, treedef = jax.tree_util.tree_flatten_with_path(target)
        specs = {}
        for path, leaf in flat:
            specs[_path_key(path)] = (tuple(leaf.shape),
                                      np.dtype(leaf.dtype))
        buffers = {}
        filled = {k: 0 for k in specs}

        def paste(skey, arr):
            key, _, spans = skey.rpartition("@")
            shape, dtype = specs[key]
            arr = _untag_array(arr, meta_blob["dtypes"].get(key))
            if key not in buffers:
                buffers[key] = np.zeros(shape, dtype)
            idx = tuple(slice(a, b) for a, b in _parse_spans(spans))
            buffers[key][idx] = arr
            filled[key] += arr.size

        if manifest.get("format") == "stream":
            pool = self._io_pool()
            futs = [(skey, pool.submit(self._read_entry_file,
                                       "%s/%s" % (vdir, entry["file"]),
                                       entry))
                    for skey, entry in manifest["entries"].items()
                    if skey.rpartition("@")[0] in specs]
            for skey, fut in futs:
                paste(skey, fut.result())
        else:
            def read_rank(r):
                with self._fs.open("%s/arrays.r%d.npz" % (vdir, r),
                                   "rb") as f:
                    payload = f.read()
                if zlib.crc32(payload) != manifest["crcs"][str(r)]:
                    raise IOError("checksum mismatch in %s rank %d"
                                  % (vdir, r))
                return payload
            payloads = list(self._io_pool().map(
                read_rank, range(int(manifest["ranks"]))))
            for payload in payloads:
                npz = np.load(io.BytesIO(payload))
                for skey in npz.files:
                    if skey.rpartition("@")[0] not in specs:
                        continue
                    paste(skey, npz[skey])
        missing = {k for k in specs if filled[k] < int(np.prod(
            specs[k][0], dtype=np.int64))}
        # scalars: prod(())==1, filled must be >= 1
        if missing:
            raise MissingKeysError(missing)
        keys = [_path_key(p) for p, _ in flat]
        return jax.tree_util.tree_unflatten(treedef,
                                            [buffers[k] for k in keys])

    # -- placed (locality-aware) restore -------------------------------------

    def load_manifest(self, version):
        """(vdir, manifest, meta_blob) of a committed version — the
        shared preamble of both placed restore paths (FS and peer)."""
        vdir = self._vdir(version)
        with self._fs.open(vdir + "/MANIFEST", "r") as f:
            manifest = json.load(f)
        with self._fs.open(vdir + "/meta.json", "r") as f:
            meta_blob = json.load(f)
        return vdir, manifest, meta_blob

    def _fill_stream(self, vdir, manifest, meta_blob, pt, keys=None):
        """Fill a PlacedTarget from a stream-format version dir,
        restricted to ``keys`` (None = every key). Entries whose
        manifest records chunk crcs and whose needed row hull is a
        strict subset of the entry are fetched with fs.read_range over
        just those leading-axis rows (chunk-aligned, per-chunk crc
        verified); everything else rides the whole-file reader."""
        pool = self._io_pool()
        todo = []
        for skey, entry in manifest["entries"].items():
            key, _, spans_s = skey.rpartition("@")
            if key not in pt.need or (keys is not None
                                      and key not in keys):
                continue
            entry_spans = _parse_spans(spans_s)
            pt.check_bounds(key, entry_spans)
            rows = pt.needed_rows(key, entry_spans)
            if rows is None:
                continue  # skip the file read entirely
            r0, r1 = rows
            nrows = (entry_spans[0][1] - entry_spans[0][0]
                     if entry_spans else 1)
            if entry.get("chunk_crcs") is not None and entry_spans \
                    and 0 < (r1 - r0) < nrows:
                a0 = entry_spans[0][0]
                sub = ((a0 + r0, a0 + r1),) + entry_spans[1:]
                todo.append((key, sub, pool.submit(
                    self._read_entry_rows,
                    "%s/%s" % (vdir, entry["file"]), entry, r0, r1)))
            else:
                todo.append((key, entry_spans, pool.submit(
                    self._read_entry_file,
                    "%s/%s" % (vdir, entry["file"]), entry)))
        for key, spans, fut in todo:
            pt.paste(key, spans, _untag_array(
                fut.result(), meta_blob["dtypes"].get(key)))

    def fill_placed_from_fs(self, version, pt, keys=None):
        """Fill a PlacedTarget's device blocks from ``version``'s STREAM
        files, restricted to ``keys`` (None = all): the per-span FS
        fallback of the peer restore plane. Raises IOError for
        non-stream layouts — the caller then falls back to a wholesale
        restore_placed. Returns the meta blob."""
        vdir, manifest, meta_blob = self.load_manifest(version)
        if manifest.get("format") != "stream":
            raise IOError("fill_placed_from_fs needs a stream-format "
                          "version (v%d is %s)" % (version,
                          "sharded npz" if manifest.get("sharded")
                          else "dense npz"))
        self._fill_stream(vdir, manifest, meta_blob, pt, keys)
        return meta_blob

    def restore_placed(self, version, target, shardings):
        """Restore ``version`` directly into sharded jax.Arrays laid out
        by ``shardings`` (a pytree matching ``target``).

        The scalable restore: host memory is O(local device blocks),
        not O(full model), and each process reads only the shard entries
        overlapping its own blocks — stream entries with recorded chunk
        crcs are fetched with fs.read_range over just the needed
        leading-axis rows, so a process that owns 1/Nth of a leaf pulls
        ~1/Nth of its bytes. Works over BOTH layouts — sharded files and
        dense files — and across RESHAPED shardings: any overlap between
        saved spans and needed device blocks is assembled, so an 8-way
        dp checkpoint restores onto a 4-way mesh or a different tp
        layout. A checkpoint whose saved extent EXCEEDS the target shape
        raises (never silently truncates); one that covers less raises
        MissingKeysError.
        """
        vdir, manifest, meta_blob = self.load_manifest(version)
        pt = PlacedTarget(target, shardings)

        if manifest.get("format") == "stream":
            # stream layout (dense OR sharded): bounds-check every entry
            # from the manifest table, then range-read ONLY the
            # overlapping spans, in parallel across the io pool
            self._fill_stream(vdir, manifest, meta_blob, pt)
        elif manifest.get("sharded"):
            def read_rank(r):
                with self._fs.open("%s/arrays.r%d.npz" % (vdir, r),
                                   "rb") as f:
                    payload = f.read()
                if zlib.crc32(payload) != manifest["crcs"][str(r)]:
                    raise IOError("checksum mismatch in %s rank %d"
                                  % (vdir, r))
                return payload
            for payload in self._io_pool().map(
                    read_rank, range(int(manifest["ranks"]))):
                npz = np.load(io.BytesIO(payload))
                for skey in npz.files:
                    key, _, spans_s = skey.rpartition("@")
                    if key not in pt.need:
                        continue
                    entry_spans = _parse_spans(spans_s)
                    pt.check_bounds(key, entry_spans)
                    if not pt.overlaps_local(key, entry_spans):
                        continue  # skip the decompress entirely
                    pt.paste(key, entry_spans, _untag_array(
                        npz[skey], meta_blob["dtypes"].get(key)))
        else:
            with self._fs.open(vdir + "/arrays.npz", "rb") as f:
                payload = f.read()
            if zlib.crc32(payload) != manifest["crc"]:
                raise IOError("checksum mismatch in %s" % vdir)
            npz = np.load(io.BytesIO(payload))
            for key in npz.files:
                if key not in pt.need:
                    continue
                # entry spans from the SAVED array's real shape: a
                # larger stored tensor must raise, not truncate
                arr = npz[key]
                entry_spans = tuple((0, d) for d in arr.shape)
                pt.check_bounds(key, entry_spans)
                pt.paste(key, entry_spans, _untag_array(
                    arr, meta_blob["dtypes"].get(key)))

        missing = pt.missing()
        if missing:
            raise MissingKeysError(missing)
        return version, pt.assemble(), meta_blob["meta"]

    # -- restore -------------------------------------------------------------

    def restore_latest(self, target=None):
        """Restore the newest valid checkpoint.

        Returns (version, tree, meta) or None. Corrupt versions (bad crc /
        missing manifest) are skipped, falling back to the previous one —
        the integrity contract of the reference (doc/fault_tolerance.md).
        If ``target`` is given, leaves are restored into its structure.
        """
        for version in reversed(self.versions()):
            try:
                return self.restore(version, target)
            except Exception as e:  # noqa: BLE001 — fall back to older ckpt
                logger.warning("checkpoint v%d unreadable (%r); trying older",
                               version, e)
        return None

    def restore(self, version, target=None):
        t0 = time.monotonic()
        try:
            out = self._restore(version, target)
        except Exception:
            obs_events.emit("ckpt.restore_failed", version=version)
            raise
        _RESTORE_MS.observe((time.monotonic() - t0) * 1e3)
        obs_events.emit("ckpt.restored", version=version)
        return out

    def _restore(self, version, target=None):
        vdir = self._vdir(version)
        with self._fs.open(vdir + "/MANIFEST", "r") as f:
            manifest = json.load(f)
        if manifest.get("sharded"):
            with self._fs.open(vdir + "/meta.json", "r") as f:
                meta_blob = json.load(f)
            tree = self._restore_sharded(vdir, manifest, meta_blob, target)
            return version, tree, meta_blob["meta"]
        if manifest.get("format") == "stream":
            with self._fs.open(vdir + "/meta.json", "r") as f:
                meta_blob = json.load(f)
            tree = self._restore_stream(vdir, manifest, meta_blob, target)
            return version, tree, meta_blob["meta"]
        with self._fs.open(vdir + "/arrays.npz", "rb") as f:
            payload = f.read()
        if zlib.crc32(payload) != manifest["crc"]:
            raise IOError("checksum mismatch in %s" % vdir)
        with self._fs.open(vdir + "/meta.json", "r") as f:
            meta_blob = json.load(f)
        npz = np.load(io.BytesIO(payload))
        arrays = {}
        for key in npz.files:
            arr = npz[key]
            if meta_blob["dtypes"].get(key) == "bfloat16":
                if _BFLOAT16 is None:  # pragma: no cover
                    raise IOError("bfloat16 checkpoint needs ml_dtypes")
                arr = arr.view(_BFLOAT16)
            arrays[key] = arr

        if target is None:
            tree = _unflatten_to_nested(arrays)
        else:
            keys, treedef = _paths(target)
            missing = set(keys) - set(arrays)
            if missing:
                raise MissingKeysError(missing)
            tree = jax.tree_util.tree_unflatten(treedef,
                                                [arrays[k] for k in keys])
        return version, tree, meta_blob["meta"]

    def _restore_stream(self, vdir, manifest, meta_blob, target):
        """Restore a dense stream-format version: every entry file is
        read (and CRC-checked) in parallel across the io pool. Dense
        stream entries are single full-span entries per key."""
        pool = self._io_pool()
        futs = [(skey, pool.submit(self._read_entry_file,
                                   "%s/%s" % (vdir, entry["file"]),
                                   entry))
                for skey, entry in manifest["entries"].items()]
        arrays = {}
        for skey, fut in futs:
            key, _, _ = skey.rpartition("@")
            arrays[key] = _untag_array(fut.result(),
                                       meta_blob["dtypes"].get(key))
        if target is None:
            return _unflatten_to_nested(arrays)
        keys, treedef = _paths(target)
        missing = set(keys) - set(arrays)
        if missing:
            raise MissingKeysError(missing)
        return jax.tree_util.tree_unflatten(treedef,
                                            [arrays[k] for k in keys])


def _unflatten_to_nested(arrays):
    """Rebuild a nested dict from flat path keys (lists come back as dicts
    keyed by index strings; fine for structure-free inspection)."""
    root = {}
    for key, arr in arrays.items():
        parts = key.split(_SEP)
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return root
