"""Evaluation helpers: top-k accuracy and a jitted eval loop.

Reference parity: the rank-0 test loop + acc1/acc5 reporting of the
collective example (train_with_fleet.py:573-610, the acc numbers in
README.md:83-85 / BASELINE.md).
"""

import jax
import jax.numpy as jnp
import numpy as np


def top_k_accuracies(logits, labels, ks=(1, 5)):
    """{k: fraction of rows whose label is in the top-k logits}."""
    logits = jnp.asarray(logits)
    labels = jnp.asarray(labels)
    max_k = min(max(ks), logits.shape[-1])
    _, top = jax.lax.top_k(logits, max_k)          # [batch, max_k]
    hits = top == labels[:, None]
    return {k: jnp.mean(jnp.any(hits[:, :min(k, max_k)], axis=1))
            for k in ks}


class Evaluator(object):
    """Jitted accuracy evaluation over a batch stream.

    apply_fn(params, extra, batch) -> logits. ``extra`` carries frozen
    model state (BatchNorm running stats) in eval mode.
    """

    def __init__(self, apply_fn, ks=(1, 5)):
        self._ks = tuple(ks)

        def step(params, extra, batch):
            logits = apply_fn(params, extra, batch)
            accs = top_k_accuracies(logits, batch["label"], self._ks)
            return jnp.stack([accs[k] for k in self._ks]), logits.shape[0]

        self._step = jax.jit(step)

    def evaluate(self, params, extra, batches):
        """Weighted-average top-k accuracies over ``batches``; returns
        {"acc1": ..., "acc5": ...}-style dict."""
        totals = np.zeros(len(self._ks))
        n = 0
        for batch in batches:
            accs, bs = self._step(params, extra, batch)
            totals += np.asarray(accs) * int(bs)
            n += int(bs)
        if n == 0:
            return {}
        return {"acc%d" % k: round(float(t / n), 4)
                for k, t in zip(self._ks, totals)}
