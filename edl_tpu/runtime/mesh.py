"""Device-mesh construction for dp/tp/sp/pp axes + topology validity.

The TPU replacement for the reference's NCCL world bootstrap: there is no
rendezvous to manage — `jax.devices()` exposes the slice topology and pjit /
shard_map lower collectives onto ICI/DCN (SURVEY.md §2.7, §5.8). The
launcher contributes only host membership; this module turns the surviving
hosts' devices into a Mesh.
"""

import math

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "dp"
MODEL_AXIS = "tp"
SEQ_AXIS = "sp"
PIPE_AXIS = "pp"
EXPERT_AXIS = "ep"
DCN_AXIS = "dcn"  # the cross-slice (data-center network) axis


def make_mesh(dp=None, tp=1, sp=1, pp=1, ep=1, devices=None):
    """Build a Mesh with axes (pp, dp, ep, sp, tp) over ``devices``.

    dp=None ⇒ fill dp with whatever remains after the fixed axes. Axis order
    puts tp innermost so tensor-parallel collectives ride the fastest ICI
    links, and pp outermost (classic TPU layout; cf. the scaling-book
    recipe of mesh-then-annotate).
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    fixed = tp * sp * pp * ep
    if dp is None:
        if n % fixed != 0:
            raise ValueError("devices=%d not divisible by tp*sp*pp*ep=%d"
                             % (n, fixed))
        dp = n // fixed
    if dp * fixed != n:
        raise ValueError("mesh %dx%dx%dx%dx%d != %d devices"
                         % (pp, dp, ep, sp, tp, n))
    shape = (pp, dp, ep, sp, tp)
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, AssertionError):
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array,
                (PIPE_AXIS, DATA_AXIS, EXPERT_AXIS, SEQ_AXIS, MODEL_AXIS))


def _group_slices(devices):
    """Group devices into slices: by the TPU runtime's slice_index when it
    discriminates (real multi-slice systems, where one slice spans many
    host processes), else by owning process (multi-host CPU rigs report a
    constant slice_index 0)."""
    sids = {getattr(d, "slice_index", None) for d in devices}
    key = ((lambda d: d.slice_index) if len(sids) > 1 and None not in sids
           else (lambda d: d.process_index))
    groups = {}
    for d in devices:
        groups.setdefault(key(d), []).append(d)
    return [groups[k] for k in sorted(groups)]


def make_hybrid_mesh(dcn_dp=None, dp=None, tp=1, sp=1, pp=1, ep=1,
                     devices=None):
    """Multi-slice mesh: data parallelism over DCN (one row per slice),
    the other axes within each slice over ICI.

    Axes: (dcn, pp, dp, ep, sp, tp) — shard batches with
    ``data_sharding(mesh)`` (= P(("dcn", "dp"))); the gradient all-reduce
    XLA inserts then decomposes into a fast within-slice reduce over ICI
    plus a small cross-slice reduce over DCN (the hierarchical-allreduce
    the reference exposed as a fleet knob, train_with_fleet.py:372).

    Slices are discovered from device.slice_index (real multi-slice TPU)
    or process_index (multi-host CPU test rig). If all devices report ONE
    slice and ``dcn_dp`` > 1 is requested, the device list is split
    contiguously into dcn_dp virtual slices — the hermetic single-process
    test/dryrun mode.
    """
    devices = list(devices if devices is not None else jax.devices())
    slices = _group_slices(devices)
    if len(slices) == 1 and dcn_dp and dcn_dp > 1:
        n = len(devices)
        if n % dcn_dp != 0:
            raise ValueError("devices=%d not divisible into %d virtual "
                             "slices" % (n, dcn_dp))
        per = n // dcn_dp
        slices = [devices[i * per:(i + 1) * per] for i in range(dcn_dp)]
    if dcn_dp is None:
        dcn_dp = len(slices)
    if len(slices) != dcn_dp:
        raise ValueError("found %d slices, want dcn_dp=%d"
                         % (len(slices), dcn_dp))
    sizes = sorted({len(s) for s in slices})
    if len(sizes) != 1:
        raise ValueError("unequal slice sizes %s" % sizes)
    per = sizes[0]
    fixed = tp * sp * pp * ep
    if dp is None:
        if per % fixed != 0:
            raise ValueError("slice size %d not divisible by tp*sp*pp*ep=%d"
                             % (per, fixed))
        dp = per // fixed
    if dp * fixed != per:
        raise ValueError("per-slice mesh %dx%dx%dx%dx%d != %d devices"
                         % (pp, dp, ep, sp, tp, per))
    shape = (pp, dp, ep, sp, tp)
    rows = []
    for s in slices:
        try:
            rows.append(mesh_utils.create_device_mesh(shape, devices=s))
        except (ValueError, AssertionError):
            rows.append(np.asarray(s).reshape(shape))
    dev_array = np.stack(rows)  # [dcn, pp, dp, ep, sp, tp]
    return Mesh(dev_array, (DCN_AXIS, PIPE_AXIS, DATA_AXIS, EXPERT_AXIS,
                            SEQ_AXIS, MODEL_AXIS))


def parse_mesh_arg(s):
    """Parse a CLI mesh factorization: ``"dp,tp"`` or ``"dp=2,tp=4"`` ->
    {axis: size|None} suitable for ``make_mesh(**factors)``.

    A bare model axis (tp/sp/pp/ep) defaults to 2; a bare ``dp`` maps to
    None (make_mesh fills it with the remaining devices). Unknown axis
    names raise — the CLI should fail loudly, not build a mesh the
    trainer can't rebuild on resize."""
    known = (DATA_AXIS, MODEL_AXIS, SEQ_AXIS, PIPE_AXIS, EXPERT_AXIS)
    out = {}
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            axis, _, val = part.partition("=")
            axis = axis.strip()
            size = int(val)
        else:
            axis = part
            size = None if axis == DATA_AXIS else 2
        if axis not in known:
            raise ValueError("unknown mesh axis %r (want one of %s)"
                             % (axis, ", ".join(known)))
        out[axis] = size
    return out


def data_sharding(mesh):
    """Batch-dim sharding over the data axes present in the mesh: dp, plus
    dcn for hybrid (multi-slice) meshes."""
    axes = tuple(a for a in (DCN_AXIS, DATA_AXIS) if a in mesh.shape)
    if not axes:
        return NamedSharding(mesh, P())
    return NamedSharding(mesh, P(axes if len(axes) > 1 else axes[0]))


def replicated(mesh):
    return NamedSharding(mesh, P())


def topology_valid_power_of_two(n_hosts):
    """Default TPU validity: host counts must be powers of two (sub-slices
    of a pod slice). Replace per deployment topology. Used by the cluster
    generator's validity hook (SURVEY.md §7 'hard parts')."""
    return n_hosts > 0 and (n_hosts & (n_hosts - 1)) == 0


def largest_valid_world(n_hosts):
    if n_hosts <= 0:
        return 0
    return 2 ** int(math.floor(math.log2(n_hosts)))
