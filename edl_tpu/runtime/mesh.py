"""Device-mesh construction for dp/tp/sp/pp axes + topology validity.

The TPU replacement for the reference's NCCL world bootstrap: there is no
rendezvous to manage — `jax.devices()` exposes the slice topology and pjit /
shard_map lower collectives onto ICI/DCN (SURVEY.md §2.7, §5.8). The
launcher contributes only host membership; this module turns the surviving
hosts' devices into a Mesh.
"""

import math

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "dp"
MODEL_AXIS = "tp"
SEQ_AXIS = "sp"
PIPE_AXIS = "pp"
EXPERT_AXIS = "ep"


def make_mesh(dp=None, tp=1, sp=1, pp=1, ep=1, devices=None):
    """Build a Mesh with axes (pp, dp, ep, sp, tp) over ``devices``.

    dp=None ⇒ fill dp with whatever remains after the fixed axes. Axis order
    puts tp innermost so tensor-parallel collectives ride the fastest ICI
    links, and pp outermost (classic TPU layout; cf. the scaling-book
    recipe of mesh-then-annotate).
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    fixed = tp * sp * pp * ep
    if dp is None:
        if n % fixed != 0:
            raise ValueError("devices=%d not divisible by tp*sp*pp*ep=%d"
                             % (n, fixed))
        dp = n // fixed
    if dp * fixed != n:
        raise ValueError("mesh %dx%dx%dx%dx%d != %d devices"
                         % (pp, dp, ep, sp, tp, n))
    shape = (pp, dp, ep, sp, tp)
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, AssertionError):
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array,
                (PIPE_AXIS, DATA_AXIS, EXPERT_AXIS, SEQ_AXIS, MODEL_AXIS))


def data_sharding(mesh):
    """Batch-dim sharding over dp (and sp if present)."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh):
    return NamedSharding(mesh, P())


def topology_valid_power_of_two(n_hosts):
    """Default TPU validity: host counts must be powers of two (sub-slices
    of a pod slice). Replace per deployment topology. Used by the cluster
    generator's validity hook (SURVEY.md §7 'hard parts')."""
    return n_hosts > 0 and (n_hosts & (n_hosts - 1)) == 0


def largest_valid_world(n_hosts):
    if n_hosts <= 0:
        return 0
    return 2 ** int(math.floor(math.log2(n_hosts)))
