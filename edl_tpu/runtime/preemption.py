"""Coordinated preemption stop: agree on a common stop STEP across all
ranks through the coordination store, so the grace-window emergency
checkpoint can use the normal cooperative save even for cross-host
SHARDED state (tp/sp over hosts).

Why: SIGTERMs land on each host at slightly different wall-clock times,
so ranks observe the flag at different step boundaries. Any cooperative
save (collective gather, or the sharded save's filesystem barrier)
started from misaligned boundaries deadlocks or times out. The protocol:

  1. a flagged rank publishes   preempt:<stage>/req_<rank> = its step
  2. rank 0 (watcher thread) sees any req and publishes (put-if-absent)
                                preempt:<stage>/stop_at = its step + margin
  3. every rank's watcher reads stop_at; the trainer stops at that exact
     step boundary, where the cooperative save is safe, and raises
     PreemptedError on ALL ranks.

Keys carry a TTL, republished periodically while the preemption is
pending, so they self-expire after the job moves on — a restarted job
can never trip over its predecessor's stop_at — and are namespaced by
the cluster stage uuid (a new incarnation never sees the old stage's
keys even within the TTL). The stop step is chosen ahead of every
rank: max(leader step, all requesters' steps) plus a margin ADAPTIVE to
the observation latency (the step-equivalent of a few watcher poll
intervals, from the leader's measured step time) — with fast steps a
fixed step count would already be in the past by the time a watcher
polls. If a rank still overshoots (extreme skew), the aligned save is
impossible: that rank raises PreemptedError without saving, the
stopped ranks' save barrier times out, and every rank still exits via
PreemptedError with the restart falling back to the last epoch
checkpoint; a rank blocked inside a dispatched collective is freed by
the supervisor's SIGKILL after the grace period. The checkpoint is
best-effort under pathological skew — never corrupted, and the failure
mode equals not having the feature.

Caveat: the stop is enforced at HOST step boundaries, so a training
loop that never synchronizes (no loss fetch, no metrics) can dispatch
far past the agreed step before its watcher observes it — the margin
covers normal dispatch-ahead, not a free-running dispatch loop. Real
loops sync every step or few (loss logging, metrics), which is the
cadence the adaptive margin is computed from.

Reference role: the reference had no mid-epoch preemption save at all
(per-epoch checkpoints only, train_with_fleet.py:562); this is net-new
elasticity depth for TPU pods, where preemption is routine.
"""

import threading
import time

from edl_tpu.utils.logger import logger

KEY_TTL = 120.0


class PreemptionGuard(object):
    """Async-signal-safe preemption flag + checkpoint drain hook.

    The handler only flips ``preempted`` (no I/O, no locks — the only
    things legal in a signal context); the trainer polls the flag at
    step boundaries. ``drain()`` runs the supplied callable (the async
    checkpoint engine's drain) and is called on EVERY preemption exit
    path — including the ones that save nothing — so a SIGTERM can
    never lose the in-flight async checkpoint version."""

    def __init__(self, drain=None):
        self._drain = drain
        self.preempted = False
        self.installed = False

    def install(self, signals=None):
        """Arm the flag-only handler (idempotent; main thread only —
        CPython restricts signal.signal to it). Default: SIGTERM."""
        import signal as signal_mod
        if signals is None:
            signals = (signal_mod.SIGTERM,)
        for s in signals:
            signal_mod.signal(s, self._on_signal)
        self.installed = True
        return self

    def _on_signal(self, signum, frame):
        self.preempted = True

    def drain(self):
        """Wait out the in-flight async checkpoint persist (best-effort:
        a drain failure must not mask the PreemptedError being raised)."""
        if self._drain is None:
            return
        try:
            self._drain()
        except Exception:
            logger.exception("preemption drain failed")


class CoordinatedStop(object):
    """One per trainer process. ``stop_at`` becomes the agreed stop step
    (read it each boundary); ``request(step)`` publishes this rank's
    preemption flag. A daemon watcher thread polls the store."""

    def __init__(self, coord, rank, stage="default", margin=4,
                 poll_interval=0.25, current_step=None, min_step=0,
                 step_time=None, grace_budget=8.0,
                 heartbeat_interval=2.0):
        self._coord = coord
        self._rank = rank
        self._service = "preempt:%s" % (stage or "default")
        self._margin = margin
        self._poll = poll_interval
        self._current_step = current_step or (lambda: 0)
        # seconds per train step (callable), for the adaptive margin; 0
        # or None falls back to the fixed step margin
        self._step_time = step_time or (lambda: 0.0)
        # the stop lead in WALL-CLOCK terms must fit inside the
        # SIGTERM->SIGKILL grace window: with multi-second steps a fixed
        # 4-step margin would overshoot it and the save would be killed
        # mid-flight, so the lead is capped at grace_budget seconds
        self._grace_budget = grace_budget
        # every rank (not just requesters) publishes step_<rank> at this
        # cadence so the leader's stop_at clears the furthest-ahead
        # rank's counter, not just the requesters'/leader's. It must
        # run BEFORE any preemption is pending (stop_at is computed
        # from whatever is on the store at request time), so the cost
        # is bounded instead: one lease granted once then refreshed,
        # one leased put (no fsync) per interval, 2s default cadence
        self._hb_interval = heartbeat_interval
        self._last_hb = 0.0
        self._hb_lease = None
        # leader-side per-rank heartbeat history: key -> (step, t_seen).
        # Lets the stop lead use each rank's ACTUAL heartbeat staleness
        # (observed age of its current value) instead of a blanket
        # worst-case hb_interval term, which ballooned the lead to ~30
        # steps at fast cadences (r4) and forced tests onto long epochs.
        self._hb_obs = {}
        self.stop_at = None
        # stop_at values at or below min_step are STALE (left by a prior
        # incarnation within the key TTL when the stage uuid did not
        # change). The trainer raises this to the resumed step after
        # checkpoint restore; a legitimate stop is always published
        # ahead of every live rank's step.
        self.min_step = min_step
        self._requested = False
        self._last_pub = 0.0
        self._stop_evt = threading.Event()
        self._thread = None

    @property
    def started(self):
        return self._thread is not None

    def start(self):
        """Idempotent. Callers should start the watcher only once the
        baseline step is final (after any checkpoint resume): a watcher
        polling with a too-low min_step would accept a stale stop_at in
        the window before the baseline is raised."""
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="preempt-watch-r%d"
                                            % self._rank)
            self._thread.start()
        return self

    def stop(self):
        self._stop_evt.set()

    def request(self, step):
        """Publish this rank's preemption flag (TTL'd, republished every
        few seconds while pending — a single put could expire during a
        long compile before the leader's watcher ever polls). The
        published step is clamped above min_step so the leader's
        staleness filter never discards a live request."""
        now = time.monotonic()
        if self._requested and now - self._last_pub < min(2.0,
                                                          KEY_TTL / 3.0):
            return
        self._requested = True
        self._last_pub = now
        try:
            value = str(max(int(step), self.min_step + 1))
            if self._coord.set_server_not_exists(
                    self._service, "req_%d" % self._rank, value,
                    ttl=KEY_TTL) is None:
                # the key exists — either our own earlier publish (an
                # overwrite is an idempotent refresh) or a STALE one
                # from a prior same-stage incarnation, which would
                # shadow this live request past the leader's staleness
                # filter: overwrite unconditionally
                self._coord.set_server_with_lease(
                    self._service, "req_%d" % self._rank, value,
                    ttl=KEY_TTL)
        except Exception:
            logger.exception("preempt request publish failed")

    # -- watcher ------------------------------------------------------------

    @staticmethod
    def _as_step(value):
        """Store value -> int step, None when absent/garbled (the one
        decoder for stop_at and request values)."""
        if isinstance(value, bytes):
            value = value.decode("utf-8", "replace")
        try:
            return None if value is None else int(value)
        except (TypeError, ValueError):
            return None

    @staticmethod
    def _as_step_hb(value):
        """Heartbeat value -> (step, step_time|None). Heartbeats carry
        the rank's own measured step time ("<step>:<dt>") so the leader
        can project each rank's position per-rank; bare ints (older
        writers) decode with no rate."""
        if isinstance(value, bytes):
            value = value.decode("utf-8", "replace")
        if value is None:
            return None, None
        step_s, _, dt_s = str(value).partition(":")
        try:
            step = int(step_s)
        except (TypeError, ValueError):
            return None, None
        try:
            dt = float(dt_s) if dt_s else None
        except ValueError:
            dt = None
        return step, (dt if dt and dt > 0 else None)

    def _read_stop_at(self):
        try:
            v = self._coord.get_value(self._service, "stop_at")
        except Exception:
            logger.exception("preempt stop_at read failed")
            return None
        return self._as_step(v)

    def _leader_maybe_publish(self):
        try:
            reqs = self._coord.get_service(self._service)
        except Exception:
            return

        # reqs at or below min_step are a prior incarnation's leftovers
        # (same stage uuid within the key TTL) — not a live preemption;
        # step_<rank> heartbeats widen the max to EVERY live rank's
        # counter so a fast non-requesting rank cannot already be past
        # the stop when its watcher observes it.
        now = time.monotonic()
        dt = float(self._step_time() or 0.0)
        # Per-rank position PROJECTION: a heartbeat value is stale by
        # its observed age (tracked across polls: a value first seen
        # this poll was written within the last poll interval; on the
        # leader's very first sighting the age is unknown — assume a
        # full heartbeat period, it refines at the next beat). Project
        # each rank forward by age/its-own-step-rate, so the stop
        # clears where the rank IS, not where its last beat was. This
        # replaces the old blanket worst-case hb_interval term in the
        # lead, which at fast cadences ballooned the stop ~30 steps out.
        hb_steps = []
        for name, v in reqs:
            if not name.startswith("step_"):
                continue
            s, rank_dt = self._as_step_hb(v)
            if s is None or s <= self.min_step:
                continue
            prev = self._hb_obs.get(name)
            if prev is None:
                self._hb_obs[name] = (s, now - self._hb_interval)
            elif prev[0] != s:
                self._hb_obs[name] = (s, now)
            age = now - self._hb_obs[name][1]
            rate = rank_dt or dt
            # floor, not ceil: the lead below already covers sub-step
            # observation latency for every rank. CAPPED at
            # grace_budget worth of stepping: an unchanged beat can
            # mean a PAUSED rank (epoch save, eval, recompile) whose
            # age grows without the rank advancing at all — an
            # unbounded projection would push stop_at past anything
            # reachable inside the kill grace and forfeit the save.
            if rate > 0:
                ahead = min(int((age + self._poll) / rate),
                            max(1, int(self._grace_budget / rate)))
            else:
                ahead = 0
            hb_steps.append(s + ahead)
        req_steps = [s for name, v in reqs
                     if name.startswith("req_")
                     and (s := self._as_step(v)) is not None
                     and s > self.min_step]
        if not req_steps:
            return
        # the stop must land AHEAD of every rank's (projected) counter
        # when its watcher observes it: steps are fast (ms) while
        # observation is poll-paced (100s of ms), so a fixed step margin
        # would already be in the past — convert a few poll intervals of
        # observation latency into steps using the measured step time.
        # With SLOW steps the lead is capped so lead*step_time stays
        # inside the kill grace window.
        lead = self._margin
        if dt > 0:
            adaptive = int(4.0 * self._poll / dt) + 1
            lead = max(self._margin, adaptive)
            max_lead = max(1, int(self._grace_budget / dt))
            lead = min(lead, max_lead)
        stop = (max([int(self._current_step())] + req_steps + hb_steps)
                + lead)
        try:
            existing = self._read_stop_at()
            if existing is not None and existing <= self.min_step:
                # a stale key from a prior incarnation blocks the
                # put-if-absent: overwrite it (one leader per job)
                self._coord.set_server_with_lease(
                    self._service, "stop_at", str(stop), ttl=KEY_TTL)
                logger.info("preemption leader: stop_at=%d published "
                            "(over stale %d)", stop, existing)
            elif existing is None and self._coord.set_server_not_exists(
                    self._service, "stop_at", str(stop),
                    ttl=KEY_TTL) is not None:
                logger.info("preemption leader: stop_at=%d published", stop)
        except Exception:
            logger.exception("preempt stop_at publish failed")

    def _publish_step_heartbeat(self):
        """Publish this rank's current step (TTL'd) so the leader's
        stop_at computation covers the furthest-ahead rank, not just
        requesters. One lease is granted once and refreshed; each
        interval costs refresh + leased put (no fsync)."""
        now = time.monotonic()
        if now - self._last_hb < self._hb_interval:
            return
        self._last_hb = now
        step = max(int(self._current_step()), self.min_step + 1)
        dt = float(self._step_time() or 0.0)
        # carry this rank's own step rate so the leader can project the
        # beat's staleness per-rank (see _leader_maybe_publish)
        value = ("%d:%.6f" % (step, dt)) if dt > 0 else str(step)
        key = self._coord.server_key(self._service,
                                     "step_%d" % self._rank)
        ttl = max(10.0, 4 * self._hb_interval)
        try:
            if self._hb_lease is not None and \
                    self._coord.lease_refresh(self._hb_lease):
                self._coord.put(key, value, lease_id=self._hb_lease)
            else:
                self._hb_lease = self._coord.lease_grant(ttl)
                self._coord.put(key, value, lease_id=self._hb_lease)
        except Exception:
            self._hb_lease = None
            logger.exception("preempt step heartbeat failed")

    def _run(self):
        warned_stale = False
        while not self._stop_evt.wait(self._poll):
            self._publish_step_heartbeat()
            got = self._read_stop_at()
            if got is not None:
                if got <= self.min_step:
                    if not warned_stale:
                        warned_stale = True
                        logger.warning(
                            "ignoring stale preemption stop_at=%d "
                            "(<= min_step %d)", got, self.min_step)
                else:
                    self.stop_at = got
                    logger.info("preemption stop_at=%d observed (rank %d)",
                                got, self._rank)
                    return
            if self._rank == 0:
                self._leader_maybe_publish()
