"""Peer-served state restore: the in-memory fast path of elastic resize.

The resize critical path used to restore every process from shared
storage, even though surviving peers hold the exact post-snapshot state
in host memory (the async save engine's phase-1 snapshot) and the
pipelined RPC plane can move tensors at wire speed. This module closes
that loop (the Gemini/SOSP'23 argument: in-memory peer-served
checkpoints cut recovery from storage-bandwidth to NIC-bandwidth):

- :class:`StateServer` — every trainer runs one; after each checkpoint
  COMMIT the trainer publishes the committed snapshot's host copies and
  the server serves per-leaf, per-span range reads over the v2 tensor
  frames (zero-copy uint8 views of the published buffers). The endpoint
  is advertised through the coordination store (SERVICE_STATE_SERVER,
  TTL-leased) alongside the trainer's rank.
- :class:`PeerRestorer` — a restarting/new process resolves which live
  peers cover its needed device blocks (manifests fetched in parallel),
  fetches only the overlapping leading-axis rows from each owner —
  pipelined with ``call_async`` in ~4 MB sub-reads — and pastes into
  the same :class:`~edl_tpu.runtime.checkpoint.PlacedTarget` the FS
  restore uses.

Fallback ladder (docs/elastic_resize.md): peers → alternate peers for
the same span → parity decode of dead pods' shards
(runtime/redundancy.py, zero FS reads) → per-span FS range reads
(fill_placed_from_fs) → wholesale ``restore_placed`` (the caller's
job, on PeerRestoreError).

The server doubles as the redundancy tier's shard depot: partners
push erasure-coded snapshot shards via ``state.shard_put`` (host RAM,
one version per owner) and rebuilders range-read them back via
``state.shard``/``state.shard_manifest`` — advertised separately
under SERVICE_REDUNDANCY (``advertise_redundancy``).

Version/ownership rules: a server serves exactly ONE version — the
newest committed — and ``state.read`` raises StaleStateError when a
newer save supersedes it mid-fetch; the restorer drops that peer and
falls back. Published buffers are fresh host copies captured at
snapshot time (NOT the reused _HostBufferPool staging buffers), so an
in-flight peer read can never observe the next save being staged.

Chaos fault points: ``peer_restore.connect`` (per peer dial, ctx:
endpoint, rank) and ``peer_restore.read`` (per span fetch, ctx:
endpoint, key) — see edl_tpu/robustness/faults.py.
"""

import json
import threading

import jax
import numpy as np

from edl_tpu.controller import constants
from edl_tpu.robustness import faults
from edl_tpu.rpc.client import RpcClient
from edl_tpu.rpc.server import RpcServer
from edl_tpu.runtime.checkpoint import (MissingKeysError, PlacedTarget,
                                        _concrete_spans, _parse_spans,
                                        _path_key, _spans_str,
                                        _untag_array, _wire_entry)
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger

_CHUNK = 4 << 20  # per call_async sub-read; matches the checkpoint chunk


def snapshot_entries(tree):
    """({span_key: contiguous host ndarray (wire dtype)}, dtype tags) —
    what a trainer publishes after a commit. EVERY addressable shard is
    captured (replicas included, deduped by span), so each peer serves
    exactly the blocks it physically holds; host/replicated leaves are
    served whole. Arrays are COPIED: jax may alias device buffers into
    np.asarray views on CPU, and a donated buffer must never leak into
    a served snapshot."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    entries = {}
    dtypes = {}

    def add(key, spans, arr):
        skey = "%s@%s" % (key, _spans_str(spans))
        if skey in entries:
            return
        arr, tag = _wire_entry(np.asarray(arr))
        if tag:
            dtypes[key] = tag
        entries[skey] = np.array(arr, copy=True)

    for path, leaf in flat:
        key = _path_key(path)
        if hasattr(leaf, "addressable_shards") and hasattr(leaf,
                                                           "sharding"):
            for s in leaf.addressable_shards:
                add(key, _concrete_spans(s.index, leaf.shape), s.data)
        else:
            arr = np.asarray(leaf)
            add(key, tuple((0, d) for d in arr.shape), arr)
    return entries, dtypes


class StateServer(object):
    """Serves this process's latest committed snapshot over RPC.

    Served methods:

    - ``state.manifest()`` → ``{"version", "rank", "meta", "dtypes",
      "entries": {skey: {"dtype", "shape", "nbytes"}}}`` (version None
      until the first publish).
    - ``state.read(version, skey, offset, length)`` → a uint8 ndarray
      slice of the published buffer (zero-copy on the server; rides the
      v2 tensor frames). Raises StaleStateError on a version mismatch,
      NotFoundError for a span this peer does not hold.

    ``advertise(coord)`` registers the endpoint in the coordination
    store under SERVICE_STATE_SERVER with a TTL lease, so a dead
    process drops out of peer discovery within one TTL.

    Live resize: the served snapshot is host copies captured at commit
    time, fully decoupled from the device arrays — a trainer resharding
    its mesh in place (`ElasticTrainer.live_resize`) keeps this server
    running and advertised throughout, so peers mid-restore keep their
    version and the resharding survivor can itself range-read spans its
    new placement needs (`PeerRestorer.fill_from_peers`). Only a NEW
    commit swaps the served version, exactly as in steady state.
    """

    def __init__(self, rank=0, host="0.0.0.0", port=0):
        self._rank = int(rank)
        self._lock = threading.Lock()
        self._version = None
        self._meta = None
        self._flats = {}   # skey -> flat uint8 view of the entry
        self._table = {}   # skey -> {dtype, shape, nbytes}
        self._dtypes = {}
        self._register = None
        self._redundancy_register = None
        # partner shards held for the redundancy tier
        # (runtime/redundancy.py): owner -> {"version", "k", "m",
        # "blob_len", "chunk_len", "held": {index: flat uint8}}. One
        # version per owner — a newer put evicts, an older one fences.
        self._shards = {}
        # test/bench hook (owner, index) -> None, called before a
        # state.shard read replies — peer_holdout --kill uses it to
        # drill the decode-with-missing-partner path
        self.shard_read_hook = None
        self._server = RpcServer(host=host, port=port)
        self._server.register("state.manifest", self._rpc_manifest)
        self._server.register("state.read", self._rpc_read)
        self._server.register("state.shard_put", self._rpc_shard_put)
        self._server.register("state.shard", self._rpc_shard)
        self._server.register("state.shard_manifest",
                              self._rpc_shard_manifest)
        self._server.start()

    @property
    def endpoint(self):
        return self._server.endpoint

    @property
    def version(self):
        with self._lock:
            return self._version

    def advertise(self, coord, ttl=None):
        """TTL-leased registration (controller.register.Register) under
        SERVICE_STATE_SERVER, keyed by rank. Best-effort: a coord outage
        only costs the peer fast path, never the trainer."""
        from edl_tpu.controller.register import Register
        value = json.dumps({"endpoint": self.endpoint,
                            "rank": self._rank})
        try:
            self._register = Register(
                coord, constants.SERVICE_STATE_SERVER, str(self._rank),
                value, ttl=ttl or constants.ETCD_TTL)
        except errors.EdlError as e:
            logger.warning("state server: advertise failed (%r); peers "
                           "will not find this process", e)

    def advertise_redundancy(self, coord, key=None, ttl=None):
        """Second TTL-leased registration, under SERVICE_REDUNDANCY:
        this process accepts partner checkpoint shards
        (``state.shard_put``) and serves them back (``state.shard``).
        ``key`` defaults to the rank; the redundancy ring is computed
        over these keys. Best-effort, like :meth:`advertise`."""
        from edl_tpu.controller.register import Register
        value = json.dumps({"endpoint": self.endpoint,
                            "rank": self._rank})
        try:
            self._redundancy_register = Register(
                coord, constants.SERVICE_REDUNDANCY,
                str(self._rank) if key is None else str(key),
                value, ttl=ttl or constants.ETCD_TTL)
        except errors.EdlError as e:
            logger.warning("state server: redundancy advertise failed "
                           "(%r); this process holds no partner "
                           "shards", e)

    def publish(self, version, entries, dtypes, meta=None):
        """Atomically swap the served snapshot to ``version``. Entries
        must be contiguous host ndarrays the caller hands over and never
        mutates (snapshot_entries makes such copies). In-flight reads of
        the previous version keep their buffers alive via the returned
        numpy views; new reads see only the new version."""
        flats = {}
        table = {}
        for skey, arr in entries.items():
            arr = np.ascontiguousarray(arr)
            flats[skey] = (np.frombuffer(memoryview(arr).cast("B"),
                                         np.uint8)
                           if arr.nbytes else np.empty(0, np.uint8))
            table[skey] = {"dtype": arr.dtype.str,
                           "shape": list(arr.shape),
                           "nbytes": int(arr.nbytes)}
        with self._lock:
            self._version = int(version)
            self._flats = flats
            self._table = table
            self._dtypes = dict(dtypes)
            self._meta = meta

    def unpublish(self):
        with self._lock:
            self._version = None
            self._flats = {}
            self._table = {}
            self._dtypes = {}
            self._meta = None

    def stop(self):
        for attr in ("_register", "_redundancy_register"):
            reg = getattr(self, attr)
            if reg is not None:
                try:
                    reg.stop()
                except errors.EdlError:
                    pass
                setattr(self, attr, None)
        self._server.stop()

    # -- served methods ----------------------------------------------------

    def _rpc_manifest(self):
        with self._lock:
            return {"version": self._version, "rank": self._rank,
                    "meta": self._meta, "dtypes": dict(self._dtypes),
                    "entries": self._table}

    def _rpc_read(self, version, skey, offset, length):
        with self._lock:
            if self._version != version:
                raise errors.StaleStateError(
                    "peer rank %d holds v%s, not v%s"
                    % (self._rank, self._version, version))
            flat = self._flats.get(skey)
        if flat is None:
            raise errors.NotFoundError("peer rank %d has no entry %s"
                                       % (self._rank, skey))
        return flat[int(offset):int(offset) + int(length)]

    # -- redundancy tier (erasure-coded partner shards) ---------------------

    def _rpc_shard_put(self, owner, version, index, header, payload):
        """Accept one erasure-coded shard of ``owner``'s snapshot at
        ``version`` into host RAM. One version per owner: a newer put
        drops the old shard set, an older one raises StaleStateError
        (the version fence — a stale shard is never stored past a
        newer one, so it can never be decoded into a newer restore)."""
        owner = str(owner)
        version = int(version)
        flat = np.ascontiguousarray(
            np.asarray(payload)).view(np.uint8).reshape(-1)
        with self._lock:
            rec = self._shards.get(owner)
            if rec is not None and version < rec["version"]:
                raise errors.StaleStateError(
                    "shard_put %s: held v%d is newer than v%d"
                    % (owner, rec["version"], version))
            if rec is None or version > rec["version"]:
                rec = {"version": version, "k": int(header["k"]),
                       "m": int(header["m"]),
                       "blob_len": int(header["blob_len"]),
                       "chunk_len": int(header["chunk_len"]),
                       "held": {}}
                self._shards[owner] = rec
            rec["held"][int(index)] = flat
            total = sum(len(r["held"]) for r in self._shards.values())
        from edl_tpu.runtime import redundancy
        redundancy.SHARDS_HELD.set(total)
        return {"version": version, "held": len(rec["held"])}

    def _rpc_shard(self, owner, version, index, offset, length):
        """Range-read of a held partner shard (the rebuild path's
        ``state.read`` analogue). StaleStateError on any version
        mismatch, NotFoundError for a shard this peer does not hold."""
        hook = self.shard_read_hook
        if hook is not None:
            hook(str(owner), int(index))
        with self._lock:
            rec = self._shards.get(str(owner))
            if rec is None:
                raise errors.NotFoundError(
                    "peer rank %d holds no shards for owner %s"
                    % (self._rank, owner))
            if rec["version"] != int(version):
                raise errors.StaleStateError(
                    "shards for %s are v%d, not v%s"
                    % (owner, rec["version"], version))
            flat = rec["held"].get(int(index))
        if flat is None:
            raise errors.NotFoundError(
                "peer rank %d holds no shard %s/%s"
                % (self._rank, owner, index))
        return flat[int(offset):int(offset) + int(length)]

    def _rpc_shard_manifest(self):
        """What this peer holds, per owner — the rebuilder intersects
        these across holders to find k live shards per dead owner."""
        with self._lock:
            return {"rank": self._rank,
                    "shards": {owner: {"version": rec["version"],
                                       "k": rec["k"], "m": rec["m"],
                                       "blob_len": rec["blob_len"],
                                       "chunk_len": rec["chunk_len"],
                                       "held": sorted(rec["held"])}
                               for owner, rec in self._shards.items()}}


class PeerRestorer(object):
    """Placed restore from live peers with per-span FS fallback.

    The ladder, per :meth:`restore_placed` call:

    1. discover peers (SERVICE_STATE_SERVER), fetch every manifest in
       parallel; drop unreachable/faulted peers and any whose published
       version differs from the requested one (stale).
    2. plan: each manifest entry overlapping a local device block gets
       an owner (first peer seen holding that exact span); further
       peers holding the same span queue as alternates. Within one
       world all peers share a sharding, so distinct entries for a key
       are either identical (replicas) or disjoint (shards) — the plan
       relies on that for exact coverage accounting.
    3. fetch only the needed leading-axis row hull of each entry,
       pipelined (``call_async``, ~4 MB sub-reads), paste untagged.
    4. per-entry failure → alternates → the key joins the FS fill set;
       after all pastes, failed + still-missing keys are re-filled from
       the checkpoint's stream files via range reads.
    5. still missing after a clean FS fill → MissingKeysError (the
       trainer's core-only retry handles legacy checkpoints); no usable
       peers at all, or FS fill impossible (non-stream layout) →
       PeerRestoreError (caller restores wholesale).
    """

    def __init__(self, coord, ckpt, self_endpoint=None, timeout=20.0,
                 chunk=_CHUNK):
        self._coord = coord
        self._ckpt = ckpt
        self._self_endpoint = self_endpoint
        self._timeout = timeout
        self._chunk = int(chunk)

    # -- discovery ---------------------------------------------------------

    def _discover(self, version):
        """[(rank, endpoint, client, manifest)] for peers serving
        exactly ``version``; open clients are the caller's to close."""
        try:
            servers = self._coord.get_service(
                constants.SERVICE_STATE_SERVER)
        except errors.EdlError as e:
            raise errors.PeerRestoreError(
                "peer discovery failed: %r" % (e,))
        inflight = []
        for _, value in servers:
            try:
                rec = json.loads(value)
            except ValueError:
                continue
            endpoint = rec.get("endpoint")
            if not endpoint or endpoint == self._self_endpoint:
                continue
            client = None
            try:
                if faults.PLANE is not None:
                    faults.PLANE.fire("peer_restore.connect",
                                      endpoint=endpoint,
                                      rank=str(rec.get("rank")))
                client = RpcClient(endpoint, timeout=self._timeout)
                fut = client.call_async("state.manifest",
                                        timeout=self._timeout)
            except Exception as e:  # noqa: BLE001 — any peer may be gone
                logger.warning("peer restore: %s unreachable (%r)",
                               endpoint, e)
                if client is not None:
                    client.close()
                continue
            inflight.append((rec, endpoint, client, fut))
        peers = []
        for rec, endpoint, client, fut in inflight:
            try:
                manifest = fut.result()
            except Exception as e:  # noqa: BLE001
                logger.warning("peer restore: manifest from %s failed "
                               "(%r)", endpoint, e)
                client.close()
                continue
            if manifest.get("version") != version:
                logger.info("peer restore: %s holds v%s, want v%s — "
                            "skipping stale peer", endpoint,
                            manifest.get("version"), version)
                client.close()
                continue
            peers.append((rec.get("rank"), endpoint, client, manifest))
        return peers

    # -- span fetch --------------------------------------------------------

    def _issue(self, source, version, entry_spans, rows):
        """Start the pipelined sub-reads for rows [r0, r1) of one peer
        entry; returns the future list."""
        client, skey, entry, endpoint = source
        if faults.PLANE is not None:
            faults.PLANE.fire("peer_restore.read", endpoint=endpoint,
                              key=skey)
        shape = tuple(entry["shape"])
        dtype = np.dtype(entry["dtype"])
        rowbytes = (int(np.prod(shape[1:], dtype=np.int64))
                    * dtype.itemsize)
        r0, r1 = rows
        b0, b1 = r0 * rowbytes, r1 * rowbytes
        futs = []
        for off in range(b0, b1, self._chunk):
            futs.append(client.call_async(
                "state.read", version, skey, off,
                min(self._chunk, b1 - off), timeout=self._timeout))
        return futs

    @staticmethod
    def _collect(source, futs, entry_spans, rows):
        """Join the sub-reads into the wire-dtype row-hull array."""
        _, skey, entry, _ = source
        shape = tuple(entry["shape"])
        dtype = np.dtype(entry["dtype"])
        r0, r1 = rows
        parts = [np.asarray(f.result()) for f in futs]
        data = parts[0] if len(parts) == 1 else np.concatenate(parts)
        rowbytes = (int(np.prod(shape[1:], dtype=np.int64))
                    * dtype.itemsize)
        if data.nbytes != (r1 - r0) * rowbytes:
            raise IOError("peer entry %s: got %d bytes, want %d"
                          % (skey, data.nbytes, (r1 - r0) * rowbytes))
        if not shape:  # scalar: the single "row" is the value itself
            return data.view(dtype).reshape(())
        return data.view(dtype).reshape((r1 - r0,) + shape[1:])

    # -- the restore -------------------------------------------------------

    def restore_placed(self, version, target, shardings):
        """Peer-first placed restore of ``version``. Returns
        (version, tree, meta, stats) — stats carries ``source``
        ("peer"/"peer+fs"), ``peer_bytes``, ``fs_keys``, ``peers``."""
        peers = self._discover(version)
        if not peers:
            raise errors.PeerRestoreError(
                "no live peer serves v%s" % (version,))
        clients = [p[2] for p in peers]
        try:
            return self._restore_from(peers, version, target, shardings)
        finally:
            for c in clients:
                c.close()

    def fill_from_peers(self, version, pt):
        """Fill the still-missing spans of an EXISTING PlacedTarget by
        peer range-reads at ``version`` — the live-resize reshard path:
        the caller already pasted the spans it holds locally and only
        the remainder crosses the wire. Entries a peer holds but the
        target has already fully filled are skipped. Returns
        {"peer_bytes", "peers", "failed"}; raises PeerRestoreError when
        no live peer serves the version. The caller owns the FS
        fallback and the final missing() accounting."""
        peers = self._discover(version)
        if not peers:
            raise errors.PeerRestoreError(
                "no live peer serves v%s" % (version,))
        clients = [p[2] for p in peers]
        try:
            peer_bytes, failed, _ = self._fill_from(
                peers, version, pt, only_missing=True)
            return {"peer_bytes": int(peer_bytes), "peers": len(peers),
                    "failed": sorted(failed)}
        finally:
            for c in clients:
                c.close()

    def _fill_from(self, peers, version, pt, only_missing=False):
        """The shared span-fetch core: plan owners/alternates from the
        peers' manifests, issue pipelined sub-reads, paste into ``pt``.
        Returns (peer_bytes, failed_keys, meta). ``only_missing``
        restricts the plan to keys pt still reports missing (the
        reshard path; a full restore wants every needed key)."""
        dtypes = {}
        meta = peers[0][3].get("meta")
        todo = pt.missing() if only_missing else set(pt.need)
        # (key, entry_spans) -> [(client, skey, entry, endpoint), ...]
        plan = {}
        for rank, endpoint, client, manifest in peers:
            dtypes.update(manifest.get("dtypes") or {})
            for skey, entry in manifest["entries"].items():
                key, _, spans_s = skey.rpartition("@")
                if key not in todo:
                    continue
                entry_spans = _parse_spans(spans_s)
                pt.check_bounds(key, entry_spans)
                if not pt.overlaps_local(key, entry_spans):
                    continue
                plan.setdefault((key, entry_spans), []).append(
                    (client, skey, entry, endpoint))

        # phase A: issue every owner's sub-reads back-to-back so all
        # peers stream concurrently; phase B joins in the same order
        pending = []
        for (key, entry_spans), sources in sorted(plan.items()):
            rows = pt.needed_rows(key, entry_spans)
            if rows is None:  # pragma: no cover — overlap checked above
                continue
            try:
                futs = self._issue(sources[0], version, entry_spans,
                                   rows)
            except Exception as e:  # noqa: BLE001 — peer died at issue
                futs = e
            pending.append((key, entry_spans, rows, sources, futs))

        peer_bytes = 0
        failed = set()
        for key, entry_spans, rows, sources, futs in pending:
            arr = None
            for i, src in enumerate(sources):
                try:
                    if i > 0 or isinstance(futs, Exception):
                        if isinstance(futs, Exception) and i == 0:
                            raise futs
                        futs = self._issue(src, version, entry_spans,
                                           rows)
                    arr = self._collect(src, futs, entry_spans, rows)
                    break
                except Exception as e:  # noqa: BLE001 — try alternates
                    logger.warning("peer restore: fetch %s@%s from %s "
                                   "failed (%r)", key,
                                   _spans_str(entry_spans), src[3], e)
                    arr = None
            if arr is None:
                failed.add(key)
                continue
            r0, r1 = rows
            if entry_spans:
                a0 = entry_spans[0][0]
                sub = ((a0 + r0, a0 + r1),) + entry_spans[1:]
            else:
                sub = entry_spans
            pt.paste(key, sub, _untag_array(arr, dtypes.get(key)))
            peer_bytes += arr.nbytes
        return peer_bytes, failed, meta

    def _restore_from(self, peers, version, target, shardings):
        pt = PlacedTarget(target, shardings)
        peer_bytes, failed, meta = self._fill_from(peers, version, pt)
        need_fs = failed | pt.missing()
        parity_bytes = 0
        parity_owners = []
        if need_fs and self._coord is not None:
            # the diskless rung: spans no live peer serves (a dead
            # pod's unique shards) may still decode from the parity
            # shards survivors hold — zero FS reads. Strictly
            # best-effort; the FS fill below stays the backstop.
            from edl_tpu.runtime import redundancy
            if redundancy.enabled():
                before = pt.missing()
                try:
                    par = redundancy.fill_from_parity(
                        self._coord, version, pt,
                        self_endpoint=self._self_endpoint,
                        timeout=self._timeout)
                    parity_bytes = par["parity_bytes"]
                    parity_owners = par["owners"]
                    if meta is None:
                        meta = par.get("meta")
                except errors.EdlError as e:
                    logger.info("peer restore v%s: parity rung "
                                "unavailable (%r)", version, e)
                # keys the parity decode completed need no FS refill;
                # everything else keeps the original reset-and-refill
                # accounting
                need_fs -= before - pt.missing()
        if need_fs:
            # a key partially pasted from peers restarts from zero so
            # the FS fill's coverage accounting stays exact
            for key in need_fs:
                pt.reset_key(key)
            try:
                meta_blob = self._ckpt.fill_placed_from_fs(
                    version, pt, keys=need_fs)
            except MissingKeysError:
                raise
            except (IOError, OSError) as e:
                raise errors.PeerRestoreError(
                    "per-span FS fallback for %s failed: %r"
                    % (sorted(need_fs), e))
            if meta is None:
                meta = meta_blob.get("meta")
            logger.info("peer restore v%s: %d key(s) re-filled from "
                        "FS: %s", version, len(need_fs),
                        sorted(need_fs))
        missing = pt.missing()
        if missing:
            raise MissingKeysError(missing)
        source = "peer"
        if parity_owners:
            source += "+parity"
        if need_fs:
            source += "+fs"
        stats = {"source": source, "peer_bytes": int(peer_bytes),
                 "parity_bytes": int(parity_bytes),
                 "parity_owners": parity_owners,
                 "fs_keys": sorted(need_fs), "peers": len(peers)}
        return version, pt.assemble(), meta, stats
