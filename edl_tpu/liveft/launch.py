"""The liveft launch supervisor: wait → run → watch, exit-101 convention.

Reference parity: edl/liveft/launch.py:24-59 (wait for membership, run the
trainer through a launcher, watch; RESTART ⇒ exit ELASTIC_EXIT_CODE so the
outer supervisor restarts the pod) and the LauncherInterface process
handling in edl/liveft/elastic.py. Two modes:

- ``--exit-on-restart``: exact reference behavior — the process exits 101
  on a scale event and an external supervisor (k8s) restarts it.
- default self-supervising loop: on RESTART the trainer is killed and
  respawned in-process with the new rank assignment (no external
  supervisor needed — the natural mode on TPU pods).

The trainer contract: env EDL_TPU_LIVEFT_RANK / _HOSTS / _NP; exit 0 ⇒ job
COMPLETED for the whole fleet; exit 101 ⇒ "restart me" (re-wait + respawn);
any other exit ⇒ ERROR.
"""

import os
import signal
import subprocess
import sys
import time

from edl_tpu.coordination.client import CoordClient
from edl_tpu.liveft.elastic import (COMPLETED, ELASTIC_EXIT_CODE, ERROR,
                                    HOLD, RESTART, ElasticManager)
from edl_tpu.utils.logger import logger


class TrainerLauncher(object):
    """Spawn/poll/kill one trainer process with the liveft env contract
    (reference LauncherInterface: spawn, watch via poll, kill-tree stop)."""

    def __init__(self, cmd, host, rank, hosts, log_path=None):
        self._cmd = list(cmd)
        env = dict(os.environ)
        env["EDL_TPU_LIVEFT_RANK"] = str(rank)
        env["EDL_TPU_LIVEFT_HOSTS"] = ",".join(hosts)
        env["EDL_TPU_LIVEFT_NP"] = str(len(hosts))
        env["EDL_TPU_LIVEFT_HOST"] = host
        self._env = env
        self._log_path = log_path
        self._log_f = None
        self._proc = None

    def start(self):
        out = None
        if self._log_path:
            self._log_f = open(self._log_path, "ab")
            out = self._log_f
        self._proc = subprocess.Popen(
            self._cmd, env=self._env, stdout=out, stderr=out,
            start_new_session=True)  # own group → killpg reaps children
        logger.info("liveft: trainer pid %d started (rank %s of %s)",
                    self._proc.pid, self._env["EDL_TPU_LIVEFT_RANK"],
                    self._env["EDL_TPU_LIVEFT_NP"])
        return self

    def poll(self):
        """None while running, else the exit code."""
        return self._proc.poll() if self._proc else None

    def stop(self, grace=10.0):
        if self._proc is None or self._proc.poll() is not None:
            self._close_log()
            return
        try:
            os.killpg(self._proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline and self._proc.poll() is None:
            time.sleep(0.1)
        if self._proc.poll() is None:
            try:
                os.killpg(self._proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            self._proc.wait()
        self._close_log()

    def _close_log(self):
        if self._log_f is not None:
            self._log_f.close()
            self._log_f = None


def launch_loop(coord, host, np_target, cmd, ttl=10, exit_on_restart=False,
                wait_timeout=600, log_path=None, poll=0.5):
    """The wait → run → watch supervisor loop. Returns the process exit
    code (0 completed, 3 error, ELASTIC_EXIT_CODE when --exit-on-restart)."""
    elastic = ElasticManager(coord, host, np_target, ttl=ttl).start()
    try:
        while True:
            hosts = elastic.wait(timeout=wait_timeout)
            rank = hosts.index(host)
            launcher = TrainerLauncher(cmd, host, rank, hosts,
                                       log_path=log_path).start()
            verdict = HOLD
            try:
                while True:
                    ret = launcher.poll()
                    if ret is not None:
                        if ret == 0:
                            elastic.complete()
                            verdict = COMPLETED
                        elif ret == ELASTIC_EXIT_CODE:
                            logger.info("liveft: trainer asked for restart")
                            verdict = RESTART
                        else:
                            logger.error("liveft: trainer exited rc=%d", ret)
                            verdict = ERROR
                        break
                    verdict = elastic.watch(poll=poll)
                    if verdict != HOLD:
                        break
            finally:
                launcher.stop()
            if verdict == COMPLETED:
                return 0
            if verdict == ERROR:
                return 3
            # RESTART: membership/np changed or trainer exit-101
            if exit_on_restart:
                return ELASTIC_EXIT_CODE
            logger.info("liveft: restarting under new membership")
    finally:
        elastic.stop()


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        description="liveft elastic supervisor (wait-run-watch)")
    ap.add_argument("--store_endpoints", required=True,
                    help="comma-separated host:port of the coord store")
    ap.add_argument("--job_id", required=True)
    ap.add_argument("--host", required=True,
                    help="this node's identity (host or host:port)")
    ap.add_argument("--np", type=int, required=True,
                    help="initial world-size target")
    ap.add_argument("--ttl", type=int, default=10)
    ap.add_argument("--exit-on-restart", action="store_true",
                    help="exit %d on scale events (external supervisor "
                         "mode, reference behavior)" % ELASTIC_EXIT_CODE)
    ap.add_argument("--wait_timeout", type=float, default=600)
    ap.add_argument("--log_path", default=None)
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="trainer command (prefix with --)")
    args = ap.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        ap.error("no trainer command given")

    # die cleanly on supervisor signals: SystemExit unwinds the finally
    # blocks, so the trainer process group is killed and the lease revoked
    # (reference: launch.py:31-33 signal_handler registration)
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda s, f: sys.exit(128 + s))

    coord = CoordClient(args.store_endpoints.split(","), root=args.job_id)
    rc = launch_loop(coord, args.host, args.np, cmd, ttl=args.ttl,
                     exit_on_restart=args.exit_on_restart,
                     wait_timeout=args.wait_timeout, log_path=args.log_path)
    sys.exit(rc)


if __name__ == "__main__":
    main()
