"""Minimal elastic layer: the liveft-style alternative to the full launcher.

Reference parity: edl/liveft/elastic.py (the 2021 design later upstreamed
to Paddle): store keys <job>/liveft/{nodes,np,endpoints}; node
self-registration with watch-based re-registration (:147-159); a watched
``np`` (world size) key as the scale signal (:172-178); wait() until the
registered host count equals np (:263); watch() returning COMPLETED /
RESTART / HOLD / ERROR (:284-307); rank reassignment that preserves
surviving hosts' order (:238-261); exit code 101 = "restart me"
(:25). Useful when an external supervisor (k8s) owns the processes and
only membership/rank agreement is needed.
"""

import threading
import time

from edl_tpu.robustness.policy import Deadline, RetryPolicy
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger

ELASTIC_EXIT_CODE = 101  # ask the outer supervisor to restart us

SERVICE_NODES = "liveft_nodes"
SERVICE_CONF = "liveft_conf"
NP_KEY = "np"

COMPLETED = "COMPLETED"
RESTART = "RESTART"
HOLD = "HOLD"
ERROR = "ERROR"

# membership-transition kinds (classify_transition / on_transition):
# not every delta is fatal — a live-resize-capable supervisor restarts
# nothing on grow/shrink-with-survivors, and only self-eviction means
# "this process is out of the job"
GROW = "grow"
SHRINK = "shrink"
SELF_EVICTED = "self_evicted"
UNCHANGED = "unchanged"


def classify_transition(old_hosts, new_hosts, host):
    """What a membership delta means for ``host``: GROW (new peers
    joined, we survive), SHRINK (peers left, we survive), SELF_EVICTED
    (the agreed world no longer contains us), UNCHANGED. Mixed
    join+leave counts as SHRINK when anybody left — the conservative
    reading for a supervisor deciding whether survivors can reshape in
    place."""
    old = set(old_hosts or ())
    new = set(new_hosts or ())
    if host not in new:
        return SELF_EVICTED
    if old - new:
        return SHRINK
    if new - old:
        return GROW
    return UNCHANGED


class ElasticManager(object):
    def __init__(self, coord, host, np_target, ttl=10,
                 on_transition=None):
        self._coord = coord
        self._host = host
        self._np = int(np_target)
        self._ttl = ttl
        # on_transition(kind, old_hosts, new_hosts): observe-only hook
        # fired from watch() when the agreed membership shifts; kind is
        # one of GROW/SHRINK/SELF_EVICTED. Exceptions are swallowed —
        # a broken observer must not take down supervision.
        self._on_transition = on_transition
        self._lease = None
        self._stop = threading.Event()
        self._hosts_changed = threading.Event()
        self._np_changed = threading.Event()
        self._completed = threading.Event()
        self._registered = threading.Event()
        self._keeper = None
        self._watcher = None
        self._np_watcher = None
        self._last_hosts = []
        # membership agreed at the last wait(); watch events only count as
        # a change against THIS (the initial registration listing would
        # otherwise race wait() and fire a spurious RESTART)
        self._agreed_hosts = None
        # jittered membership poll: on a full pod restart every node
        # enters wait() at once, and a fixed interval would hammer the
        # store in lockstep
        self._poll = RetryPolicy(base_delay=0.2, max_delay=1.0,
                                 multiplier=1.5, jitter=0.5)

        if self._coord.get_value(SERVICE_CONF, NP_KEY) is None:
            self._coord.set_server_permanent(SERVICE_CONF, NP_KEY,
                                             str(self._np))

    # -- registration with self-healing ------------------------------------

    def start(self):
        self._register()
        self._keeper = threading.Thread(target=self._keep_registered,
                                        daemon=True, name="liveft-keeper")
        self._keeper.start()
        self._watcher = self._coord.watch_service(SERVICE_NODES,
                                                  self._on_nodes)
        self._np_watcher = self._coord.watch_service(SERVICE_CONF,
                                                     self._on_conf)
        return self

    def _register(self):
        self._lease = self._coord.set_server_with_lease(
            SERVICE_NODES, self._host, str(time.time()), self._ttl)
        self._registered.set()
        logger.info("liveft: %s registered", self._host)

    def _keep_registered(self):
        """Refresh; on lease loss, re-register (reference watch-based
        re-registration, elastic.py:147-159)."""
        while not self._stop.wait(self._ttl / 3.0):
            try:
                self._coord.refresh_server(SERVICE_NODES, self._host,
                                           self._lease)
            except errors.EdlError:
                logger.warning("liveft: registration lost; re-registering")
                try:
                    self._register()
                except errors.EdlError:
                    # fell out AND could not get back in → watch() = ERROR
                    self._registered.clear()

    def _on_nodes(self, added, removed, all_servers):
        self._last_hosts = sorted(all_servers)
        if self._agreed_hosts is not None \
                and self._last_hosts != self._agreed_hosts:
            self._hosts_changed.set()

    def _on_conf(self, added, removed, all_servers):
        np_val = all_servers.get(NP_KEY)
        if np_val is None:
            return
        try:
            np_int = int(np_val)
        except (TypeError, ValueError):
            # a malformed np must not raise here: the exception would
            # silently kill the watch thread and freeze the scale signal
            logger.warning("liveft: ignoring malformed np value %r",
                           np_val)
            return
        if np_int != self._np:
            self._np = np_int
            self._np_changed.set()

    # -- the public protocol ----------------------------------------------

    def hosts(self):
        return sorted(h for h, _ in
                      self._coord.get_service(SERVICE_NODES))

    def wait(self, timeout=600):
        """Block until the registered host count equals np; returns ranked
        host list (this host's rank = index)."""
        deadline = Deadline(timeout)
        attempt = 0
        while True:
            hosts = self.hosts()
            if len(hosts) == self._np:
                self._agreed_hosts = hosts
                self._hosts_changed.clear()
                return hosts
            attempt += 1
            if not self._poll.sleep(attempt, deadline):
                raise errors.TimeoutError_(
                    "liveft: %d/%d hosts after %ss"
                    % (len(self.hosts()), self._np, timeout))

    def set_np(self, np_target):
        """Scale signal: update the shared world-size target."""
        self._coord.set_server_permanent(SERVICE_CONF, NP_KEY,
                                         str(int(np_target)))

    def complete(self):
        self._completed.set()

    def _notify_transition(self, kind, old_hosts, new_hosts):
        if self._on_transition is None:
            return
        try:
            self._on_transition(kind, list(old_hosts or ()), list(new_hosts))
        except Exception:  # noqa: BLE001 — observer must not kill watch()
            logger.exception("liveft: on_transition observer failed")

    def watch(self, poll=1.0):
        """One supervision tick: COMPLETED | RESTART (membership or np
        changed, and we survive) | HOLD (keep running) | ERROR (we fell
        out and could not re-register, or the settled world evicted us).

        When an ``on_transition`` observer is installed it is told
        WHICH kind of change settled — GROW / SHRINK (survivors, verdict
        RESTART) vs SELF_EVICTED (verdict ERROR) — so a live-resize
        supervisor can reshape survivors in place instead of treating
        every delta as a full restart. Self-eviction used to HOLD
        forever; it now surfaces as ERROR."""
        if self._completed.is_set():
            return COMPLETED
        if not self._registered.is_set():
            return ERROR
        if self._np_changed.is_set() or self._hosts_changed.is_set():
            hosts = self.hosts()
            if len(hosts) == self._np:
                kind = classify_transition(self._agreed_hosts, hosts,
                                           self._host)
                if self._host in hosts:
                    self._np_changed.clear()
                    self._hosts_changed.clear()
                    if kind != UNCHANGED:
                        self._notify_transition(kind, self._agreed_hosts,
                                                hosts)
                        return RESTART
                else:
                    # the world settled at np WITHOUT us: we were
                    # evicted, and no future event will re-admit us
                    self._np_changed.clear()
                    self._hosts_changed.clear()
                    self._notify_transition(SELF_EVICTED,
                                            self._agreed_hosts, hosts)
                    return ERROR
        time.sleep(poll)
        return HOLD

    def rank(self):
        hosts = self.hosts()
        return hosts.index(self._host) if self._host in hosts else -1

    def stop(self):
        self._stop.set()
        for w in (self._watcher, self._np_watcher):
            if w is not None:
                w.stop()
        if self._lease is not None:
            try:
                self._coord.lease_revoke(self._lease)
            except errors.EdlError:
                pass
