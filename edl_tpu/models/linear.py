"""Linear regression — the fit_a_line smoke model.

Reference parity: example/fit_a_line (UCI-housing linear regression, the
reference's smallest end-to-end config, BASELINE.json configs[0]). Feature
dim defaults to 13 to match the housing dataset shape.
"""

import jax.numpy as jnp
import numpy as np


def init_params(feature_dim=13, rng=None):
    rng = rng or np.random.RandomState(0)
    return {
        "w": jnp.asarray(rng.randn(feature_dim).astype(np.float32) * 0.01),
        "b": jnp.zeros((), jnp.float32),
    }


def predict(params, x):
    return x @ params["w"] + params["b"]


def loss_fn(params, batch, rng=None):
    pred = predict(params, batch["x"])
    return jnp.mean((pred - batch["y"]) ** 2)


def synthetic_batch(batch_size, feature_dim=13, seed=0, noise=0.01):
    """Deterministic synthetic housing-like data: y = x·w* + b* + ε."""
    rng = np.random.RandomState(seed)
    w_true = np.linspace(-1.0, 1.0, feature_dim).astype(np.float32)
    x = rng.randn(batch_size, feature_dim).astype(np.float32)
    y = x @ w_true + 0.5 + noise * rng.randn(batch_size).astype(np.float32)
    return {"x": x, "y": y}
