"""Bag-of-words sentiment classifier — the distillation student.

Reference parity: example/distill/nlp — the ERNIE→BOW sentiment
distillation student (BASELINE.md ChnSentiCorp row). Here the teacher is a
TPU-served BERT; distillation mixes hard-label CE with soft-label KL.
"""

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax


class BOW(nn.Module):
    vocab_size: int = 30522
    embed_dim: int = 128
    hidden: int = 128
    num_classes: int = 2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, input_ids):
        emb = nn.Embed(self.vocab_size, self.embed_dim,
                       param_dtype=jnp.float32, dtype=self.dtype,
                       name="embed")(input_ids)
        x = jnp.tanh(emb.sum(axis=1))
        x = jnp.tanh(nn.Dense(self.hidden, dtype=self.dtype,
                              param_dtype=jnp.float32, name="fc1")(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="fc2")(x)


def create_model_and_loss(vocab_size=1000, num_classes=2,
                          distill_weight=0.5, temperature=1.0):
    """Loss = (1-w)·CE(hard) + w·KL(teacher soft labels) — the standard
    distill objective the reference's student used (soft_label input)."""
    model = BOW(vocab_size=vocab_size, num_classes=num_classes)
    dummy = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), dummy)["params"]

    def loss_fn(params, batch, rng):
        logits = model.apply({"params": params}, batch["input_ids"])
        one_hot = jax.nn.one_hot(batch["label"], num_classes)
        hard = optax.softmax_cross_entropy(logits, one_hot).mean()
        if "soft_label" not in batch:
            return hard
        t = temperature
        teacher_probs = jax.nn.softmax(
            batch["soft_label"].astype(jnp.float32) / t, axis=-1)
        soft = optax.softmax_cross_entropy(logits / t, teacher_probs).mean()
        return (1.0 - distill_weight) * hard + distill_weight * soft * t * t

    return model, params, loss_fn
