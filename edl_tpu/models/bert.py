"""BERT encoder family in flax.linen, TP/SP-ready.

Reference parity: the reference's NLP scope was ERNIE/BERT distillation
(example/distill/nlp, doc/ROADMAP.md 0.3.0) with no model parallelism.
This implementation is TPU-first and goes further by design (a stated goal
of the rebuild): Megatron-style tensor-parallel partition rules for the
attention/MLP projections, and an optional ring-attention path so long
sequences shard over the ``sp`` mesh axis.
"""

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P


class BertSelfAttention(nn.Module):
    """``use_flash``: None = auto-dispatch by kernel legality (note a
    non-None attention mask always forces dense), True/False force a
    path. The pre-auto default was ``False``."""
    num_heads: int
    dtype: Any = jnp.bfloat16
    use_ring: bool = False
    use_flash: Optional[bool] = None
    mesh: Any = None
    # in-shard ring: the module is ALREADY inside a shard_map (e.g. a
    # pipeline stage) and the named axis carries the sequence sharding —
    # run the ring body directly instead of opening a nested shard_map
    ring_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, mask=None):
        d_model = x.shape[-1]
        head_dim = d_model // self.num_heads
        dense = lambda feats, name: nn.DenseGeneral(
            feats, dtype=self.dtype, param_dtype=jnp.float32, name=name)
        q = dense((self.num_heads, head_dim), "query")(x)
        k = dense((self.num_heads, head_dim), "key")(x)
        v = dense((self.num_heads, head_dim), "value")(x)
        from edl_tpu.ops.attention import attention_context
        ctx = attention_context(
            q, k, v, causal=False, mask=mask, dtype=self.dtype,
            ring_axis=self.ring_axis, use_ring=self.use_ring,
            use_flash=self.use_flash, mesh=self.mesh)
        out = nn.DenseGeneral(d_model, axis=(-2, -1), dtype=self.dtype,
                              param_dtype=jnp.float32, name="out")(ctx)
        return out


class MoeFFN(nn.Module):
    """Mixture-of-experts FFN as a flax module: expert-parallel over the
    mesh's ep axis when a mesh is given, dense fallback otherwise. The
    Switch load-balancing aux loss and the ST-MoE router z-loss are sowed
    into the "losses" collection (collect with mutable=["losses"] and add
    to the training loss — create_model_and_loss does this); the
    capacity-overflow drop fraction is sowed into "metrics" for
    observability (0 on the dense fallback, which has no capacity)."""
    num_experts: int
    d_ff: int
    mesh: Any = None
    k: int = 1
    capacity_factor: float = 2.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        from edl_tpu.parallel.moe import moe_ffn, moe_ffn_dense
        d_model = x.shape[-1]
        scale_in = nn.initializers.normal(d_model ** -0.5)
        scale_out = nn.initializers.normal(self.d_ff ** -0.5)
        params = {
            "router": self.param("router", scale_in,
                                 (d_model, self.num_experts), jnp.float32),
            "w_in": self.param("w_in", scale_in,
                               (self.num_experts, d_model, self.d_ff),
                               jnp.float32),
            "w_out": self.param("w_out", scale_out,
                                (self.num_experts, self.d_ff, d_model),
                                jnp.float32),
        }
        params = jax.tree_util.tree_map(
            lambda a: a.astype(self.dtype), params)
        tokens = x.reshape(-1, d_model).astype(self.dtype)
        if self.mesh is not None:
            y, metrics = moe_ffn(params, tokens, self.mesh, k=self.k,
                                 capacity_factor=self.capacity_factor,
                                 return_metrics=True)
        else:
            y, metrics = moe_ffn_dense(params, tokens, k=self.k,
                                       return_metrics=True)
        self.sow("losses", "moe_aux", metrics["aux_loss"])
        self.sow("losses", "moe_z", metrics["z_loss"])
        self.sow("metrics", "moe_drop_fraction", metrics["drop_fraction"])
        return y.reshape(x.shape)


class BertLayer(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    use_ring: bool = False
    # None = auto flash dispatch (was False before the auto default)
    use_flash: Optional[bool] = None
    mesh: Any = None
    ring_axis: Optional[str] = None  # in-shard ring (see BertSelfAttention)
    # mixture-of-experts FFN: replaces the dense MLP with num_experts
    # expert-parallel FFNs (ep mesh axis) behind a top-k router
    moe_experts: int = 0
    moe_k: int = 1

    @nn.compact
    def __call__(self, x, mask=None):
        attn = BertSelfAttention(self.num_heads, self.dtype, self.use_ring,
                                 self.use_flash, self.mesh,
                                 ring_axis=self.ring_axis,
                                 name="attention")(x, mask)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                         name="ln_attn")(x + attn)
        if self.moe_experts:
            h = MoeFFN(self.moe_experts, self.mlp_dim, self.mesh,
                       k=self.moe_k, dtype=self.dtype, name="moe")(x)
        else:
            h = nn.Dense(self.mlp_dim, dtype=self.dtype,
                         param_dtype=jnp.float32, name="mlp_up")(x)
            h = nn.gelu(h)
            h = nn.Dense(x.shape[-1], dtype=self.dtype,
                         param_dtype=jnp.float32, name="mlp_down")(h)
        return nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                            name="ln_mlp")(x + h)


class Bert(nn.Module):
    """BERT encoder; bert-base = defaults (12 layers, 768 hidden, 12 heads).

    ``use_flash``: None = auto-dispatch (flash on TPU for kernel-legal
    shapes and no attention mask; dense otherwise), True = force flash,
    False = force dense. Default was ``False`` until the roofline-gap
    PR; explicit True/False callers are unaffected.
    """
    vocab_size: int = 30522
    num_layers: int = 12
    d_model: int = 768
    num_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 512
    num_classes: Optional[int] = 2
    dtype: Any = jnp.bfloat16
    use_ring: bool = False
    use_flash: Optional[bool] = None
    mesh: Any = None
    # activation recompute: save only layer-boundary activations and
    # recompute layer internals (attention scores, MLP hidden) in the
    # backward pass — the TPU equivalent of the reference's recompute
    # checkpointing knob (train_with_fleet.py:322-325)
    remat: bool = False
    # mixture-of-experts FFNs (expert-parallel over ep when mesh given)
    moe_experts: int = 0
    moe_k: int = 1

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        b, s = input_ids.shape
        word = nn.Embed(self.vocab_size, self.d_model,
                        param_dtype=jnp.float32, dtype=self.dtype,
                        name="word_embed")(input_ids)
        pos = nn.Embed(self.max_len, self.d_model,
                       param_dtype=jnp.float32, dtype=self.dtype,
                       name="pos_embed")(jnp.arange(s)[None, :])
        x = word + pos
        if token_type_ids is not None:
            x = x + nn.Embed(2, self.d_model, param_dtype=jnp.float32,
                             dtype=self.dtype,
                             name="type_embed")(token_type_ids)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                         name="ln_embed")(x)
        layer_cls = nn.remat(BertLayer) if self.remat else BertLayer
        for i in range(self.num_layers):
            x = layer_cls(self.num_heads, self.mlp_dim, self.dtype,
                          self.use_ring, self.use_flash, self.mesh,
                          moe_experts=self.moe_experts, moe_k=self.moe_k,
                          name="layer_%d" % i)(x, attention_mask)
        pooled = jnp.tanh(nn.Dense(self.d_model, dtype=jnp.float32,
                                   param_dtype=jnp.float32,
                                   name="pooler")(x[:, 0]))
        if self.num_classes is None:
            return x, pooled
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="classifier")(pooled)


class BertEmbed(nn.Module):
    """The pipeline ``encode`` end: token ids → activations (stage 0).
    With ``seq_axis`` set (in-shard sequence parallelism) each shard
    embeds its seq SLICE, so positions are offset by the shard index."""
    vocab_size: int
    d_model: int
    max_len: int
    dtype: Any = jnp.bfloat16
    seq_axis: Optional[str] = None

    @nn.compact
    def __call__(self, input_ids):
        s = input_ids.shape[1]
        word = nn.Embed(self.vocab_size, self.d_model,
                        param_dtype=jnp.float32, dtype=self.dtype,
                        name="word_embed")(input_ids)
        pos_ids = jnp.arange(s)[None, :]
        if self.seq_axis:
            pos_ids = pos_ids + jax.lax.axis_index(self.seq_axis) * s
        pos = nn.Embed(self.max_len, self.d_model, param_dtype=jnp.float32,
                       dtype=self.dtype, name="pos_embed")(pos_ids)
        return nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                            name="ln_embed")(word + pos)


class BertStage(nn.Module):
    """One pipeline stage: ``layers_per_stage`` BertLayers, activation →
    activation (the uniform ring body for pipeline_value_and_grad).
    ring_axis composes sequence parallelism INTO the pipeline stage: the
    layers' attention runs the in-shard ring over that mesh axis."""
    layers_per_stage: int
    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    remat: bool = False
    ring_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        layer_cls = nn.remat(BertLayer) if self.remat else BertLayer
        for i in range(self.layers_per_stage):
            x = layer_cls(self.num_heads, self.mlp_dim, self.dtype,
                          ring_axis=self.ring_axis,
                          name="layer_%d" % i)(x)
        return x


class BertHead(nn.Module):
    """The pipeline ``decode`` end: activations → logits (last stage).
    mean_pool replaces CLS pooling (required under sequence parallelism,
    where token 0 lives on one shard; seq_axis pmean makes the pooled
    vector global)."""
    d_model: int
    num_classes: int
    mean_pool: bool = False
    seq_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        if self.mean_pool:
            pooled_in = x.mean(axis=1)
            if self.seq_axis:
                pooled_in = jax.lax.pmean(pooled_in, self.seq_axis)
        else:
            pooled_in = x[:, 0]
        pooled = jnp.tanh(nn.Dense(self.d_model, dtype=jnp.float32,
                                   param_dtype=jnp.float32,
                                   name="pooler")(pooled_in))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="classifier")(pooled)


def create_bert_pipeline(pp, num_layers=4, d_model=64, num_heads=4,
                         mlp_dim=128, vocab_size=1000, max_len=128,
                         num_classes=2, seq_len=16, dtype=jnp.bfloat16,
                         seed=0, seq_parallel_axis=None):
    """A BERT classifier factored for pipeline parallelism.

    Returns (params, encode_fn, stage_fn, decode_fn, sequential_loss):
    params = {"encode", "stages" (leading stage axis [pp, ...]), "decode"}
    for ``pipeline_value_and_grad``; ``sequential_loss(params, ids,
    labels)`` is the numerically-identical unpipelined composite for
    grad-equivalence tests and single-chip runs.

    seq_parallel_axis composes sequence parallelism into the pipeline:
    the apply fns run on seq SLICES inside the pipeline's shard_map —
    shard-offset positions, in-shard ring attention, pmean mean-pooling —
    and decode returns this shard's loss contribution (pass the same
    axis name as pipeline_value_and_grad's seq_axes). Params are
    identical either way (attention impl and pooling don't change the
    tree), so init uses the plain modules.
    """
    if num_layers % pp != 0:
        raise ValueError("num_layers %d not divisible by pp %d"
                         % (num_layers, pp))
    spa = seq_parallel_axis
    mean_pool = spa is not None
    # init twins (no collectives — init runs outside any shard_map)
    embed = BertEmbed(vocab_size, d_model, max_len, dtype)
    stage = BertStage(num_layers // pp, num_heads, mlp_dim, dtype)
    head = BertHead(d_model, num_classes, mean_pool=mean_pool)
    # apply variants (collectives over spa, valid inside shard_map)
    embed_sp = BertEmbed(vocab_size, d_model, max_len, dtype,
                         seq_axis=spa)
    stage_sp = BertStage(num_layers // pp, num_heads, mlp_dim, dtype,
                         ring_axis=spa)
    head_sp = BertHead(d_model, num_classes, mean_pool=mean_pool,
                       seq_axis=spa)

    root = jax.random.PRNGKey(seed)
    k_embed, k_head, *k_stages = jax.random.split(root, 2 + pp)
    ids = jnp.zeros((1, seq_len), jnp.int32)
    p_enc = embed.init(k_embed, ids)["params"]
    act = embed.apply({"params": p_enc}, ids)
    per_stage = [stage.init(k, act)["params"] for k in k_stages]
    p_stages = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage)
    p_dec = head.init(k_head, act)["params"]
    params = {"encode": p_enc, "stages": p_stages, "decode": p_dec}

    def encode_fn(p, batch_x):
        return embed_sp.apply({"params": p}, batch_x)

    def stage_fn(p, x):
        return stage_sp.apply({"params": p}, x)

    def decode_fn(p, x, labels):
        logits = head_sp.apply({"params": p}, x)
        one_hot = jax.nn.one_hot(labels, num_classes)
        loss = optax.softmax_cross_entropy(logits, one_hot).mean()
        if spa:
            # per-shard CONTRIBUTION: the engine sums over seq_axes
            loss = loss / jax.lax.psum(1, spa)
        return loss

    def sequential_loss(params, batch_x, labels):
        """Unsharded reference: dense attention on the full sequence."""
        x = embed.apply({"params": params["encode"]}, batch_x)
        for s in range(pp):
            p_s = jax.tree_util.tree_map(lambda a: a[s], params["stages"])
            x = stage.apply({"params": p_s}, x)
        logits = head.apply({"params": params["decode"]}, x)
        one_hot = jax.nn.one_hot(labels, num_classes)
        return optax.softmax_cross_entropy(logits, one_hot).mean()

    return params, encode_fn, stage_fn, decode_fn, sequential_loss


def bert_base(**kw):
    return Bert(**kw)


def bert_tiny(**kw):
    """4-layer test-size config."""
    kw.setdefault("num_layers", 4)
    kw.setdefault("d_model", 64)
    kw.setdefault("num_heads", 4)
    kw.setdefault("mlp_dim", 128)
    kw.setdefault("vocab_size", 1000)
    kw.setdefault("max_len", 128)
    return Bert(**kw)


def bert_partition_rules():
    """Megatron-style TP rules: column-shard up-projections, row-shard
    down-projections, vocab-shard embeddings; everything else replicated."""
    return [
        (r"attention/(query|key|value)/kernel", P(None, "tp", None)),
        (r"attention/out/kernel", P("tp", None, None)),
        (r"mlp_up/kernel", P(None, "tp")),
        (r"mlp_down/kernel", P("tp", None)),
        (r"word_embed/embedding", P("tp", None)),
    ]


def create_model_and_loss(model=None, dummy_batch=1, dummy_seq=16,
                          moe_aux_weight=0.01, moe_z_weight=1e-3, **kw):
    """(model, params, loss_fn) for ElasticTrainer (classification).

    dummy_batch/dummy_seq size the init trace — sharded models (use_ring
    over sp, MoE over ep) need init shapes divisible by their mesh axes.

    For MoE configs the sowed router losses are folded into the training
    loss: + moe_aux_weight * Σ load-balance (Switch's 0.01 default)
    + moe_z_weight * Σ router z-loss (ST-MoE's 1e-3 default).
    """
    model = model or bert_tiny(**kw)
    dummy = jnp.zeros((dummy_batch, dummy_seq), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), dummy)["params"]
    is_moe = bool(getattr(model, "moe_experts", 0))

    def loss_fn(params, batch, rng):
        if is_moe:
            # only "losses" is collected here — the "metrics" collection
            # (drop fraction) is for eval/debug applies, not the hot path
            logits, muts = model.apply(
                {"params": params}, batch["input_ids"],
                batch.get("attention_mask"), mutable=["losses"])
        else:
            logits = model.apply({"params": params}, batch["input_ids"],
                                 batch.get("attention_mask"))
        one_hot = jax.nn.one_hot(batch["label"], model.num_classes)
        loss = optax.softmax_cross_entropy(logits, one_hot).mean()
        if is_moe:
            sowed = jax.tree_util.tree_leaves_with_path(
                muts.get("losses", {}))
            for path, v in sowed:
                name = path[-2].key if len(path) >= 2 else ""
                w = moe_z_weight if name == "moe_z" else moe_aux_weight
                loss = loss + w * v
        return loss

    return model, params, loss_fn


def synthetic_text_batch(batch_size, seq_len=64, vocab_size=1000,
                         num_classes=2, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "input_ids": rng.randint(0, vocab_size,
                                 (batch_size, seq_len)).astype(np.int32),
        "label": rng.randint(0, num_classes,
                             (batch_size,)).astype(np.int32),
    }
