"""GPT decoder family: causal LM with KV-cache generation, TP/SP-ready.

Net-new vs the reference (its NLP scope stopped at classification
distillation — SURVEY.md §5.7 marks long-context/causal LM absent): a
decoder-only transformer for the model zoo, built on the same attention
substrate as BERT — dense causal attention by default, the Pallas flash
kernel (`edl_tpu/ops/flash_attention.py`) or ring attention over the sp
axis (`edl_tpu/parallel/ring_attention.py`) for long sequences — plus an
incremental-decode path (flax "cache" collection) so teacher-style
serving and sampling don't re-run the prefix per token.
"""

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P


class CausalSelfAttention(nn.Module):
    """Causal MHA with an optional single-token decode mode.

    decode=False: full-sequence causal attention via the shared
    edl_tpu.ops.attention.attention_context dispatch (dense / flash /
    ring).
    decode=True: x is [b, 1, d]; K/V are written into "cache" variables
    sized [b, max_len, h, hd] at ``decode_index`` — the ONE source of
    truth for the decode position (the same value drives the position
    embedding in Gpt), so a retried step overwrites its own slot instead
    of silently drifting — and attention runs against the prefix.
    ``decode_index`` may be a scalar (all rows at the same position, the
    ``generate`` path) or a [b] vector (each row at its OWN position —
    the slot-batched continuous-decode path in serve.decode_engine).

    prefill=True with ``prefill_offset`` set: x is a CHUNK of the prompt
    [b, C, d] whose first token sits at sequence position ``offset``;
    K/V are written into the cache at ``[offset, offset+C)`` and each
    chunk row attends the ALREADY-WRITTEN prefix ``[0, offset+i]`` —
    the Sarathi-style chunked-prefill primitive (and the suffix-prefill
    step of shared-prefix KV reuse, where ``[0, offset)`` was copied
    from a cached row). The mask runs against the full cache like the
    decode path, so junk beyond ``offset+C`` is never attended.

    ``use_flash=None`` (default) auto-dispatches dense→flash by kernel
    legality (see ops/attention.flash_dispatch_reason); True/False still
    force a path. The pre-auto default was ``False`` — pass it
    explicitly to pin the dense path."""
    num_heads: int
    max_len: int
    dtype: Any = jnp.bfloat16
    use_ring: bool = False
    use_flash: Optional[bool] = None
    mesh: Any = None
    ring_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, decode=False, decode_index=None,
                 prefill=False, prefill_offset=None):
        d_model = x.shape[-1]
        head_dim = d_model // self.num_heads
        dense = lambda feats, name: nn.DenseGeneral(
            feats, dtype=self.dtype, param_dtype=jnp.float32, name=name)
        q = dense((self.num_heads, head_dim), "query")(x)
        k = dense((self.num_heads, head_dim), "key")(x)
        v = dense((self.num_heads, head_dim), "value")(x)

        if prefill:
            # ONE batched causal forward over the whole prompt that also
            # fills cache slots [0:s] — generation then decodes only the
            # new tokens instead of re-feeding the prefix one at a time
            if self.ring_axis or self.use_ring:
                # the cache layout holds the FULL sequence per device;
                # a seq-sharded prefill would fill it with local slices
                raise ValueError("prefill does not support ring "
                                 "attention (seq-sharded K/V); build the "
                                 "serving model without use_ring")
            b, s = x.shape[:2]
            ck = self.variable(
                "cache", "k", jnp.zeros,
                (b, self.max_len, self.num_heads, head_dim), self.dtype)
            cv = self.variable(
                "cache", "v", jnp.zeros,
                (b, self.max_len, self.num_heads, head_dim), self.dtype)
            if prefill_offset is not None:
                # chunked / suffix prefill: write this chunk's K/V at the
                # offset and attend the full cache under the shifted
                # causal mask — chunk row i sees keys [0, off+i], i.e.
                # the already-written prefix plus its own chunk prefix.
                # Same dense-masked numeric class as the decode path
                # (f32 scores, -1e30 mask), so junk beyond off+s — rows
                # are reused without zeroing — is never attended.
                off = jnp.asarray(prefill_offset, jnp.int32)
                ck.value = jax.lax.dynamic_update_slice(
                    ck.value, k.astype(self.dtype), (0, off, 0, 0))
                cv.value = jax.lax.dynamic_update_slice(
                    cv.value, v.astype(self.dtype), (0, off, 0, 0))
                key_pos = jnp.arange(self.max_len)[None, None, None, :]
                q_pos = (off + jnp.arange(s))[None, None, :, None]
                mask = key_pos <= q_pos
                scale = head_dim ** -0.5
                scores = jnp.einsum(
                    "bqhd,bkhd->bhqk", (q * scale).astype(jnp.float32),
                    ck.value.astype(jnp.float32))
                scores = jnp.where(mask, scores, -1e30)
                probs = jax.nn.softmax(scores, axis=-1)
                ctx = jnp.einsum("bhqk,bkhd->bqhd", probs,
                                 cv.value.astype(jnp.float32))
                ctx = ctx.astype(self.dtype)
            else:
                ck.value = jax.lax.dynamic_update_slice(
                    ck.value, k.astype(self.dtype), (0, 0, 0, 0))
                cv.value = jax.lax.dynamic_update_slice(
                    cv.value, v.astype(self.dtype), (0, 0, 0, 0))
                from edl_tpu.ops.attention import attention_context
                ctx = attention_context(q, k, v, causal=True, mask=None,
                                        dtype=self.dtype,
                                        use_flash=self.use_flash)
        elif decode:
            if x.shape[1] != 1:
                raise ValueError("decode mode feeds one token at a time")
            if decode_index is None:
                raise ValueError("decode mode needs decode_index")
            b = x.shape[0]
            ck = self.variable(
                "cache", "k", jnp.zeros,
                (b, self.max_len, self.num_heads, head_dim), self.dtype)
            cv = self.variable(
                "cache", "v", jnp.zeros,
                (b, self.max_len, self.num_heads, head_dim), self.dtype)
            idx = jnp.asarray(decode_index, jnp.int32)
            if idx.ndim == 0:
                ck.value = jax.lax.dynamic_update_slice(
                    ck.value, k.astype(self.dtype), (0, idx, 0, 0))
                cv.value = jax.lax.dynamic_update_slice(
                    cv.value, v.astype(self.dtype), (0, idx, 0, 0))
                mask = (jnp.arange(self.max_len)[None, None, None, :]
                        <= idx)
            else:
                # vector decode_index: one position PER ROW, the slot
                # layout of the continuous-batching engine — every slot
                # advances through its own sequence independently inside
                # ONE fixed-shape step (scatter write + per-row prefix
                # mask; no recompile as slot membership churns)
                if idx.shape != (b,):
                    raise ValueError(
                        "vector decode_index must be [batch]=%d, got %s"
                        % (b, idx.shape))
                rows = jnp.arange(b)
                ck.value = ck.value.at[rows, idx].set(
                    k[:, 0].astype(self.dtype))
                cv.value = cv.value.at[rows, idx].set(
                    v[:, 0].astype(self.dtype))
                mask = (jnp.arange(self.max_len)[None, None, None, :]
                        <= idx[:, None, None, None])
            scale = head_dim ** -0.5
            scores = jnp.einsum(
                "bqhd,bkhd->bhqk", (q * scale).astype(jnp.float32),
                ck.value.astype(jnp.float32))
            scores = jnp.where(mask, scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", probs,
                             cv.value.astype(jnp.float32))
            ctx = ctx.astype(self.dtype)
        else:
            from edl_tpu.ops.attention import attention_context
            ctx = attention_context(
                q, k, v, causal=True, mask=None, dtype=self.dtype,
                ring_axis=self.ring_axis, use_ring=self.use_ring,
                use_flash=self.use_flash, mesh=self.mesh)
        return nn.DenseGeneral(d_model, axis=(-2, -1), dtype=self.dtype,
                               param_dtype=jnp.float32, name="out")(ctx)


class GptBlock(nn.Module):
    """Pre-LN decoder block: x + attn(ln(x)); x + mlp(ln(x)).

    ``use_flash``: None = auto (flash where legal on TPU), True/False
    force; was ``False`` before the auto default."""
    num_heads: int
    mlp_dim: int
    max_len: int
    dtype: Any = jnp.bfloat16
    use_ring: bool = False
    use_flash: Optional[bool] = None
    mesh: Any = None
    ring_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, decode=False, decode_index=None,
                 prefill=False, prefill_offset=None):
        h = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                         name="ln_attn")(x)
        x = x + CausalSelfAttention(
            self.num_heads, self.max_len, self.dtype, self.use_ring,
            self.use_flash, self.mesh, ring_axis=self.ring_axis,
            name="attention")(h, decode=decode,
                              decode_index=decode_index,
                              prefill=prefill,
                              prefill_offset=prefill_offset)
        h = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                         name="ln_mlp")(x)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype,
                     param_dtype=jnp.float32, name="mlp_up")(h)
        h = nn.gelu(h)
        h = nn.Dense(x.shape[-1], dtype=self.dtype,
                     param_dtype=jnp.float32, name="mlp_down")(h)
        return x + h


class Gpt(nn.Module):
    """Decoder-only causal LM; logits via the tied word embedding.

    ``use_flash``: None = auto-dispatch (Pallas flash on TPU when the
    shape is kernel-legal, dense otherwise — numerics-gated vs dense in
    tier-1), True = force flash, False = force dense. The default was
    ``False`` until the roofline-gap PR; explicit callers are
    unaffected."""
    vocab_size: int = 32000
    num_layers: int = 12
    d_model: int = 768
    num_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 1024
    dtype: Any = jnp.bfloat16
    use_ring: bool = False
    use_flash: Optional[bool] = None
    mesh: Any = None
    ring_axis: Optional[str] = None
    remat: bool = False

    @nn.compact
    def __call__(self, input_ids, decode=False, decode_index=None,
                 prefill=False, prefill_offset=None):
        # Embed with dtype=f32 so the tied-head attend() computes fp32
        # logits (Embed.attend promotes to its OWN dtype — a bf16 embed
        # would silently demote the logits); the activation stream is
        # cast down explicitly instead.
        embed = nn.Embed(self.vocab_size, self.d_model,
                         param_dtype=jnp.float32, dtype=jnp.float32,
                         name="word_embed")
        x = embed(input_ids).astype(self.dtype)
        s = input_ids.shape[1]
        if decode:
            if decode_index is None:
                raise ValueError("decode mode needs decode_index")
            idx = jnp.asarray(decode_index, jnp.int32)
            if idx.ndim == 0:
                pos_ids = jnp.full((1, s), idx, jnp.int32)
            else:
                # per-row positions (slot-batched decode): row i sits at
                # its own sequence offset
                pos_ids = idx[:, None]
        else:
            pos_ids = jnp.arange(s)[None, :]
            if prefill and prefill_offset is not None:
                # chunk rows sit at absolute positions off..off+s-1
                pos_ids = pos_ids + jnp.asarray(prefill_offset, jnp.int32)
            if self.ring_axis:
                pos_ids = pos_ids + jax.lax.axis_index(self.ring_axis) * s
        x = x + nn.Embed(self.max_len, self.d_model,
                         param_dtype=jnp.float32, dtype=self.dtype,
                         name="pos_embed")(pos_ids)
        # remat is a TRAINING lever; on the decode/prefill paths it is
        # useless AND nn.remat would trace the boolean kwargs into
        # abstract values (TracerBoolConversionError — caught by the
        # r5 static accounting, which compiled remat=True for the
        # first time; the tunnel had been down since the flag landed)
        use_remat = self.remat and not decode and not prefill
        block_cls = nn.remat(GptBlock) if use_remat else GptBlock
        for i in range(self.num_layers):
            block = block_cls(self.num_heads, self.mlp_dim,
                              self.max_len, self.dtype, self.use_ring,
                              self.use_flash, self.mesh,
                              ring_axis=self.ring_axis,
                              name="block_%d" % i)
            if use_remat:
                x = block(x)  # training defaults; no traced bools
            else:
                x = block(x, decode=decode, decode_index=decode_index,
                          prefill=prefill, prefill_offset=prefill_offset)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                         name="ln_final")(x)
        # weight-tied LM head (embed.attend = x @ embedding.T)
        return embed.attend(x.astype(jnp.float32))


class GptEmbed(nn.Module):
    """Pipeline ``encode`` end: token ids → activations. With seq_axis
    set (in-shard sequence parallelism) each shard embeds its seq SLICE
    with shard-offset positions."""
    vocab_size: int
    d_model: int
    max_len: int
    dtype: Any = jnp.bfloat16
    seq_axis: Optional[str] = None

    @nn.compact
    def __call__(self, input_ids):
        s = input_ids.shape[1]
        x = nn.Embed(self.vocab_size, self.d_model,
                     param_dtype=jnp.float32, dtype=self.dtype,
                     name="word_embed")(input_ids)
        pos_ids = jnp.arange(s)[None, :]
        if self.seq_axis:
            pos_ids = pos_ids + jax.lax.axis_index(self.seq_axis) * s
        return x + nn.Embed(self.max_len, self.d_model,
                            param_dtype=jnp.float32, dtype=self.dtype,
                            name="pos_embed")(pos_ids)


class GptStage(nn.Module):
    """One pipeline stage: ``layers_per_stage`` causal blocks. ring_axis
    composes sequence parallelism INTO the stage (causal in-shard ring —
    cross-shard causality is the ring algorithm's job)."""
    layers_per_stage: int
    num_heads: int
    mlp_dim: int
    max_len: int
    dtype: Any = jnp.bfloat16
    remat: bool = False
    ring_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        block_cls = nn.remat(GptBlock) if self.remat else GptBlock
        for i in range(self.layers_per_stage):
            x = block_cls(self.num_heads, self.mlp_dim, self.max_len,
                          self.dtype, ring_axis=self.ring_axis,
                          name="block_%d" % i)(x)
        return x


class GptHead(nn.Module):
    """Pipeline ``decode`` end: final LN + (untied) LM head. The tied
    head of ``Gpt`` would couple decode params to the encode stage across
    the pipeline, so the factored form unties it."""
    vocab_size: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                         name="ln_final")(x)
        return nn.Dense(self.vocab_size, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="lm_head")(x)


def create_gpt_pipeline(pp, num_layers=4, d_model=64, num_heads=4,
                        mlp_dim=128, vocab_size=256, max_len=128,
                        seq_len=32, dtype=jnp.bfloat16, seed=0,
                        seq_parallel_axis=None):
    """A causal LM factored for pipeline parallelism.

    Returns (params, encode_fn, stage_fn, decode_fn, sequential_loss)
    for ``pipeline_value_and_grad`` (same contract as
    bert.create_bert_pipeline). ``y`` passed to the engine is the FULL
    [batch, seq] id tensor (replicated along seq shards); the decode end
    computes the next-token loss, and under ``seq_parallel_axis`` each
    shard slices its own global-offset targets from it and returns its
    loss CONTRIBUTION (the engine sums over seq shards). The boundary
    token between neighboring shards is handled by the global slicing —
    the last local position of shard i targets the first token of shard
    i+1."""
    if num_layers % pp != 0:
        raise ValueError("num_layers %d not divisible by pp %d"
                         % (num_layers, pp))
    spa = seq_parallel_axis
    embed = GptEmbed(vocab_size, d_model, max_len, dtype)
    stage = GptStage(num_layers // pp, num_heads, mlp_dim, max_len, dtype)
    head = GptHead(vocab_size, dtype)
    embed_sp = GptEmbed(vocab_size, d_model, max_len, dtype, seq_axis=spa)
    stage_sp = GptStage(num_layers // pp, num_heads, mlp_dim, max_len,
                        dtype, ring_axis=spa)

    root = jax.random.PRNGKey(seed)
    k_embed, k_head, *k_stages = jax.random.split(root, 2 + pp)
    ids = jnp.zeros((1, seq_len), jnp.int32)
    p_enc = embed.init(k_embed, ids)["params"]
    act = embed.apply({"params": p_enc}, ids)
    per_stage = [stage.init(k, act)["params"] for k in k_stages]
    p_stages = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage)
    p_dec = head.init(k_head, act)["params"]
    params = {"encode": p_enc, "stages": p_stages, "decode": p_dec}

    def encode_fn(p, batch_x):
        return embed_sp.apply({"params": p}, batch_x)

    def stage_fn(p, x):
        return stage_sp.apply({"params": p}, x)

    def _lm_loss(logits, y, shard_idx):
        """Loss contribution of this shard's logits [b, s_loc, V] given
        the FULL targets y [b, s_glob]: local position j predicts global
        token shard_idx*s_loc + j + 1; the final global position has no
        target and is masked. Normalized by the GLOBAL token count so
        contributions sum to the sequential mean."""
        b, s_loc = logits.shape[:2]
        s_glob = y.shape[1]
        # pad y so the last shard's slice never overruns
        y_pad = jnp.concatenate(
            [y, jnp.zeros((b, 1), y.dtype)], axis=1)
        tgt = jax.lax.dynamic_slice(
            y_pad, (0, shard_idx * s_loc + 1), (b, s_loc))
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), tgt)
        glob_pos = shard_idx * s_loc + jnp.arange(s_loc)
        valid = (glob_pos < s_glob - 1).astype(jnp.float32)
        return (ce * valid[None]).sum() / (b * (s_glob - 1))

    def decode_fn(p, x, y):
        logits = head.apply({"params": p}, x)
        if spa:
            return _lm_loss(logits, y, jax.lax.axis_index(spa))
        return _lm_loss(logits, y, 0)

    def sequential_loss(params, batch_x, y):
        x = embed.apply({"params": params["encode"]}, batch_x)
        for s_i in range(pp):
            p_s = jax.tree_util.tree_map(lambda a: a[s_i],
                                         params["stages"])
            x = stage.apply({"params": p_s}, x)
        logits = head.apply({"params": params["decode"]}, x)
        return _lm_loss(logits, y, 0)

    return params, encode_fn, stage_fn, decode_fn, sequential_loss


def gpt_partition_rules():
    """Megatron-style TP rules, same scheme as bert_partition_rules."""
    return [
        (r"attention/(query|key|value)/kernel", P(None, "tp", None)),
        (r"attention/out/kernel", P("tp", None, None)),
        (r"mlp_up/kernel", P(None, "tp")),
        (r"mlp_down/kernel", P("tp", None)),
        (r"word_embed/embedding", P("tp", None)),
    ]


def gpt_tiny(**kw):
    kw.setdefault("num_layers", 4)
    kw.setdefault("d_model", 64)
    kw.setdefault("num_heads", 4)
    kw.setdefault("mlp_dim", 128)
    kw.setdefault("vocab_size", 256)
    kw.setdefault("max_len", 128)
    return Gpt(**kw)


def create_model_and_loss(model=None, dummy_batch=1, dummy_seq=16, **kw):
    """(model, params, loss_fn) for ElasticTrainer — next-token
    cross-entropy over batch["input_ids"] (shift inside)."""
    model = model or gpt_tiny(**kw)
    dummy = jnp.zeros((dummy_batch, dummy_seq), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), dummy)["params"]

    def loss_fn(params, batch, rng):
        ids = batch["input_ids"]
        logits = model.apply({"params": params}, ids)
        # predict token t+1 from prefix <= t; integer-label form avoids
        # materializing a [b, s, vocab] one-hot at LM vocab sizes
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], ids[:, 1:]).mean()

    return model, params, loss_fn


def init_cache(model, params, batch_size):
    """Zeroed KV caches for incremental decode. Shapes come from
    eval_shape over init — no parameter tensor is materialized, and the
    cache contents (which init would have polluted with the dummy
    token's K/V) are created as real zeros."""
    dummy = jnp.zeros((batch_size, 1), jnp.int32)
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), dummy, decode=True,
                           decode_index=jnp.zeros((), jnp.int32)))
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"])


def _filter_logits(logits, top_k=0, top_p=0.0):
    """Mask logits outside the sampling nucleus: keep the top_k largest
    (0 = all) and/or the smallest prefix of the sorted distribution whose
    probability mass reaches top_p (0 = all). Static shapes throughout
    (sort + mask, no dynamic gather sizes) so it scans under jit."""
    if top_k and top_k > 0:
        k = min(top_k, logits.shape[-1])
        kth = jax.lax.top_k(logits, k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p and 0.0 < top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep ranks whose PRECEDING mass is < top_p (always >= 1 token)
        keep = jnp.concatenate(
            [jnp.zeros_like(cum[..., :1]), cum[..., :-1]], axis=-1) < top_p
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf),
                         axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def generate(model, params, prompt_ids, max_new_tokens, rng=None,
             temperature=0.0, top_k=0, top_p=0.0):
    """Autoregressive sampling with the KV cache: ONE batched prefill
    forward fills the cache over the whole prompt (no per-token prefix
    re-feeding), then a lax.scan decodes ``max_new_tokens`` (greedy at
    temperature 0; temperature > 0 samples, optionally truncated to the
    ``top_k`` largest logits and/or the ``top_p`` nucleus). Returns
    [b, prompt+new] ids."""
    b, prompt_len = prompt_ids.shape
    total = prompt_len + max_new_tokens
    if total > model.max_len:
        raise ValueError("prompt+new %d exceeds max_len %d"
                         % (total, model.max_len))
    if max_new_tokens < 1:
        return prompt_ids
    cache = init_cache(model, params, b)
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    def sample(logits, feed_pos):
        if temperature > 0:
            # temperature FIRST, then the nucleus: top_p must be a mass
            # of the actual sampling distribution (the HF processor order)
            scaled = _filter_logits(logits / temperature, top_k=top_k,
                                    top_p=top_p)
            nxt = jax.random.categorical(
                jax.random.fold_in(rng, feed_pos), scaled, axis=-1)
        else:
            nxt = jnp.argmax(logits, axis=-1)
        return nxt.astype(jnp.int32)

    logits, muts = model.apply(
        {"params": params, "cache": cache}, prompt_ids, prefill=True,
        mutable=["cache"])
    cache = muts["cache"]
    first = sample(logits[:, -1], prompt_len - 1)
    seq0 = jnp.concatenate(
        [prompt_ids, first[:, None],
         jnp.zeros((b, max_new_tokens - 1), jnp.int32)], axis=1)

    def step(carry, t):
        cache, seq, tok = carry
        logits, muts = model.apply(
            {"params": params, "cache": cache}, tok[:, None],
            decode=True, decode_index=t, mutable=["cache"])
        nxt = sample(logits[:, 0], t)
        seq = jax.lax.dynamic_update_slice(seq, nxt[:, None], (0, t + 1))
        return (muts["cache"], seq, nxt), None

    # feed positions prompt_len..total-2; position t produces token t+1
    (_, seq, _), _ = jax.lax.scan(
        step, (cache, seq0, first),
        jnp.arange(prompt_len, total - 1))
    return seq


def synthetic_lm_batch(batch_size, seq_len=32, vocab_size=256, seed=0):
    """Learnable synthetic stream: arithmetic sequences mod vocab (each
    next token is prev + step, a pattern a causal LM can learn)."""
    rng = np.random.RandomState(seed)
    start = rng.randint(0, vocab_size, (batch_size, 1))
    step = rng.randint(1, 7, (batch_size, 1))
    pos = np.arange(seq_len)[None, :]
    ids = (start + step * pos) % vocab_size
    return {"input_ids": ids.astype(np.int32)}
