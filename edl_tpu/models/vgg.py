"""VGG 11/13/16/19 in flax.linen, bf16-first for the MXU.

Reference parity: the second family in the collective example's model zoo
(example/collective/resnet50/models/vgg.py:37-115 — 5 conv blocks of
[1,1,2,2,2]/[2,2,2,2,2]/[2,2,3,3,3]/[2,2,4,4,4] 3x3 convs + 2x2 max
pools, then 4096-4096-classes FCs with dropout 0.5). TPU-first: NHWC,
bfloat16 compute with float32 params; ``global_pool`` replaces the
7x7x512→4096 flatten with global average pooling, making the head
input-size-independent (finetuning at non-224 resolutions).
"""

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

VGG_SPECS = {
    11: (1, 1, 2, 2, 2),
    13: (2, 2, 2, 2, 2),
    16: (2, 2, 3, 3, 3),
    19: (2, 2, 4, 4, 4),
}


class VGG(nn.Module):
    depth: int = 16
    num_classes: int = 1000
    fc_dim: int = 4096
    dtype: Any = jnp.bfloat16
    dropout: float = 0.5
    global_pool: bool = False  # avg-pool instead of flatten (size-free)

    @nn.compact
    def __call__(self, x, train=False):
        if self.depth not in VGG_SPECS:
            raise ValueError("supported depths %s, got %d"
                             % (sorted(VGG_SPECS), self.depth))
        x = x.astype(self.dtype)
        for block, (filters, n_convs) in enumerate(
                zip((64, 128, 256, 512, 512), VGG_SPECS[self.depth])):
            for i in range(n_convs):
                x = nn.Conv(filters, (3, 3), dtype=self.dtype,
                            param_dtype=jnp.float32,
                            name="conv%d_%d" % (block + 1, i + 1))(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        if self.global_pool:
            x = x.mean(axis=(1, 2))
        else:
            x = x.reshape((x.shape[0], -1))
        for i, name in enumerate(("fc6", "fc7")):
            x = nn.relu(nn.Dense(self.fc_dim, dtype=self.dtype,
                                 param_dtype=jnp.float32, name=name)(x))
            x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="fc8")(x)


def VGG11(**kw):
    return VGG(depth=11, **kw)


def VGG13(**kw):
    return VGG(depth=13, **kw)


def VGG16(**kw):
    return VGG(depth=16, **kw)


def VGG19(**kw):
    return VGG(depth=19, **kw)


def create_model_and_loss(depth=16, num_classes=1000, image_size=224,
                          fc_dim=4096, dtype=jnp.bfloat16,
                          label_smoothing=0.1):
    """(model, params, loss_fn) wired for ElasticTrainer (no aux state —
    VGG has no BatchNorm)."""
    model = VGG(depth=depth, num_classes=num_classes, fc_dim=fc_dim,
                dtype=dtype)
    dummy = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), dummy,
                        train=False)["params"]

    def loss_fn(params, batch, rng):
        logits = model.apply({"params": params}, batch["image"],
                             train=True, rngs={"dropout": rng})
        one_hot = optax.smooth_labels(
            jax.nn.one_hot(batch["label"], num_classes), label_smoothing)
        return optax.softmax_cross_entropy(logits, one_hot).mean()

    return model, params, loss_fn
