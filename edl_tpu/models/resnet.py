"""ResNet / ResNet-vd family in flax.linen, bf16-first for the MXU.

Reference parity: the models zoo used by the collective example
(example/collective/resnet50/models/resnet.py + resnet_vd variants; the
headline benchmark model is ResNet50_vd — README.md:83). Built TPU-first:
NHWC layout, bfloat16 compute with float32 params/BN statistics, and
cross-replica BatchNorm for free via sharded-batch jit (XLA inserts the
mean/var all-reduce from the sharding annotations).

The vd tweaks vs vanilla ResNet:
- deep stem: three 3x3 convs (32, 32, 64) instead of one 7x7;
- stride-2 moved off the 1x1 bottleneck conv onto the 3x3;
- downsampling shortcuts use avg_pool then stride-1 1x1 conv.
"""

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.ops.batch_norm import SubsetBatchNorm


def _make_norm(train, dtype, bn_stats_every):
    """The BN constructor shared by stems and blocks: flax BatchNorm for
    full-batch statistics, SubsetBatchNorm (same variable structure, so
    checkpoint-compatible) when statistics come from a strided subset of
    the batch — the BN-bandwidth lever measured in edl_tpu/ops/batch_norm.py."""
    if bn_stats_every > 1:
        return partial(SubsetBatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=dtype,
                       param_dtype=jnp.float32,
                       stats_every=bn_stats_every)
    return partial(nn.BatchNorm, use_running_average=not train,
                   momentum=0.9, epsilon=1e-5, dtype=dtype,
                   param_dtype=jnp.float32)

DEPTH_CONFIGS = {
    18: ((2, 2, 2, 2), False),
    34: ((3, 4, 6, 3), False),
    50: ((3, 4, 6, 3), True),
    101: ((3, 4, 23, 3), True),
    152: ((3, 8, 36, 3), True),
}


def space_to_depth(x, block=2):
    """[B, H, W, C] -> [B, H/b, W/b, b*b*C] (channel = (di*b+dj)*C + c)."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // block, w // block, block * block * c)


class _S2DStemConv(nn.Module):
    """The vd stem's 3x3/stride-2 conv on 3 channels, computed on the
    space-to-depth input instead (MLPerf-style TPU optimization).

    A 3-channel 224x224 conv runs the MXU at K=27 contraction depth —
    mostly padding. On the 2x2 space-to-depth image it becomes a DENSE
    stride-1 2x2 conv with K=48: the trained parameter stays the original
    [3,3,3,F] kernel (checkpoint-compatible either way); it is scattered
    into the equivalent [2,2,4*3,F] kernel inside the step, which is exact
    — every (tap, packed-channel) pair maps to one original (u,v,c) weight
    or to zero where the 4x4 region exceeds the 3x3 window.
    """
    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, y):
        # y: [B, H/2, W/2, 12] space-to-depth image
        in_c = 3
        w = self.param("kernel", nn.initializers.lecun_normal(),
                       (3, 3, in_c, self.features), jnp.float32)
        w2 = jnp.zeros((2, 2, 4 * in_c, self.features), w.dtype)
        for dp in range(2):
            for dq in range(2):
                for di in range(2):
                    for dj in range(2):
                        u, v = 2 * dp + di, 2 * dq + dj
                        if u < 3 and v < 3:
                            ch = (di * 2 + dj) * in_c
                            w2 = w2.at[dp, dq, ch:ch + in_c].set(w[u, v])
        return jax.lax.conv_general_dilated(
            y.astype(self.dtype), w2.astype(self.dtype),
            window_strides=(1, 1), padding=((0, 1), (0, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


class BottleneckBlock(nn.Module):
    filters: int
    stride: int
    vd: bool
    dtype: Any = jnp.bfloat16
    bn_stats_every: int = 1
    # ResNeXt: cardinality (grouped 3x3) and per-group base width; the
    # inner width is filters * base_width/64 * groups (groups=1,
    # base_width=64 = plain ResNet)
    groups: int = 1
    base_width: int = 64

    @nn.compact
    def __call__(self, x, train):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = _make_norm(train, self.dtype, self.bn_stats_every)
        width = int(self.filters * self.base_width / 64.0) * self.groups
        residual = x
        y = conv(width, (1, 1), name="conv1")(x)
        y = nn.relu(norm(name="bn1")(y))
        y = conv(width, (3, 3), strides=(self.stride, self.stride),
                 feature_group_count=self.groups, name="conv2")(y)
        y = nn.relu(norm(name="bn2")(y))
        y = conv(self.filters * 4, (1, 1), name="conv3")(y)
        y = norm(name="bn3", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            if self.vd and self.stride > 1:
                residual = nn.avg_pool(residual, (2, 2), strides=(2, 2))
                residual = conv(self.filters * 4, (1, 1),
                                name="downsample")(residual)
            else:
                residual = conv(self.filters * 4, (1, 1),
                                strides=(self.stride, self.stride),
                                name="downsample")(residual)
            residual = norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class BasicBlock(nn.Module):
    filters: int
    stride: int
    vd: bool
    dtype: Any = jnp.bfloat16
    bn_stats_every: int = 1

    @nn.compact
    def __call__(self, x, train):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = _make_norm(train, self.dtype, self.bn_stats_every)
        residual = x
        y = conv(self.filters, (3, 3), strides=(self.stride, self.stride),
                 name="conv1")(x)
        y = nn.relu(norm(name="bn1")(y))
        y = conv(self.filters, (3, 3), name="conv2")(y)
        y = norm(name="bn2", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            if self.vd and self.stride > 1:
                residual = nn.avg_pool(residual, (2, 2), strides=(2, 2))
                residual = conv(self.filters, (1, 1),
                                name="downsample")(residual)
            else:
                residual = conv(self.filters, (1, 1),
                                strides=(self.stride, self.stride),
                                name="downsample")(residual)
            residual = norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    depth: int = 50
    num_classes: int = 1000
    vd: bool = True
    dtype: Any = jnp.bfloat16
    stage_filters: Sequence[int] = (64, 128, 256, 512)
    # activation recompute per residual block: save only block boundaries,
    # recompute conv/BN internals in backward (reference knob:
    # train_with_fleet.py:322-325 fleet recompute checkpointing)
    remat: bool = False
    # MLPerf-style space-to-depth stem: exact, checkpoint-compatible
    # re-layout of the thin first conv (vd stems only)
    space_to_depth: bool = False
    # train-time BN statistics from x[::bn_stats_every] (1 = full batch;
    # 4 at batch 128/chip reproduces the reference's per-GPU stats batch
    # of 32 — see edl_tpu/ops/batch_norm.py)
    bn_stats_every: int = 1
    # ResNeXt cardinality/width (bottleneck depths only); the reference's
    # distill teacher config names ResNeXt101_32x16d_wsl (BASELINE.md)
    groups: int = 1
    base_width: int = 64

    @nn.compact
    def __call__(self, x, train=False):
        blocks_per_stage, bottleneck = DEPTH_CONFIGS[self.depth]
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = _make_norm(train, self.dtype, self.bn_stats_every)
        x = x.astype(self.dtype)
        if self.vd:
            if self.space_to_depth:
                x = _S2DStemConv(32, self.dtype, name="stem1")(
                    space_to_depth(x, 2))
            else:
                x = conv(32, (3, 3), strides=(2, 2), name="stem1")(x)
            x = nn.relu(norm(name="stem_bn1")(x))
            x = conv(32, (3, 3), name="stem2")(x)
            x = nn.relu(norm(name="stem_bn2")(x))
            x = conv(64, (3, 3), name="stem3")(x)
            x = nn.relu(norm(name="stem_bn3")(x))
        else:
            x = conv(64, (7, 7), strides=(2, 2), name="stem")(x)
            x = nn.relu(norm(name="stem_bn")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        block_cls = BottleneckBlock if bottleneck else BasicBlock
        if self.remat:
            # train is a static python bool → static_argnums (0 = self)
            block_cls = nn.remat(block_cls, static_argnums=(2,))
        block_kw = ({"groups": self.groups, "base_width": self.base_width}
                    if bottleneck else {})
        if not bottleneck and (self.groups != 1 or self.base_width != 64):
            raise ValueError("grouped (ResNeXt) blocks need a bottleneck "
                             "depth (>= 50), got depth=%d" % self.depth)
        for stage, (filters, n_blocks) in enumerate(
                zip(self.stage_filters, blocks_per_stage)):
            for i in range(n_blocks):
                stride = 2 if stage > 0 and i == 0 else 1
                x = block_cls(filters, stride, self.vd, self.dtype,
                              self.bn_stats_every,
                              name="stage%d_block%d" % (stage, i),
                              **block_kw)(x, train)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32, name="head")(x)
        return x


def ResNet50_vd(**kw):
    return ResNet(depth=50, vd=True, **kw)


def ResNeXt(depth=101, groups=32, base_width=16, **kw):
    """ResNeXt-{depth} {groups}x{base_width}d (e.g. the reference's
    distill teacher ResNeXt101_32x16d_wsl — BASELINE.md; 'wsl' names the
    weakly-supervised pretraining of the public weights, not an
    architecture difference). Vanilla (non-vd) stem by default, matching
    the canonical ResNeXt."""
    kw.setdefault("vd", False)
    return ResNet(depth=depth, groups=groups, base_width=base_width, **kw)


def ResNeXt101_32x16d(**kw):
    return ResNeXt(depth=101, groups=32, base_width=16, **kw)


def create_model_and_loss(depth=50, num_classes=1000, vd=True,
                          image_size=224, label_smoothing=0.1,
                          dtype=jnp.bfloat16, remat=False,
                          space_to_depth=False, bn_stats_every=1,
                          groups=1, base_width=64):
    """Build (model, params, batch_stats, loss_fn) wired for ElasticTrainer
    with has_aux=True — aux carries the BatchNorm running stats."""
    model = ResNet(depth=depth, num_classes=num_classes, vd=vd, dtype=dtype,
                   remat=remat, space_to_depth=space_to_depth,
                   bn_stats_every=bn_stats_every, groups=groups,
                   base_width=base_width)
    dummy = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), dummy, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})

    def loss_fn(params, extra, batch, rng):
        logits, updated = model.apply(
            {"params": params, "batch_stats": extra["batch_stats"]},
            batch["image"], train=True, mutable=["batch_stats"])
        labels = batch["label"]
        one_hot = optax.smooth_labels(
            jax.nn.one_hot(labels, num_classes), label_smoothing)
        loss = optax.softmax_cross_entropy(logits, one_hot).mean()
        return loss, {"batch_stats": updated["batch_stats"]}

    return model, params, {"batch_stats": batch_stats}, loss_fn


def synthetic_image_batch(batch_size, image_size=224, num_classes=1000,
                          seed=0):
    rng = np.random.RandomState(seed)
    return {
        "image": rng.randn(batch_size, image_size, image_size, 3)
                    .astype(np.float32),
        "label": rng.randint(0, num_classes, size=(batch_size,))
                    .astype(np.int32),
    }
