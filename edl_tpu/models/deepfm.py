"""DeepFM for CTR prediction.

Reference parity: the CTR example (example/ctr, BASELINE.json configs[3]).
The reference ran it parameter-server style; per BASELINE.md the TPU
mapping is data-parallel — embeddings live replicated (or vocab-sharded via
partition rules for huge tables) and gradients ride the dp all-reduce.
"""

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax


class DeepFM(nn.Module):
    field_vocab_sizes: Sequence[int]   # one vocab per categorical field
    embed_dim: int = 8
    mlp_dims: Sequence[int] = (128, 64)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, fields):
        """fields: int32 [batch, num_fields] of per-field category ids."""
        n_fields = len(self.field_vocab_sizes)
        # first-order weights and k-dim factors per field
        linear_terms, factors = [], []
        for i, vocab in enumerate(self.field_vocab_sizes):
            ids = fields[:, i]
            w = nn.Embed(vocab, 1, param_dtype=jnp.float32,
                         dtype=self.dtype, name="linear_%d" % i)(ids)
            v = nn.Embed(vocab, self.embed_dim, param_dtype=jnp.float32,
                         dtype=self.dtype, name="factor_%d" % i)(ids)
            linear_terms.append(w[:, 0])
            factors.append(v)
        vs = jnp.stack(factors, axis=1)          # [b, fields, k]
        first_order = sum(linear_terms)
        # FM second order: 0.5 * ((Σv)² − Σv²)
        sum_sq = jnp.square(vs.sum(axis=1))
        sq_sum = jnp.square(vs).sum(axis=1)
        second_order = 0.5 * (sum_sq - sq_sum).sum(axis=-1)
        # deep part over concatenated embeddings
        h = vs.reshape(vs.shape[0], n_fields * self.embed_dim)
        for j, dim in enumerate(self.mlp_dims):
            h = nn.relu(nn.Dense(dim, dtype=self.dtype,
                                 param_dtype=jnp.float32,
                                 name="deep_%d" % j)(h))
        deep = nn.Dense(1, dtype=self.dtype, param_dtype=jnp.float32,
                        name="deep_out")(h)[:, 0]
        bias = self.param("bias", nn.initializers.zeros, ())
        return first_order + second_order + deep + bias  # logit


class DeepFMTail(nn.Module):
    """DeepFM decoupled from ``nn.Embed``: the dense tail over
    PRE-GATHERED embedding rows.

    ``rows`` is ``[batch, num_fields, 1 + embed_dim]`` — each field's
    first-order weight and k-dim factor side by side, the combined-row
    layout :func:`combined_embedding_table` produces and the sharded
    embedding plane (:mod:`edl_tpu.embed`) serves. The op sequence and
    parameter names (``deep_%d``, ``deep_out``, ``bias``) replicate
    :class:`DeepFM` exactly, so the dense model's non-embedding param
    subtree (:func:`dense_tail_params`) applies verbatim and the
    logits match the dense path bitwise — the parity test's contract.
    """

    num_fields: int
    embed_dim: int = 8
    mlp_dims: Sequence[int] = (128, 64)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, rows):
        w = rows[..., 0].astype(self.dtype)        # [b, fields]
        vs = rows[..., 1:].astype(self.dtype)      # [b, fields, k]
        # same python-sum accumulation order as the dense loop
        first_order = sum(w[:, i] for i in range(self.num_fields))
        sum_sq = jnp.square(vs.sum(axis=1))
        sq_sum = jnp.square(vs).sum(axis=1)
        second_order = 0.5 * (sum_sq - sq_sum).sum(axis=-1)
        h = vs.reshape(vs.shape[0], self.num_fields * self.embed_dim)
        for j, dim in enumerate(self.mlp_dims):
            h = nn.relu(nn.Dense(dim, dtype=self.dtype,
                                 param_dtype=jnp.float32,
                                 name="deep_%d" % j)(h))
        deep = nn.Dense(1, dtype=self.dtype, param_dtype=jnp.float32,
                        name="deep_out")(h)[:, 0]
        bias = self.param("bias", nn.initializers.zeros, ())
        return first_order + second_order + deep + bias  # logit


def dense_tail_params(params):
    """The subtree of a dense :class:`DeepFM` param tree that
    :class:`DeepFMTail` consumes directly (everything but the
    embeddings)."""
    return {k: v for k, v in params.items()
            if k.startswith("deep_") or k == "bias"}


def field_offsets(field_vocab_sizes):
    """Per-field base row in the flat combined table (fields stacked
    in declaration order)."""
    return np.concatenate(
        [[0], np.cumsum(field_vocab_sizes)[:-1]]).astype(np.int64)


def flat_ctr_keys(fields, field_vocab_sizes):
    """Map per-field category ids ``[batch, num_fields]`` to keys into
    the single flat combined table: ``id + field_offset``, flattened
    row-major so ``reshape(batch, num_fields)`` restores slot order."""
    offs = field_offsets(field_vocab_sizes)
    return (np.asarray(fields, np.int64) + offs[None, :]).reshape(-1)


def combined_embedding_table(params, field_vocab_sizes):
    """Flatten a dense param tree's per-field embeddings into ONE host
    table ``[sum(vocabs), 1 + k]``: row = ``[linear | factor]``. One
    flat table means one sharded-plane table serves every field, and a
    single gather of :func:`flat_ctr_keys` feeds :class:`DeepFMTail`."""
    rows = []
    for i, _ in enumerate(field_vocab_sizes):
        lin = np.asarray(params["linear_%d" % i]["embedding"],
                         np.float32)
        fac = np.asarray(params["factor_%d" % i]["embedding"],
                         np.float32)
        rows.append(np.concatenate([lin, fac], axis=1))
    return np.ascontiguousarray(np.concatenate(rows, axis=0))


def create_model_and_loss(field_vocab_sizes=(100,) * 10, embed_dim=8,
                          mlp_dims=(64, 32)):
    model = DeepFM(field_vocab_sizes, embed_dim, mlp_dims)
    dummy = jnp.zeros((1, len(field_vocab_sizes)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), dummy)["params"]

    def loss_fn(params, batch, rng):
        logit = model.apply({"params": params}, batch["fields"])
        return optax.sigmoid_binary_cross_entropy(
            logit, batch["label"].astype(jnp.float32)).mean()

    return model, params, loss_fn


def synthetic_ctr_batch(batch_size, field_vocab_sizes=(100,) * 10, seed=0):
    """Clicks correlated with field 0 so learning is observable."""
    rng = np.random.RandomState(seed)
    n = len(field_vocab_sizes)
    fields = np.stack([rng.randint(0, v, batch_size)
                       for v in field_vocab_sizes], axis=1).astype(np.int32)
    prob = (fields[:, 0] % 10) / 10.0
    label = (rng.rand(batch_size) < prob).astype(np.int32)
    return {"fields": fields, "label": label}
