"""Subset-statistics BatchNorm: train-time mean/var from a strided slice
of the batch.

Why (TPU): profiling the ResNet50_vd train step on v5e showed the convs
running at ~87% MFU while ~15.8 ms of the 50 ms step went to BatchNorm
statistic reductions (`convert_reduce_fusion` reading the full activation
from HBM) — BN, not matmul, is the throughput ceiling. Computing the
statistics from every ``stats_every``-th row was built to cut that HBM
traffic by the same factor while normalizing the full batch.

PERF CAVEAT (r5 static accounting, PERF_ACCOUNTING.json): the TPU
compiler's own cost model says the subset slice BREAKS the conv->stats
fusion — full-batch stats fuse into the producing conv and read nothing
extra, while the strided subset forces an extra materialized pass, so
bn4 accounts MORE total bytes than bn1 (true for both the gather and
the lax.slice lowering; slice is kept as the cheaper of the two). Until
a live-hardware A/B says otherwise, ``stats_every`` is a STATISTICS
knob (matching the reference's 32-per-accelerator stats batch), not a
throughput lever; bench.py's default stays 1.

Why it is faithful: the reference's headline run normalizes over 32
images per accelerator (global batch 256 on 8 GPUs, per-GPU BatchNorm —
/root/reference/README.md:83 with example/collective/resnet50/
train_with_fleet.py batch math), so a v5e chip training at batch 128
with ``stats_every=4`` sees the *same* statistics batch (32) as the
reference; full-batch statistics are the stricter-than-reference default
(``stats_every=1``).

Under a dp-sharded batch the strided slice stays shard-local whenever
the per-device batch is divisible by ``stats_every`` (contiguous batch
partitions each contribute every ``stats_every``-th row), so the only
cross-device traffic is the [C]-vector statistics all-reduce XLA already
inserts — the sync-BN cost, not a resharding.

Variable/param structure matches ``flax.linen.BatchNorm`` exactly
("batch_stats": {mean, var} float32; "params": {scale, bias}), so models
can switch the flag without breaking checkpoints.
"""

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class SubsetBatchNorm(nn.Module):
    """BatchNorm over the trailing feature axis with train statistics
    computed from ``x[::stats_every]`` (``stats_every<=1`` = full batch).

    The normalization is applied in folded ``x * a + b`` form with ``a``
    and ``b`` precomputed in float32 from (scale, bias, mean, var) — one
    fused elementwise pass over the activation.

    Keep the effective stats batch (batch // stats_every) at >= ~32 —
    the reference's per-GPU stats batch. Measured on the 10-class gate
    (tests/test_examples_and_resize.py): 32-sample statistics converge
    indistinguishably from full-batch; 8-sample statistics cost real
    accuracy (0.8 vs 0.85+ under identical budgets). bench.py enforces
    a floor of 16.
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = None
    param_dtype: Any = jnp.float32
    use_scale: bool = True
    use_bias: bool = True
    scale_init: Any = nn.initializers.ones
    bias_init: Any = nn.initializers.zeros
    stats_every: int = 1

    @nn.compact
    def __call__(self, x, use_running_average=None):
        use_ra = nn.merge_param(
            "use_running_average", self.use_running_average,
            use_running_average)
        feat = x.shape[-1]
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((feat,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((feat,), jnp.float32))
        if self.use_scale:
            scale = self.param("scale", self.scale_init, (feat,),
                               self.param_dtype).astype(jnp.float32)
        else:
            scale = jnp.ones((feat,), jnp.float32)
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (feat,),
                              self.param_dtype).astype(jnp.float32)
        else:
            bias = jnp.zeros((feat,), jnp.float32)

        if use_ra:
            mean, var = ra_mean.value, ra_var.value
        else:
            k = max(1, self.stats_every)
            if k > 1 and x.shape[0] >= k:
                # lax.slice, NOT x[::k]: jnp's strided indexing lowers
                # to iota+gather (and scatter-add in the backward),
                # which XLA:TPU cannot fuse into the producing conv —
                # the static account showed it ADDING ~65% bytes
                # accessed to the step instead of cutting the stats
                # reads (PERF_ACCOUNTING.json, r5). The slice primitive
                # fuses, which is the entire point of subset stats.
                s = jax.lax.slice(
                    x, (0,) * x.ndim, x.shape,
                    (k,) + (1,) * (x.ndim - 1))
            else:
                s = x
            axes = tuple(range(s.ndim - 1))
            # one pass over s: E[x] and E[x^2] reduce together (the flax
            # use_fast_variance formulation), accumulated in f32
            mean = jnp.mean(s, axes, dtype=jnp.float32)
            m2 = jnp.mean(jax.lax.square(s.astype(jnp.float32)), axes)
            var = jnp.maximum(m2 - mean * mean, 0.0)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var

        inv = scale * jax.lax.rsqrt(var + self.epsilon)
        out_dtype = self.dtype or x.dtype
        a = inv.astype(out_dtype)
        b = (bias - mean * inv).astype(out_dtype)
        return x.astype(out_dtype) * a + b
