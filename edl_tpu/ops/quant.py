"""Absmax per-channel weight quantization for the teacher forward.

Serving is weight-bandwidth-bound at decode time: one token per step
means every matmul streams the full weight matrix from HBM for a [b, 1]
activation, so the decode roofline is set by weight bytes, not FLOPs.
Storing teacher kernels as int8 (absmax per output channel, f32 scales)
or bf16 halves/quarters that traffic; the dequant happens INSIDE the
jitted forward so XLA sees int8 arrays as inputs and fuses the
scale-multiply into the consumer matmul.

Scheme (int8): for a kernel ``w`` with input axis 0 (the Flax
DenseGeneral layout — axis 0 contracts, trailing axes are output
features), ``scale = max(|w|, axis=0) / 127`` and
``q = round(w / scale)``. Each output channel gets its own scale, so a
single outlier channel cannot crush the resolution of the rest — the
standard absmax-per-channel recipe (LLM.int8(), Dettmers et al. '22,
without the outlier decomposition: teacher kernels here are small and
well-conditioned, gated by the logits-parity test in tier-1).

What gets quantized: 2-D+ leaves whose path ends in ``kernel``
(attention q/k/v/out DenseGenerals, MLP up/down). Embeddings, biases
and LayerNorm scales stay f32 — the word embedding doubles as the tied
LM head, so quantizing it would perturb the logits directly for a
negligible byte win.

``QTensor`` is a registered pytree node: jitted functions take the
quantized tree as a regular argument and call :func:`dequantize_tree`
under trace.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class QTensor(NamedTuple):
    """int8 values + per-output-channel f32 scales (axis 0 reduced)."""
    values: Any   # int8 [in, *out]
    scale: Any    # f32  [1, *out]


def absmax_quantize(w, axis=0):
    """``(q, scale)`` with ``q*scale ~= w``; absmax per channel over
    ``axis`` (the contracting axis — every output channel keeps its own
    dynamic range)."""
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.where(amax > 0, amax, 1.0) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize(q, scale, dtype=jnp.float32):
    return (q.astype(jnp.float32) * scale).astype(dtype)


def int8_matmul(x, q, scale, dtype=jnp.float32):
    """``x @ dequant(q)`` with the scale applied AFTER the contraction:
    ``(x @ q) * scale`` — per-channel scales broadcast over the output
    axis, so the inner matmul runs on the int8 operand (XLA upcasts on
    platforms without native int8 MACs; on TPU the int8 operand halves
    the HBM read either way)."""
    acc = jnp.matmul(x.astype(jnp.float32), q.astype(jnp.float32))
    return (acc * scale).astype(dtype)


def _is_kernel(path):
    last = path[-1]
    key = getattr(last, "key", getattr(last, "name", None))
    return key == "kernel"


def quantize_tree(params, mode="int8"):
    """Quantize a Flax param tree for serving.

    mode="int8": 2-D+ ``kernel`` leaves become :class:`QTensor`
    (absmax per-channel over the contracting axis 0); everything else
    is left f32. mode="bf16": kernels are cast to bf16 (pure storage
    cast, no scales). Returns a tree :func:`dequantize_tree` restores.
    """
    if mode not in ("int8", "bf16"):
        raise ValueError("quantize mode must be int8|bf16, got %r" % mode)

    def _q(path, leaf):
        if not (_is_kernel(path) and getattr(leaf, "ndim", 0) >= 2):
            return leaf
        if mode == "bf16":
            return jnp.asarray(leaf, jnp.bfloat16)
        return QTensor(*absmax_quantize(leaf, axis=0))

    return jax.tree_util.tree_map_with_path(_q, params)


def dequantize_tree(params, dtype=jnp.float32):
    """Inverse of :func:`quantize_tree` — call INSIDE jit so the
    scale-multiply fuses into the consuming matmul and the int8 array is
    what crosses the host->device / HBM boundary."""
    def _dq(leaf):
        if isinstance(leaf, QTensor):
            return dequantize(leaf.values, leaf.scale, dtype)
        if getattr(leaf, "dtype", None) == jnp.bfloat16:
            return jnp.asarray(leaf, dtype)
        return leaf
    return jax.tree_util.tree_map(
        _dq, params, is_leaf=lambda x: isinstance(x, QTensor))


def quantized_bytes(params):
    """(bytes_quantized, bytes_fp32) for the tree — the advertised
    compression ratio in stats/bench output."""
    qb = fb = 0
    for leaf in jax.tree_util.tree_leaves(
            params, is_leaf=lambda x: isinstance(x, QTensor)):
        if isinstance(leaf, QTensor):
            n = leaf.values.size
            qb += n + leaf.scale.size * 4
            fb += n * 4
        else:
            qb += leaf.size * leaf.dtype.itemsize
            fb += leaf.size * 4
    return qb, fb
