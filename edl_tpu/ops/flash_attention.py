"""Pallas flash attention for TPU: blockwise online-softmax forward kernel
with a memory-efficient blockwise-recompute backward.

The hot op of the transformer models (edl_tpu/models/bert.py) and of the
teacher inference servers. Never materializes the [seq, seq] score matrix:

- forward: a Pallas kernel gridded over (batch*heads, q_blocks); each
  program streams kv blocks from VMEM with fp32 online-softmax
  accumulation on the MXU (q/k/v blocks sized to the 128-lane tiling);
- backward: custom_vjp that recomputes per-block attention under
  `lax.scan` (flash-style recompute — O(seq) memory, XLA-fused), so the
  kernel composes with jit/grad and with the ring-attention sp layer
  (edl_tpu/parallel/ring_attention.py) which shards the sequence BEFORE
  attention is applied per shard.

Layout: q, k, v are [batch, heads, seq, head_dim].
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                block_k, seq_len, causal, sm_scale, q_block):
    """One (bh, q_block, k_block) grid step. kv blocks stream through VMEM
    via the third grid dimension (fastest-varying, revisiting the same out
    block), so VMEM holds only tiles regardless of sequence length."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: blocks strictly right of the diagonal contribute nothing
    diag_ok = (ki * block_k <= qi * q_block + q_block - 1) if causal \
        else True

    @pl.when(diag_ok)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * sm_scale      # [TQ, d]
        tq = q.shape[0]
        k_blk = k_ref[0].astype(jnp.float32)             # [TK, d]
        v_blk = v_ref[0].astype(jnp.float32)
        scores = jax.lax.dot_general(                    # [TQ, TK]
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        q_pos = qi * q_block + lax.broadcasted_iota(jnp.int32, (tq, 1), 0)
        k_pos = ki * block_k + lax.broadcasted_iota(jnp.int32,
                                                    (1, block_k), 1)
        mask = k_pos < seq_len                           # ragged last block
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        scores = jnp.where(mask, scores, _NEG_INF)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, scores.max(axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        p = jnp.where(mask, p, 0.0)
        correction = jnp.exp(m_prev - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_ref[:] * correction + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:]
                    / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def _fwd_kernel_resident(q_ref, k_ref, v_ref, o_ref, *, block_k, seq_len,
                         causal, sm_scale, q_block):
    """Fast path for kv that fits VMEM: fori_loop over kv blocks so causal
    masking skips the loads AND compute right of the diagonal."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale        # [TQ, d]
    tq, d = q.shape
    q_pos = qi * q_block + lax.broadcasted_iota(jnp.int32, (tq, 1), 0)

    def body(ki, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(
            jnp.float32)
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(
            jnp.float32)
        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            mask = q_pos >= k_pos
            scores = jnp.where(mask, scores, _NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + p.sum(axis=-1, keepdims=True)
        acc_new = acc * correction + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc = jnp.zeros((tq, d), jnp.float32)
    m = jnp.full((tq, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((tq, 1), jnp.float32)
    if causal:
        last = lax.div(qi * q_block + (tq - 1), block_k) + 1
    else:
        last = seq_len // block_k
    acc, m, l = lax.fori_loop(0, last, body, (acc, m, l))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


# kv (k + v) resident in VMEM up to this many bytes; beyond it, stream
_RESIDENT_KV_BYTES = 4 << 20


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    b, h, s, d = q.shape
    sk = k.shape[2]
    bh = b * h
    qf = q.reshape(bh, s, d)
    kf = k.reshape(bh, sk, d)
    vf = v.reshape(bh, sk, d)
    block_q = min(block_q, s)
    block_k = min(block_k, sk)
    n_q = pl.cdiv(s, block_q)
    n_k = pl.cdiv(sk, block_k)

    kv_bytes = 2 * sk * d * k.dtype.itemsize
    if kv_bytes <= _RESIDENT_KV_BYTES and sk % block_k == 0:
        out = pl.pallas_call(
            functools.partial(_fwd_kernel_resident, block_k=block_k,
                              seq_len=sk, causal=causal, sm_scale=sm_scale,
                              q_block=block_q),
            grid=(bh, n_q),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, d),
                                   lambda i, j: (i, j, 0)),
            out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            interpret=interpret,
        )(qf, kf, vf)
        return out.reshape(b, h, s, d)

    out = pl.pallas_call(
        functools.partial(_fwd_kernel, block_k=block_k, seq_len=sk,
                          causal=causal, sm_scale=sm_scale,
                          q_block=block_q),
        grid=(bh, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kb: (i, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kb: (i, kb, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s, d)


def _block_layout(k, v, block_k):
    """Pad kv to a whole number of blocks and reshape for scanning:
    (kb, vb) are [n_blocks, b, h, block_k, d] f32. ONE copy of the
    layout shared by the blockwise forward and the recompute backward
    so the two can never disagree on padding."""
    b, h, sk, d = k.shape
    n_blocks = (sk + block_k - 1) // block_k
    pad = n_blocks * block_k - sk
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = kp.reshape(b, h, n_blocks, block_k, d).astype(
        jnp.float32).transpose(2, 0, 1, 3, 4)
    vb = vp.reshape(b, h, n_blocks, block_k, d).astype(
        jnp.float32).transpose(2, 0, 1, 3, 4)
    return kb, vb, n_blocks


def _block_mask(ki, block_k, s, sk, causal):
    """[s, block_k] validity mask for kv block ``ki``: ragged tail rows
    beyond sk are invalid; under causal q may not attend ahead. The one
    copy of the mask convention for forward AND backward."""
    q_pos = jnp.arange(s)[:, None]
    k_pos = ki * block_k + jnp.arange(block_k)[None, :]
    mask = k_pos < sk
    if causal:
        mask = jnp.logical_and(mask, q_pos >= k_pos)
    return mask


def _blockwise_reference(q, k, v, causal, sm_scale, block_k=512):
    """O(seq)-memory attention via lax.scan over kv blocks — the
    semantic twin of the pallas forward."""
    b, h, s, d = q.shape
    sk = k.shape[2]
    q32 = q.astype(jnp.float32) * sm_scale
    kb, vb, n_blocks = _block_layout(k, v, block_k)

    def body(carry, blk):
        acc, m, l = carry
        k_blk, v_blk, ki = blk
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32, k_blk)
        mask = _block_mask(ki, block_k, s, sk, causal)
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
        m_new = jnp.maximum(m, scores.max(-1))
        p = jnp.exp(scores - m_new[..., None])
        p = jnp.where(mask[None, None], p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk)
        return (acc_new, m_new, l_new), None

    acc0 = jnp.zeros((b, h, s, d), jnp.float32)
    m0 = jnp.full((b, h, s), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    (acc, m, l), _ = lax.scan(body, (acc0, m0, l0),
                              (kb, vb, jnp.arange(n_blocks)))
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=False, sm_scale=None, block_q=128,
                    block_k=128, interpret=False):
    """Blockwise exact attention; q/k/v/out are [batch, heads, seq, dim]."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    return _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                      interpret)


def _flash_bwd(q, k, v, out, g, causal, sm_scale, block_k=512):
    """The FA2-style memory-efficient backward: recompute per-block
    attention from saved (out) plus a cheap O(seq)-carry statistics
    pass, then accumulate dq and emit per-block dk/dv under lax.scan.
    Live memory is O(seq*(dim + block_k)) — LINEAR in sequence length.
    (The previous implementation took jax.vjp of the blockwise forward,
    whose scan residuals stash every block's scores: O(seq^2) — the
    static account showed its temp memory EXCEEDING dense attention at
    8k, PERF_ACCOUNTING.json r5.)"""
    b, h, s, d = q.shape
    sk = k.shape[2]
    q32 = q.astype(jnp.float32) * sm_scale
    g32 = g.astype(jnp.float32)
    kb, vb, n_blocks = _block_layout(k, v, block_k)

    # pass 1: row statistics (m, l) only — O(seq) carry, no O(s^2) stash
    def stats_body(carry, blk):
        m, l = carry
        k_blk, ki = blk
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32, k_blk)
        mask = _block_mask(ki, block_k, s, sk, causal)
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
        m_new = jnp.maximum(m, scores.max(-1))
        l = l * jnp.exp(m - m_new) + jnp.where(
            mask[None, None],
            jnp.exp(scores - m_new[..., None]), 0.0).sum(-1)
        return (m_new, l), None

    m0 = jnp.full((b, h, s), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    (m, l), _ = lax.scan(stats_body, (m0, l0),
                         (kb, jnp.arange(n_blocks)))
    l = jnp.maximum(l, 1e-30)
    # delta_i = sum_d g_i * out_i  (the softmax-jacobian row term)
    delta = jnp.sum(g32 * out.astype(jnp.float32), axis=-1)  # [b,h,s]

    # pass 2: dq accumulates in the carry; dk/dv emit per block (the
    # stacked outputs reassemble to full dk/dv — O(seq*dim) total)
    def grad_body(dq, blk):
        k_blk, v_blk, ki = blk
        mask = _block_mask(ki, block_k, s, sk, causal)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32, k_blk)
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
        p = jnp.exp(scores - m[..., None]) / l[..., None]
        p = jnp.where(mask[None, None], p, 0.0)
        dv_blk = jnp.einsum("bhqk,bhqd->bhkd", p, g32)
        dp = jnp.einsum("bhqd,bhkd->bhqk", g32, v_blk)
        ds = p * (dp - delta[..., None])
        dq = dq + sm_scale * jnp.einsum("bhqk,bhkd->bhqd", ds, k_blk)
        # q32 already carries one sm_scale factor, which is exactly
        # dk_j = sm_scale * sum_i ds_ij q_i
        dk_blk = jnp.einsum("bhqk,bhqd->bhkd", ds, q32)
        return dq, (dk_blk, dv_blk)

    dq0 = jnp.zeros((b, h, s, d), jnp.float32)
    dq, (dk_blocks, dv_blocks) = lax.scan(
        grad_body, dq0, (kb, vb, jnp.arange(n_blocks)))
    dk = dk_blocks.transpose(1, 2, 0, 3, 4).reshape(b, h,
                                                    n_blocks * block_k, d)
    dv = dv_blocks.transpose(1, 2, 0, 3, 4).reshape(b, h,
                                                    n_blocks * block_k, d)
    return (dq.astype(q.dtype), dk[:, :, :sk].astype(k.dtype),
            dv[:, :, :sk].astype(v.dtype))


def _vjp_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    out = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret)
    return out, (q, k, v, out)


def _vjp_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    q, k, v, out = res
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    return _flash_bwd(q, k, v, out, g, causal, sm_scale)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)


def mha(q, k, v, causal=False, sm_scale=None, **kw):
    """Convenience wrapper for [batch, seq, heads, dim] layouts (the model
    code's layout): transposes in/out around flash_attention."""
    out = flash_attention(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                          v.transpose(0, 2, 1, 3), causal, sm_scale, **kw)
    return out.transpose(0, 2, 1, 3)
