"""Shared attention-impl dispatch for the model families (BERT, GPT):
in-shard ring / ring over the sp mesh axis / Pallas flash kernel / dense
— one copy of the -1e30 mask convention, sm_scale, and the CPU interpret
fallback. Lives in ops/ (neutral layer) so model modules don't import
each other for infrastructure."""

import jax
import jax.numpy as jnp


def attention_context(q, k, v, *, causal, mask, dtype, ring_axis=None,
                      use_ring=False, use_flash=False, mesh=None):
    """The shared attention-impl dispatch for BERT and GPT: in-shard ring
    (already inside a shard_map over ``ring_axis``) / ring over the sp
    mesh axis / Pallas flash kernel / dense — one copy of the -1e30 mask
    convention, sm_scale, and the CPU interpret fallback."""
    head_dim = q.shape[-1]
    scale = head_dim ** -0.5
    if ring_axis:
        from edl_tpu.parallel.ring_attention import _ring_attention_shard
        return _ring_attention_shard(q, k, v, axis_name=ring_axis,
                                     causal=causal, sm_scale=scale)
    if use_ring:
        from edl_tpu.parallel.ring_attention import ring_attention
        return ring_attention(q, k, v, mesh, causal=causal)
    if use_flash:
        if mask is not None:
            raise ValueError(
                "use_flash does not support attention_mask yet; drop "
                "the mask (fixed-length batches) or use the dense path")
        from edl_tpu.ops.flash_attention import mha
        return mha(q, k, v, causal=causal,
                   interpret=jax.default_backend() != "tpu")
    scores = jnp.einsum("bqhd,bkhd->bhqk",
                        (q * scale).astype(jnp.float32),
                        k.astype(jnp.float32))
    if causal:
        s = q.shape[1]
        tri = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(tri[None, None], scores, -1e30)
    if mask is not None:
        scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs,
                      v.astype(jnp.float32)).astype(dtype)
