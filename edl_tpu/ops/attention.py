"""Shared attention-impl dispatch for the model families (BERT, GPT):
in-shard ring / ring over the sp mesh axis / Pallas flash kernel / dense
— one copy of the -1e30 mask convention, sm_scale, and the CPU interpret
fallback. Lives in ops/ (neutral layer) so model modules don't import
each other for infrastructure.

``use_flash=None`` (the default) auto-dispatches: on TPU, shapes the
Pallas kernel handles exactly take the flash path; everything else stays
dense. Explicit ``True``/``False`` still force a path, so callers that
pinned a choice before the auto default keep their behavior.
"""

import os

import jax
import jax.numpy as jnp

# Pallas kernel defaults (ops/flash_attention.py): blocks are 128x128
# with block_q clamped to seq. Lane tiling wants head_dim % 8 == 0.
_FLASH_BLOCK = 128
_FLASH_HEAD_MULT = 8


def flash_dispatch_reason(seq_len, head_dim, *, mask=None, platform=None,
                          seq_kv=None, offset=None):
    """Why auto-dispatch would (not) pick flash for this shape.

    Returns ``None`` when the flash path is legal and profitable, else a
    human-readable reason string (the dense path is taken). Pure shape
    math — safe to call from tests and benches without tracing.

    ``seq_kv`` (default: ``seq_len``) is the K/V sequence length.
    Decode-shaped queries — seq_q=1 (or any seq_q != seq_kv) against a
    cached K/V — are NEVER flash-legal here: the Pallas kernel derives
    its causal block mask from the query position, so with q shorter
    than kv it would mask against the wrong diagonal and read an
    under-tiled q block. The decode path in models/gpt.py owns its own
    masked dense attention against the cache; auto-dispatch must not
    steal it mid-decode.

    ``offset`` (chunked/suffix prefill: the chunk's KV write offset)
    marks a CHUNK-SHAPED query: row i's causal frontier sits at
    ``offset + i``, not ``i``, and the legal key range spans the whole
    cached row. The flash kernel anchors its diagonal at position 0, so
    any non-None offset is dense-only for the same reason decode is —
    the offset-prefill path in models/gpt.py owns its masked dense
    attention against the cache.
    """
    if mask is not None:
        return "attention_mask set (flash kernel has no mask support)"
    if offset is not None:
        return ("chunk-shaped query (prefill_offset set): flash causal "
                "masking anchors the diagonal at position 0, not at the "
                "chunk offset")
    if seq_kv is not None and seq_kv != seq_len:
        return ("decode-shaped query (seq_q %d != seq_kv %d): flash "
                "causal masking assumes square q/kv" % (seq_len, seq_kv))
    platform = platform or jax.default_backend()
    if os.environ.get("EDL_TPU_FLASH_AUTO", "") == "0":
        return "disabled via EDL_TPU_FLASH_AUTO=0"
    if platform not in ("tpu", "axon"):
        return "platform %r (interpret-mode flash is slower than dense)" \
            % platform
    if head_dim % _FLASH_HEAD_MULT != 0:
        return "head_dim %d not a multiple of %d" % (head_dim,
                                                     _FLASH_HEAD_MULT)
    if seq_len > _FLASH_BLOCK and seq_len % _FLASH_BLOCK != 0:
        # ragged q blocks are not masked by the kernel; ragged kv is.
        # Stay conservative: only whole-block (or single-block) seqs.
        return "seq_len %d not a multiple of block %d" % (seq_len,
                                                          _FLASH_BLOCK)
    return None


def attention_context(q, k, v, *, causal, mask, dtype, ring_axis=None,
                      use_ring=False, use_flash=None, mesh=None):
    """The shared attention-impl dispatch for BERT and GPT: in-shard ring
    (already inside a shard_map over ``ring_axis``) / ring over the sp
    mesh axis / Pallas flash kernel / dense — one copy of the -1e30 mask
    convention, sm_scale, and the CPU interpret fallback.

    ``use_flash``: ``True`` forces the Pallas flash kernel, ``False``
    forces dense, ``None`` (default) auto-dispatches by
    :func:`flash_dispatch_reason` (flash on TPU for kernel-legal shapes,
    dense otherwise). The old default was ``False``; auto is numerics-
    gated against dense in tier-1 (tests/test_attention_dispatch.py).
    """
    head_dim = q.shape[-1]
    scale = head_dim ** -0.5
    if ring_axis:
        from edl_tpu.parallel.ring_attention import _ring_attention_shard
        return _ring_attention_shard(q, k, v, axis_name=ring_axis,
                                     causal=causal, sm_scale=scale)
    if use_ring:
        from edl_tpu.parallel.ring_attention import ring_attention
        return ring_attention(q, k, v, mesh, causal=causal)
    if use_flash is None:
        use_flash = flash_dispatch_reason(q.shape[1], head_dim,
                                          mask=mask,
                                          seq_kv=k.shape[1]) is None
    if use_flash:
        if mask is not None:
            raise ValueError(
                "use_flash does not support attention_mask yet; drop "
                "the mask (fixed-length batches) or use the dense path")
        if q.shape[1] != k.shape[1]:
            raise ValueError(
                "use_flash=True with decode-shaped q (seq_q %d != "
                "seq_kv %d): the flash kernel's causal mask assumes "
                "square q/kv; use the cached dense decode path"
                % (q.shape[1], k.shape[1]))
        from edl_tpu.ops.flash_attention import mha
        return mha(q, k, v, causal=causal,
                   interpret=jax.default_backend() != "tpu")
    scores = jnp.einsum("bqhd,bkhd->bhqk",
                        (q * scale).astype(jnp.float32),
                        k.astype(jnp.float32))
    if causal:
        s = q.shape[1]
        tri = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(tri[None, None], scores, -1e30)
    if mask is not None:
        scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs,
                      v.astype(jnp.float32)).astype(dtype)
