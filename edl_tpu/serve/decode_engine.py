"""Continuous-batching autoregressive decode engine for the serving plane.

Decode-step-level scheduling (Orca, Yu et al. OSDI'22) on top of the
slot KV cache (:mod:`edl_tpu.serve.kv_cache`): instead of batching at
request granularity — where every sequence in a batch waits for the
longest one — the device loop makes an admission decision EVERY DECODE
STEP. Each iteration it

1. admits newly arrived sequences into free slots (one prefill forward
   per arrival fills the slot's cache rows ``[0:prompt_len)`` via the
   path ``models/gpt.py`` exposes, and yields the first token),
2. runs ONE fused decode step over all occupied slots — a fixed-shape
   jit over ``[slots]`` tokens and ``[slots]`` per-row positions
   (vector ``decode_index``), so slot membership churn never
   recompiles; free rows ride along masked-out on the host side,
3. retires finished sequences (slot back to the free list, future
   resolved) and evicts ones past their deadline,

and streams tokens back over the pipelined RPC plane (``lm_submit`` /
``lm_poll`` on :class:`~edl_tpu.distill.teacher_server.TeacherServer`,
or blocking ``lm_generate``).

Generation is greedy (argmax) — deliberately: tier-1 gates the engine
on TOKEN-IDENTICAL output vs the unbatched ``models.gpt.generate`` for
the same prompts, which pins down the whole slot machinery (prefill
padding, scatter, per-row masks, cache reuse without zeroing).

Two serving fast paths ride the same machinery:

- **Shared-prefix KV reuse** (SGLang RadixAttention): retired rows are
  RETAINED as cached prefixes in a host-side token trie
  (:class:`~edl_tpu.serve.kv_cache.PrefixCache`); a prompt sharing a
  stored prefix copies the donor row on-device and prefills only the
  suffix. Causality makes the reuse exact — K/V at position i depends
  only on tokens <= i — and the suffix path is token-parity-gated vs
  cold prefill. ``EDL_TPU_PREFIX_CACHE=0`` (or ``prefix_cache=False``)
  kills the path byte-identically.
- **Chunked prefill** (Sarathi-Serve, OSDI'24): with
  ``prefill_chunk=C`` (or ``EDL_TPU_PREFILL_CHUNK``), prefills split
  into fixed-width chunks and AT MOST ONE chunk rides each fused decode
  step in the SAME dispatch, so a long prompt costs every resident
  sequence one slightly-heavier step per chunk instead of a full
  prefill-sized ITL stall. Chunk calls write K/V at the chunk's offset
  (``models/gpt.py prefill_offset``) and the final chunk yields the
  first token.

Idle rows (free, cached, or mid-chunked-prefill) ride fused steps with
a junk write pointed at position ``max_len - 1`` — a position every
future tenant overwrites before attending — so step traffic can never
corrupt a cached prefix or a half-prefilled row.

Faults: the ``serve.decode.step`` point fires before every fused step;
a faulted step fails ONLY the sequences active in it (typed
:class:`~edl_tpu.utils.errors.DecodeStepError`, slots freed) and the
loop keeps serving — chaos-drilled in tests/test_decode_engine.py.
``serve.decode.prefix_lookup`` fires before each trie lookup; a fault
there falls back LOSSLESSLY to cold prefill (never a wrong token).

Quantization: pass ``params`` straight from
:func:`edl_tpu.ops.quant.quantize_tree` — the jitted prefill/step call
:func:`~edl_tpu.ops.quant.dequantize_tree` under trace, so int8/bf16
weights are what cross the HBM boundary (identity on f32 trees).
"""

import collections
import itertools
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.ops.quant import dequantize_tree
from edl_tpu.robustness import faults
from edl_tpu.serve.admission import DecodeAdmission
from edl_tpu.serve.kv_cache import PrefixCache, SlotKvCache
from edl_tpu.utils import errors

_MS_BUCKETS = (0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500)

_SLOTS_OCCUPIED = obs_metrics.gauge(
    "edl_decode_slots_occupied", "KV-cache slots holding a live sequence")
_SLOTS_TOTAL = obs_metrics.gauge(
    "edl_decode_slots_total", "preallocated KV-cache slots")
_PREFILL_QUEUE = obs_metrics.gauge(
    "edl_decode_prefill_queue", "admitted sequences waiting for a slot "
    "+ prefill")
_TTFT = obs_metrics.histogram(
    "edl_decode_ttft_ms", "submit -> first token (prefill phase)",
    buckets=_MS_BUCKETS)
_ITL = obs_metrics.histogram(
    "edl_decode_itl_ms", "inter-token latency (one fused decode step)",
    buckets=_MS_BUCKETS)
_TOKENS = obs_metrics.counter(
    "edl_decode_tokens_total", "tokens generated across all sequences")
_EVICTED = obs_metrics.counter(
    "edl_decode_evicted_sequences_total", "sequences evicted before "
    "completion (deadline or faulted step)")
_STEPS = obs_metrics.counter(
    "edl_decode_steps_total", "fused decode steps executed")


class _Seq(object):
    __slots__ = ("id", "prompt", "max_new", "deadline_ms", "submitted_at",
                 "slot", "pos", "tok", "tokens", "ttft_ms", "itl_ms",
                 "done", "error", "event", "next_off", "reuse_tokens",
                 "suffix_est", "last_emit")

    def __init__(self, seq_id, prompt, max_new, deadline_ms, submitted_at):
        self.id = seq_id
        self.prompt = prompt
        self.max_new = max_new
        self.deadline_ms = deadline_ms
        self.submitted_at = submitted_at
        self.slot = None
        self.pos = None      # position the NEXT fed token occupies
        self.tok = None      # the next token to feed
        self.tokens = []     # generated tokens (streamed via poll)
        self.ttft_ms = None
        self.itl_ms = []
        self.done = False
        self.error = None
        self.event = threading.Event()
        self.next_off = None            # prefill frontier (chunked path)
        self.reuse_tokens = 0           # prefix tokens reused from cache
        self.suffix_est = len(prompt)   # projected prefill work at submit
        self.last_emit = None           # clock stamp of the last token


class SeqHandle(object):
    """Client-side handle: stream via :meth:`tokens_from`, or block on
    :meth:`result`."""

    def __init__(self, engine, seq):
        self._engine = engine
        self._seq = seq

    @property
    def seq_id(self):
        return self._seq.id

    def tokens_from(self, start):
        """(new_tokens, done) — tokens generated since index ``start``.
        Raises the sequence's typed error once it has failed."""
        return self._engine._poll(self._seq, start)

    def result(self, timeout=None):
        """Block until the sequence finishes; returns a report dict
        (tokens, ttft_ms, itl p50/p99) or raises its typed error."""
        if not self._seq.event.wait(timeout):
            raise errors.TimeoutError_(
                "sequence %d still decoding after %ss"
                % (self._seq.id, timeout))
        return self._engine._report(self._seq)


class DecodeEngine(object):
    """One device loop + slot cache + per-phase admission, serving a
    single causal-LM ``model`` with KV-cache decode (``models/gpt.py``).

    ``params`` may be plain f32 or the output of
    :func:`~edl_tpu.ops.quant.quantize_tree`. ``slots`` bounds resident
    sequences; ``admission`` is a :class:`DecodeAdmission` (``None`` =
    defaults, ``False`` = admit everything except when draining).

    ``prefix_cache``: ``None`` = on unless ``EDL_TPU_PREFIX_CACHE=0``,
    ``False`` = off (cold prefill only, byte-identical to the pre-reuse
    engine), ``True`` = on regardless of the env knob, or a
    :class:`~edl_tpu.serve.kv_cache.PrefixCache` to share or pre-seed
    one. ``prefill_chunk``: chunk width in tokens for
    Sarathi-style chunked prefill (``None`` = ``EDL_TPU_PREFILL_CHUNK``,
    0/unset = monolithic prefill)."""

    def __init__(self, model, params, slots=8, admission=None,
                 clock=time.monotonic, prefix_cache=None,
                 prefill_chunk=None):
        self.model = model
        self.params = params
        self.slots = int(slots)
        self.max_len = int(model.max_len)
        self._clock = clock
        if admission is None:
            admission = DecodeAdmission(clock=clock)
        self.admission = admission or DecodeAdmission(
            max_waiting=1 << 30, clock=clock)
        if prefix_cache is None:
            env = os.environ.get("EDL_TPU_PREFIX_CACHE", "1").lower()
            prefix_cache = (PrefixCache()
                            if env not in ("0", "off", "false") else None)
        elif prefix_cache is False:
            prefix_cache = None
        elif prefix_cache is True:  # force on, ignoring the env knob
            prefix_cache = PrefixCache()
        self.prefix = prefix_cache
        if prefill_chunk is None:
            prefill_chunk = int(
                os.environ.get("EDL_TPU_PREFILL_CHUNK", "0") or 0)
        self.prefill_chunk = min(max(0, int(prefill_chunk)), self.max_len)
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._waiting = collections.deque()
        self._prefill_q = collections.deque()  # chunked: slot held, prefill
        self._seqs = {}      # id -> _Seq (live + recently finished)
        self._by_slot = {}   # slot -> _Seq (active only)
        self._ids = itertools.count(1)
        self._stop = False
        self._thread = None
        self._sequences_done = 0
        self._evicted = 0
        self._tokens_total = 0
        self._steps_total = 0
        self._prefilled_tokens = 0  # tokens cold-prefilled (not reused)
        self._step_traces = 0     # fixed-shape discipline: must stay 1
        self._prefill_traces = 0  # bounded by len(prefill buckets)
        self._chunk_traces = 0    # bounded: 1 width under chunking,
        #                           power-of-two buckets for suffixes

        self.kv = SlotKvCache(
            lambda n: _init_cache(model, params, n), self.slots)
        _SLOTS_TOTAL.set(self.slots)
        # the cache argument is DONATED: every impl threads the full
        # slot cache in and out, and without aliasing each dispatch
        # round-trips a copy of the whole KV arena — at serving sizes
        # that copy costs more than the step itself. Call sites always
        # reassign self.kv.cache from the return value, so the donated
        # (invalidated) input is never touched again.
        self._jit_prefill = jax.jit(self._prefill_impl, donate_argnums=1)
        self._jit_step = jax.jit(self._step_impl, donate_argnums=1)
        self._jit_reuse = jax.jit(self._reuse_impl, donate_argnums=0)
        self._jit_chunk = jax.jit(self._chunk_impl, donate_argnums=1)
        self._jit_fused = jax.jit(self._fused_impl, donate_argnums=1)

    # -- jitted device functions -------------------------------------------

    def _prefill_impl(self, qparams, cache, ids, prompt_len, slot):
        """Fills slot ``slot`` of ``cache`` from a padded prompt
        ``ids [1, P]`` and returns (cache', last-prompt-position logits).
        The prefill cache row is FULL-length (prompt K/V then zeros), so
        the scatter erases any previous tenant of the slot; junk K/V at
        padded positions ``[prompt_len, P)`` is overwritten by the decode
        step at each position before it is ever attended."""
        self._prefill_traces += 1  # python side effect: counts traces
        params = dequantize_tree(qparams)
        row = _init_cache(self.model, None, 1)
        logits, muts = self.model.apply(
            {"params": params, "cache": row}, ids, prefill=True,
            mutable=["cache"])
        starts = (slot, 0, 0, 0)
        cache = jax.tree_util.tree_map(
            lambda full, r: jax.lax.dynamic_update_slice(full, r, starts),
            cache, muts["cache"])
        return cache, logits[0, prompt_len - 1]

    def _step_impl(self, qparams, cache, toks, pos):
        """ONE fused decode step over every slot: fixed shapes
        ``toks [slots]`` / ``pos [slots]`` whatever subset is live (free
        rows carry tok=0 at pos=0 — their junk write lands in a row the
        next prefill fully overwrites). Returns (cache', logits
        [slots, vocab])."""
        self._step_traces += 1  # python side effect: counts traces
        params = dequantize_tree(qparams)
        logits, muts = self.model.apply(
            {"params": params, "cache": cache}, toks[:, None],
            decode=True, decode_index=pos, mutable=["cache"])
        return muts["cache"], logits[:, 0]

    def _reuse_impl(self, cache, src, dst):
        """Copy slot row ``src`` (a cached prefix donor) onto ``dst``.
        The WHOLE row is copied — positions beyond the reused depth hold
        junk, but the suffix prefill / decode writes overwrite every
        position before it is attended (the no-zeroing invariant)."""
        def cp(full):
            row = jax.lax.dynamic_slice_in_dim(full, src, 1, axis=0)
            return jax.lax.dynamic_update_slice_in_dim(full, row, dst,
                                                       axis=0)
        return jax.tree_util.tree_map(cp, cache)

    def _apply_chunk(self, params, cache, ids, offset, slot):
        """Shared chunk body: extract slot ``slot``'s row, run one
        offset-prefill chunk over it (K/V written at ``offset``, rows
        attend the already-written prefix), scatter it back. Returns
        (cache', chunk logits [1, W, vocab])."""
        row = jax.tree_util.tree_map(
            lambda full: jax.lax.dynamic_slice(
                full, (slot, 0, 0, 0), (1,) + full.shape[1:]), cache)
        logits, muts = self.model.apply(
            {"params": params, "cache": row}, ids, prefill=True,
            prefill_offset=offset, mutable=["cache"])
        cache = jax.tree_util.tree_map(
            lambda full, r: jax.lax.dynamic_update_slice(
                full, r, (slot, 0, 0, 0)), cache, muts["cache"])
        return cache, logits

    def _chunk_impl(self, qparams, cache, ids, offset, last, slot):
        """One solo prefill chunk (no live decode rows to fuse with):
        suffix prefill after a prefix hit, or a chunked-prefill quantum
        on an otherwise idle engine. ``last`` indexes the final valid
        prompt position in the window (its logits yield the first
        token when this is the final chunk)."""
        self._chunk_traces += 1  # python side effect: counts traces
        params = dequantize_tree(qparams)
        cache, logits = self._apply_chunk(params, cache, ids, offset, slot)
        return cache, logits[0, last]

    def _fused_impl(self, qparams, cache, ids, offset, last, slot,
                    toks, pos):
        """Sarathi-style fused quantum: ONE dispatch prefills one chunk
        into slot ``slot`` AND advances every live decode row. The cache
        threads chunk-then-step, and the step only writes real K/V for
        live rows (the chunking row rides the decode side as junk at
        max_len-1), so the chunk's window survives the step intact."""
        self._chunk_traces += 1  # python side effect: counts traces
        params = dequantize_tree(qparams)
        cache, clogits = self._apply_chunk(params, cache, ids, offset,
                                           slot)
        logits, muts = self.model.apply(
            {"params": params, "cache": cache}, toks[:, None],
            decode=True, decode_index=pos, mutable=["cache"])
        return muts["cache"], logits[:, 0], clogits[0, last]

    # -- client surface ----------------------------------------------------

    def submit(self, prompt_ids, max_new_tokens, deadline_ms=None):
        """Admit one sequence (or raise ``OverloadedError``); returns a
        :class:`SeqHandle`. ``prompt_ids`` is a 1-D int sequence."""
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not prompt:
            raise errors.FeedSpecError("empty prompt")
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise errors.FeedSpecError("max_new_tokens must be >= 1")
        total = len(prompt) + max_new
        if total > self.max_len:
            raise errors.FeedSpecError(
                "prompt+new %d exceeds max_len %d" % (total, self.max_len))
        now = self._clock()
        with self._work:
            suffix_est = len(prompt)
            if self.prefix is not None:
                suffix_est -= self.prefix.peek_len(prompt)
            queued_tok = sum(s.suffix_est for s in self._waiting)
            for s in self._prefill_q:
                queued_tok += max(0, len(s.prompt) - (s.next_off or 0))
            free = self.kv.free_slots
            if self.prefix is not None:
                # cached prefix rows are reclaimable on demand (LRU
                # evict), so they count as capacity, not occupancy
                free += self.kv.cached_rows
            self.admission.admit(
                free_slots=free, waiting=len(self._waiting),
                occupied=self.kv.occupied, slots=self.slots,
                suffix_tokens=suffix_est,
                queued_prefill_tokens=queued_tok)
            seq = _Seq(next(self._ids), prompt, max_new, deadline_ms, now)
            seq.suffix_est = suffix_est
            self._seqs[seq.id] = seq
            self._waiting.append(seq)
            _PREFILL_QUEUE.set(len(self._waiting))
            self._work.notify()
        return SeqHandle(self, seq)

    def generate(self, prompt_ids, max_new_tokens, deadline_ms=None,
                 timeout=None):
        """Blocking submit: the full report dict when the sequence
        finishes (tokens include the prompt, matching
        ``models.gpt.generate``)."""
        return self.submit(prompt_ids, max_new_tokens,
                           deadline_ms=deadline_ms).result(timeout)

    def handle(self, seq_id):
        with self._lock:
            seq = self._seqs.get(int(seq_id))
        if seq is None:
            raise errors.NotFoundError("unknown sequence %s" % seq_id)
        return SeqHandle(self, seq)

    def _poll(self, seq, start):
        with self._lock:
            if seq.error is not None:
                raise seq.error
            return list(seq.tokens[int(start):]), seq.done

    def _report(self, seq):
        with self._lock:
            if seq.error is not None:
                raise seq.error
            itl = sorted(seq.itl_ms)
            return {
                "tokens": seq.prompt + list(seq.tokens),
                "generated": list(seq.tokens),
                "ttft_ms": seq.ttft_ms,
                "itl_ms": list(seq.itl_ms),
                "itl_p50_ms": _pct(itl, 0.50),
                "itl_p99_ms": _pct(itl, 0.99),
            }

    # -- lifecycle ---------------------------------------------------------

    @property
    def running(self):
        return self._thread is not None

    def start(self):
        if self._thread is not None:
            raise RuntimeError("engine already started")
        self._thread = threading.Thread(target=self._loop,
                                        name="decode-engine", daemon=True)
        self._thread.start()
        return self

    def drain(self, deadline_s=30.0):
        """Stop admitting, finish every in-flight sequence (waiting AND
        active), then return True; False if ``deadline_s`` elapsed with
        work still live. Zero stranded: nothing is dropped — waiting
        sequences still get slots as they free up. (The wait rides the
        engine condition var — every retire/evict notifies — not a
        poll.)"""
        self.admission.set_draining(True)
        deadline = self._clock() + deadline_s
        with self._work:
            self._work.notify_all()
            while self._waiting or self._by_slot or self._prefill_q:
                remaining = deadline - self._clock()
                if remaining <= 0:
                    return False
                self._work.wait(timeout=min(0.05, remaining))
            return True

    def stop(self):
        """Stop the device loop. Any sequence still live is resolved
        with a typed ``StopError`` so no client blocks forever — call
        :meth:`drain` first for a zero-stranded shutdown."""
        with self._work:
            self._stop = True
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        with self._lock:
            leftovers = (list(self._waiting) + list(self._prefill_q)
                         + list(self._by_slot.values()))
            self._waiting.clear()
            self._prefill_q.clear()
            for seq in leftovers:
                if seq.slot is not None:
                    self._by_slot.pop(seq.slot, None)
                    if self.prefix is not None:
                        self.prefix.forget(seq.slot)
                    self.kv.free(seq.slot)
                    seq.slot = None
                self._resolve_locked(seq, error=errors.StopError(
                    "engine stopped with sequence %d live" % seq.id))

    # -- the device loop ---------------------------------------------------

    def _loop(self):
        while True:
            with self._work:
                if self._stop:
                    return
                if (not self._by_slot and not self._waiting
                        and not self._prefill_q):
                    self._work.wait(timeout=0.05)
                    if self._stop:
                        return
            self._admit_arrivals()
            self._service()

    def _service(self):
        """One scheduling quantum: at most ONE prefill chunk, fused
        with the decode step when rows are live (the Sarathi budget —
        residents pay one bounded chunk per step, never a monolithic
        prefill stall)."""
        with self._lock:
            chunk_seq = self._prefill_q[0] if self._prefill_q else None
        if chunk_seq is not None:
            now = self._clock()
            if (chunk_seq.deadline_ms is not None
                    and (now - chunk_seq.submitted_at) * 1000.0
                    > chunk_seq.deadline_ms):
                # budget burned mid-prefill: drop before device work
                with self._lock:
                    if self._prefill_q and self._prefill_q[0] is chunk_seq:
                        self._prefill_q.popleft()
                    self._evict_locked(chunk_seq)
                _SLOTS_OCCUPIED.set(self.kv.occupied)
                return
            self._run_chunk(chunk_seq)
        elif self._by_slot:
            self._run_step()

    def _admit_arrivals(self):
        while True:
            with self._lock:
                if not self._waiting:
                    return
                seq = self._waiting[0]
                if (seq.deadline_ms is not None
                        and (self._clock() - seq.submitted_at) * 1000.0
                        > seq.deadline_ms):
                    # dead on arrival: budget burned in the queue
                    self._waiting.popleft()
                    _PREFILL_QUEUE.set(len(self._waiting))
                    self._resolve_locked(
                        seq, error=self.admission.shed_evicted())
                    self._evicted += 1
                    _EVICTED.inc()
                    continue
                slot = self.kv.alloc()
                if slot is None and self.prefix is not None:
                    # allocator dry but idle cached rows exist: evict
                    # the LRU stored prefix and reclaim its row — reuse
                    # never reduces decode capacity
                    victim = self.prefix.evict_lru(self.kv.cached())
                    if victim is not None:
                        self.kv.release(victim)
                        slot = self.kv.alloc()
                if slot is None:
                    return
                self._waiting.popleft()
                _PREFILL_QUEUE.set(len(self._waiting))
            self._start_prefill(seq, slot)

    def _start_prefill(self, seq, slot):
        """Route one admitted sequence onto its prefill path: prefix
        lookup + row copy first (chaos point ``serve.decode.
        prefix_lookup``; any fault falls back losslessly to cold
        prefill), then either a monolithic/suffix prefill now, or —
        under chunking — park the sequence on the chunk queue and let
        its prefill ride the fused steps."""
        src, reused = None, 0
        if self.prefix is not None:
            try:
                if faults.PLANE is not None:
                    faults.PLANE.fire("serve.decode.prefix_lookup",
                                      seq=seq.id,
                                      prompt_len=len(seq.prompt))
                src, reused = self.prefix.lookup(seq.prompt)
            except Exception:  # noqa: BLE001 — lossless cold fallback
                self.prefix.note_miss()
                src, reused = None, 0
        if src is not None and reused > 0:
            try:
                self.kv.cache = self._jit_reuse(
                    self.kv.cache, jnp.asarray(src, jnp.int32),
                    jnp.asarray(slot, jnp.int32))
            except Exception:  # noqa: BLE001 — lossless cold fallback
                reused = 0
        seq.reuse_tokens = reused
        seq.next_off = reused
        if self.prefill_chunk:
            with self._work:
                seq.slot = slot
                self._prefill_q.append(seq)
                self._work.notify_all()
        elif reused > 0:
            self._prefill_suffix(seq, slot)
        else:
            self._prefill(seq, slot)

    def _prefill(self, seq, slot):
        plen = len(seq.prompt)
        bucket = _prefill_bucket(plen, self.max_len)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :plen] = seq.prompt
        t0 = time.monotonic()
        try:
            cache, last = self._jit_prefill(
                self.params, self.kv.cache, jnp.asarray(ids),
                jnp.asarray(plen, jnp.int32), jnp.asarray(slot, jnp.int32))
            first = int(np.argmax(np.asarray(last)))
        except Exception as exc:  # noqa: BLE001 — fail one seq, not the loop
            self._drop_slot(slot)
            with self._lock:
                self._resolve_locked(seq, error=errors.DecodeStepError(
                    "prefill failed: %s" % exc))
                self._evicted += 1
            _EVICTED.inc()
            return
        self.kv.cache = cache
        # TTFT = submit -> first token; one interval feeds the histogram,
        # the admission EWMA and the per-seq report (allowlisted pair
        # site in tools/check_no_ad_hoc_instrumentation.py)
        prefill_ms = (time.monotonic() - t0) * 1000.0
        self.admission.observe_prefill_ms(prefill_ms, tokens=plen)
        with self._lock:
            self._prefilled_tokens += plen
        self._finish_prefill(seq, slot, first)

    def _prefill_suffix(self, seq, slot):
        """Prefill ONLY the suffix after a prefix hit: one offset-chunk
        call over a power-of-two window ending at the prompt's tail.
        The window may slide back over the reused span (when the padded
        width overruns ``max_len``) — overlap recomputes bit-identical
        K/V, so correctness never depends on the slide."""
        plen = len(seq.prompt)
        width = _prefill_bucket(plen - seq.next_off, self.max_len)
        start = min(seq.next_off, self.max_len - width)
        span = min(width, plen - start)
        ids = np.zeros((1, width), np.int32)
        ids[0, :span] = seq.prompt[start:start + span]
        t0 = time.monotonic()
        try:
            cache, last = self._jit_chunk(
                self.params, self.kv.cache, jnp.asarray(ids),
                jnp.asarray(start, jnp.int32),
                jnp.asarray(plen - 1 - start, jnp.int32),
                jnp.asarray(slot, jnp.int32))
            first = int(np.argmax(np.asarray(last)))
        except Exception as exc:  # noqa: BLE001 — fail one seq, not the loop
            self._drop_slot(slot)
            with self._lock:
                self._resolve_locked(seq, error=errors.DecodeStepError(
                    "suffix prefill failed: %s" % exc))
                self._evicted += 1
            _EVICTED.inc()
            return
        self.kv.cache = cache
        # same stopwatch-pair contract as _prefill (allowlisted site)
        suffix_ms = (time.monotonic() - t0) * 1000.0
        suffix_tokens = plen - seq.next_off
        self.admission.observe_prefill_ms(suffix_ms, tokens=suffix_tokens)
        with self._lock:
            self._prefilled_tokens += suffix_tokens
        self._finish_prefill(seq, slot, first)

    def _finish_prefill(self, seq, slot, first):
        """Common prefill completion: store the prompt's path in the
        trie (the row is a valid donor from here on — decode only
        writes positions >= prompt_len) and activate the sequence."""
        if self.prefix is not None:
            self.prefix.insert(seq.prompt, slot)
        with self._lock:
            seq.slot = slot
            seq.pos = len(seq.prompt)
            seq.tok = first
            seq.tokens.append(first)
            now = self._clock()
            seq.ttft_ms = (now - seq.submitted_at) * 1000.0
            seq.last_emit = now
            self._tokens_total += 1
            self._by_slot[slot] = seq
            ttft = seq.ttft_ms
            if len(seq.tokens) >= seq.max_new:
                self._retire_locked(seq)
        _TTFT.observe(ttft)
        _TOKENS.inc()
        _SLOTS_OCCUPIED.set(self.kv.occupied)

    def _plan_chunk(self, seq):
        """Host-side plan for the next chunk of ``seq``'s prefill:
        (padded ids [1, C], window start, last-valid index, tokens of
        NEW progress, final?). The window slides back when it would
        overrun ``max_len`` (or, on the final chunk, past the prompt
        tail) — overlapped positions recompute identical K/V."""
        plen = len(seq.prompt)
        width = self.prefill_chunk
        start = min(seq.next_off, max(0, self.max_len - width))
        span = min(width, plen - start)
        ids = np.zeros((1, width), np.int32)
        ids[0, :span] = seq.prompt[start:start + span]
        end = start + span
        progress = end - seq.next_off
        final = end >= plen
        last = (plen - 1 - start) if final else (span - 1)
        return ids, start, last, progress, final

    def _run_chunk(self, seq):
        """One chunked-prefill quantum: fuse the chunk with the decode
        step when rows are live (ONE dispatch — residents' ITL pays a
        bounded chunk, not a monolithic prefill), solo otherwise."""
        ids, start, last, progress, final = self._plan_chunk(seq)
        toks = np.zeros(self.slots, np.int32)
        # junk writes for non-live rows land at max_len-1: a position
        # every future tenant overwrites before attending, so steps
        # never corrupt cached prefixes or half-prefilled rows
        pos = np.full(self.slots, self.max_len - 1, np.int32)
        with self._lock:
            active = dict(self._by_slot)
            for slot, s in active.items():
                toks[slot] = s.tok
                pos[slot] = s.pos
        t0 = time.monotonic()
        try:
            if active:
                if faults.PLANE is not None:
                    faults.PLANE.fire("serve.decode.step",
                                      active=len(active),
                                      step=self._steps_total)
                cache, logits, clog = self._jit_fused(
                    self.params, self.kv.cache, jnp.asarray(ids),
                    jnp.asarray(start, jnp.int32),
                    jnp.asarray(last, jnp.int32),
                    jnp.asarray(seq.slot, jnp.int32),
                    jnp.asarray(toks), jnp.asarray(pos))
                logits = np.asarray(logits)
            else:
                cache, clog = self._jit_chunk(
                    self.params, self.kv.cache, jnp.asarray(ids),
                    jnp.asarray(start, jnp.int32),
                    jnp.asarray(last, jnp.int32),
                    jnp.asarray(seq.slot, jnp.int32))
                logits = None
        except Exception as exc:  # noqa: BLE001 — fail the quantum's
            with self._lock:      # seqs, never the loop
                if self._prefill_q and self._prefill_q[0] is seq:
                    self._prefill_q.popleft()
                self._evict_locked(seq, error=errors.DecodeStepError(
                    "prefill chunk faulted for seq %d: %s"
                    % (seq.id, exc)))
            self._fail_step(active, exc)
            return
        self.kv.cache = cache
        quantum_ms = (time.monotonic() - t0) * 1000.0
        # the chunk's EWMA charge includes the fused step's share — a
        # conservative (early-shedding) per-token estimate
        self.admission.observe_prefill_ms(quantum_ms,
                                          tokens=max(1, progress))
        with self._lock:
            self._prefilled_tokens += progress
            seq.next_off += progress
        if active:
            self._finish_step(active, logits, quantum_ms)
        if final:
            with self._lock:
                if self._prefill_q and self._prefill_q[0] is seq:
                    self._prefill_q.popleft()
            self._finish_prefill(seq, seq.slot,
                                 int(np.argmax(np.asarray(clog))))

    def _run_step(self):
        toks = np.zeros(self.slots, np.int32)
        # junk writes for non-live rows land at max_len-1 (see
        # _run_chunk) — never position 0, which a cached prefix row's
        # donor span may need intact
        pos = np.full(self.slots, self.max_len - 1, np.int32)
        with self._lock:
            active = dict(self._by_slot)
            for slot, seq in active.items():
                toks[slot] = seq.tok
                pos[slot] = seq.pos
        t0 = time.monotonic()
        try:
            if faults.PLANE is not None:
                faults.PLANE.fire("serve.decode.step",
                                  active=len(active),
                                  step=self._steps_total)
            cache, logits = self._jit_step(
                self.params, self.kv.cache, jnp.asarray(toks),
                jnp.asarray(pos))
            logits = np.asarray(logits)
        except Exception as exc:  # noqa: BLE001 — fail the step's seqs,
            self._fail_step(active, exc)  # never the loop
            return
        self.kv.cache = cache
        step_ms = (time.monotonic() - t0) * 1000.0
        self._finish_step(active, logits, step_ms)

    def _finish_step(self, active, logits, step_ms):
        """Post-step bookkeeping shared by the pure and fused paths:
        fold the interval into the ITL plane and advance every active
        row (append token, retire/evict on completion/deadline).

        Two ITL planes on purpose: the admission EWMA and the _ITL
        histogram see ``step_ms`` (the device step cost the shed
        projection prices), while each sequence's report ``itl_ms``
        records the CLIENT-VISIBLE wall gap since its previous token —
        the gap is what a monolithic prefill stall inflates and what
        the chunked-prefill bound (tools/serve_bench.py, chunked arc)
        is gated on."""
        self.admission.observe_itl_ms(step_ms)
        _ITL.observe(step_ms)
        _STEPS.inc()
        now = self._clock()
        done_or_evicted = False
        with self._lock:
            self._steps_total += 1
            for slot, seq in active.items():
                nxt = int(np.argmax(logits[slot]))
                seq.tokens.append(nxt)
                seq.itl_ms.append((now - seq.last_emit) * 1000.0)
                seq.last_emit = now
                seq.pos += 1
                seq.tok = nxt
                self._tokens_total += 1
                _TOKENS.inc()
                if len(seq.tokens) >= seq.max_new:
                    self._retire_locked(seq)
                    done_or_evicted = True
                elif (seq.deadline_ms is not None
                        and (now - seq.submitted_at) * 1000.0
                        > seq.deadline_ms):
                    self._evict_locked(seq)
                    done_or_evicted = True
        if done_or_evicted:
            _SLOTS_OCCUPIED.set(self.kv.occupied)

    def _fail_step(self, active, exc):
        """A faulted fused step fails ONLY the sequences in it: typed
        error, slots freed, loop keeps running (never wedged)."""
        with self._lock:
            for seq in active.values():
                self._evict_locked(seq, error=errors.DecodeStepError(
                    "decode step faulted for seq %d: %s" % (seq.id, exc)))
        _SLOTS_OCCUPIED.set(self.kv.occupied)

    def _retire_locked(self, seq):
        if seq.slot is not None:
            self._by_slot.pop(seq.slot, None)
            self._release_slot_locked(seq.slot)
            seq.slot = None
        self._sequences_done += 1
        self._resolve_locked(seq)

    def _evict_locked(self, seq, error=None):
        if seq.slot is not None:
            self._by_slot.pop(seq.slot, None)
            self._release_slot_locked(seq.slot, keep_cached=False)
            seq.slot = None
        self._evicted += 1
        _EVICTED.inc()
        if error is None:
            error = self.admission.shed_evicted()
        self._resolve_locked(seq, error=error)

    def _release_slot_locked(self, slot, keep_cached=True):
        """Return a slot to the allocator — or, on the RETIRE path with
        its prompt stored in the trie, retain it as a cached prefix
        donor (decode only wrote positions >= prompt_len, so the prefix
        span is intact). Evictions always forget+free: a faulted or
        deadline-killed row is not a trustworthy donor."""
        if (keep_cached and self.prefix is not None
                and self.prefix.has(slot)):
            self.kv.retain(slot)
        else:
            if self.prefix is not None:
                self.prefix.forget(slot)
            self.kv.free(slot)

    def _drop_slot(self, slot):
        """Failure-path slot return (outside the engine lock)."""
        if self.prefix is not None:
            self.prefix.forget(slot)
        self.kv.free(slot)

    def _resolve_locked(self, seq, error=None):
        seq.error = error
        seq.done = True
        seq.event.set()
        self._work.notify_all()  # wake drain()

    # -- observability -----------------------------------------------------

    def stats(self):
        with self._lock:
            waiting = len(self._waiting)
            prefilling = len(self._prefill_q)
            active = len(self._by_slot)
            steps = self._steps_total
            prefilled = self._prefilled_tokens
        occ = self.kv.occupied
        if self.prefix is not None:
            prefix = self.prefix.stats()
            prefix["enabled"] = True
            prefix["cached_rows"] = self.kv.cached_rows
            reused = prefix["reuse_tokens"]
            prefix["reuse_frac"] = (
                reused / float(reused + prefilled)
                if (reused + prefilled) else 0.0)
        else:
            prefix = {"enabled": False}
        return {
            "decode_slots_total": self.slots,
            "decode_slots_occupied": occ,
            "decode_slot_frac": occ / float(self.slots),
            "decode_waiting": waiting,
            "decode_prefilling": prefilling,
            "decode_active": active,
            "decode_steps_total": steps,
            "decode_step_traces": self._step_traces,
            "decode_prefill_traces": self._prefill_traces,
            "decode_chunk_traces": self._chunk_traces,
            "decode_prefill_chunk": self.prefill_chunk,
            "decode_prefilled_tokens": prefilled,
            "decode_prefix": prefix,
            "decode_tokens_total": self._tokens_total,
            "decode_sequences_total": self._sequences_done,
            "decode_evicted_total": self._evicted,
            "decode_ttft_p50_ms": _TTFT.percentile(0.50),
            "decode_ttft_p99_ms": _TTFT.percentile(0.99),
            "decode_itl_p50_ms": _ITL.percentile(0.50),
            "decode_itl_p99_ms": _ITL.percentile(0.99),
            "decode_kv_bytes": self.kv.bytes(),
            "decode_admission": self.admission.stats(),
        }


def _init_cache(model, params, batch_size):
    """Zeroed cache pytree for ``batch_size`` rows (trace-safe: shapes
    from eval_shape, no params materialized — mirrors
    ``models.gpt.init_cache`` without importing the params)."""
    dummy = jnp.zeros((batch_size, 1), jnp.int32)
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), dummy, decode=True,
                           decode_index=jnp.zeros((), jnp.int32)))
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"])


def _prefill_bucket(prompt_len, max_len):
    """Pad prompts to power-of-two buckets: prefill compile count is
    O(log max_len), not O(distinct prompt lengths)."""
    b = 1
    while b < prompt_len:
        b <<= 1
    return min(b, max_len)


def _pct(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]
