"""Admission control + load shedding for the teacher serving tier.

Clipper-style layered serving: the decision whether a request may even
enter the device queue is made HERE, at the front door, so overload
turns into a fast typed :class:`~edl_tpu.utils.errors.OverloadedError`
(with a retry-after hint) instead of a timeout pile-up deep in the
batching pipeline. Four shed reasons, checked in order:

- ``draining``    — the server is decommissioning (new work must go
                    elsewhere; admitted work is still served).
- ``queue_full``  — the bounded admission queue is at ``max_queue_rows``.
- ``rate_limit``  — the token bucket (``rate`` rows/s, ``burst`` rows)
                    is empty; the hint is the bucket's refill time.
- ``slo``         — queue-wait projection: pending rows × the EWMA of
                    per-row service time exceeds ``slo_ms`` (the
                    predict-latency SLO, default the ``predict_p99``
                    threshold from ``obs/slo.py``). Early shedding —
                    the request would have missed its SLO anyway, so
                    shedding it NOW preserves goodput for the queue.

The projection needs a service-time estimate, so it never sheds before
the first completed batch — a cold server admits freely — and an IDLE
server (zero pending rows) always admits regardless of the estimate:
the EWMA only updates when admitted work completes, so shedding on an
empty queue would freeze a poisoned estimate (a first-batch jit
compile spike) into shedding forever. Per-request
deadlines ride along as ``deadline_ms``; the device loop calls
:meth:`expired` and sheds dead-on-arrival items (their budget elapsed
while queued) rather than burning device time on them.

The ``serve.admit`` fault point fires before the decision, so chaos
drills can delay or fail admission deterministically. Health/stats
RPCs never pass through here — admission guards ``predict`` only, and
the RPC substrate serves plain (non-pipelined) calls inline on the
connection read thread, so observability survives overload by
construction (docs/distill_dataplane.md §"The serving plane").

:class:`DecodeAdmission` is the PER-PHASE variant for the
autoregressive decode engine: a sequence's cost splits into a prefill
phase (one batched forward, governs time-to-first-token) and a decode
phase (one slot for its whole lifetime, governs everyone's inter-token
latency), so the front door projects against BOTH — TTFT for the
prefill queue, ITL for the slot plane — plus slot-occupancy shedding
(docs/distill_dataplane.md §"Autoregressive decode").
"""

import threading
import time

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.robustness import faults
from edl_tpu.utils import errors

_ADMITTED = obs_metrics.counter(
    "edl_serve_admitted_total", "predict rows admitted to the device "
    "queue")
_SHED = obs_metrics.counter(
    "edl_serve_shed_total", "predict rows shed by admission control",
    labels=("reason",))
_PENDING = obs_metrics.gauge(
    "edl_serve_pending_rows", "admitted rows not yet served")

SHED_REASONS = ("draining", "queue_full", "rate_limit", "slo",
                "deadline")

# decode-phase taxonomy: prefill-phase reasons (queue_full, ttft) speak
# about the waiting queue; decode-phase reasons (slots, itl, deadline)
# speak about the slot plane
DECODE_SHED_REASONS = ("draining", "queue_full", "slots", "ttft", "itl",
                       "deadline")

_DECODE_SHED = obs_metrics.counter(
    "edl_decode_shed_total", "sequences shed by per-phase decode "
    "admission", labels=("reason",))


class AdmissionController(object):
    """Front-door policy for one teacher server. Thread-safe; one
    instance per :class:`TeacherServer`.

    ``max_queue_rows``: bound on admitted-but-unserved rows (the
    admission queue). ``slo_ms``: queue-wait projection threshold
    (None disables projection shedding). ``rate``/``burst``: token
    bucket in rows/s and rows (``rate=None`` disables). ``ewma_alpha``:
    smoothing for the per-row service-time estimate."""

    def __init__(self, max_queue_rows=4096, slo_ms=500.0, rate=None,
                 burst=None, ewma_alpha=0.2, clock=time.monotonic):
        self._max_queue_rows = int(max_queue_rows)
        self._slo_ms = None if slo_ms is None else float(slo_ms)
        self._rate = None if rate in (None, 0) else float(rate)
        self._burst = float(burst) if burst is not None else (
            self._rate if self._rate is not None else 0.0)
        self._alpha = float(ewma_alpha)
        self._clock = clock
        self._lock = threading.Lock()
        self._pending_rows = 0
        self._tokens = self._burst
        self._refill_at = clock()
        self._row_ms = None  # EWMA of per-row device service time
        self._draining = False
        self._admitted = 0
        self._shed = {r: 0 for r in SHED_REASONS}

    # -- policy inputs -----------------------------------------------------

    def set_draining(self, flag=True):
        with self._lock:
            self._draining = bool(flag)

    @property
    def draining(self):
        with self._lock:
            return self._draining

    def _refill_locked(self, now):
        if self._rate is None:
            return
        dt = max(0.0, now - self._refill_at)
        self._refill_at = now
        self._tokens = min(self._burst, self._tokens + dt * self._rate)

    def _projected_wait_ms_locked(self, extra_rows=0):
        if self._row_ms is None:
            return None
        return (self._pending_rows + extra_rows) * self._row_ms

    # -- the decision ------------------------------------------------------

    def admit(self, rows=1):
        """Admit ``rows`` or raise :class:`OverloadedError`. The caller
        MUST balance every successful admit with :meth:`release` (the
        device loop does, on every resolution path)."""
        if faults.PLANE is not None:
            faults.PLANE.fire("serve.admit", rows=rows,
                              pending=self._pending_rows)
        now = self._clock()
        with self._lock:
            if self._draining:
                raise self._shed_locked("draining", retry_after_s=0.1)
            if self._pending_rows + rows > self._max_queue_rows:
                wait = self._projected_wait_ms_locked()
                raise self._shed_locked(
                    "queue_full",
                    retry_after_s=(wait / 1000.0) if wait else 0.2)
            self._refill_locked(now)
            if self._rate is not None and self._tokens < rows:
                deficit = rows - self._tokens
                raise self._shed_locked(
                    "rate_limit", retry_after_s=deficit / self._rate)
            # liveness: an EMPTY queue never SLO-sheds, whatever the
            # estimate says. The EWMA only updates when admitted work
            # completes, so shedding at pending == 0 would freeze a
            # poisoned estimate (e.g. a first-batch jit compile spike)
            # into shedding forever — admitting is the only way the
            # projection can recover.
            if self._slo_ms is not None and self._pending_rows > 0:
                wait = self._projected_wait_ms_locked(extra_rows=rows)
                if wait is not None and wait > self._slo_ms:
                    raise self._shed_locked(
                        "slo",
                        retry_after_s=(wait - self._slo_ms) / 1000.0)
            if self._rate is not None:
                self._tokens -= rows
            self._pending_rows += rows
            self._admitted += rows
        _ADMITTED.inc(rows)
        _PENDING.set(self._pending_rows)
        return now  # admit timestamp, for queue-wait accounting

    def _shed_locked(self, reason, retry_after_s=None):
        self._shed[reason] += 1
        _SHED.labels(reason).inc()
        return errors.OverloadedError.shed(reason,
                                           retry_after_s=retry_after_s)

    def expired(self, admitted_at, deadline_ms):
        """True when a queued item's per-request budget has elapsed
        (the device loop sheds it dead-on-arrival as ``deadline``)."""
        if deadline_ms is None:
            return False
        return (self._clock() - admitted_at) * 1000.0 > float(deadline_ms)

    def shed_expired(self, rows):
        """Account one dead-on-arrival shed (rows already admitted)."""
        with self._lock:
            err = self._shed_locked("deadline")
            self._pending_rows = max(0, self._pending_rows - rows)
        _PENDING.set(self._pending_rows)
        return err

    def release(self, rows, service_s=None):
        """Balance an admit: ``rows`` left the queue. ``service_s``
        (device wall time for the batch that served them) updates the
        per-row EWMA feeding the queue-wait projection."""
        with self._lock:
            self._pending_rows = max(0, self._pending_rows - rows)
            if service_s is not None and rows > 0:
                ms = service_s * 1000.0 / rows
                self._row_ms = ms if self._row_ms is None else (
                    self._alpha * ms + (1.0 - self._alpha) * self._row_ms)
        _PENDING.set(self._pending_rows)

    def idle(self):
        with self._lock:
            return self._pending_rows == 0

    def stats(self):
        with self._lock:
            wait = self._projected_wait_ms_locked()
            return {
                "pending_rows": self._pending_rows,
                "max_queue_rows": self._max_queue_rows,
                "queue_frac": (self._pending_rows
                               / float(self._max_queue_rows)),
                "projected_wait_ms": wait,
                "row_ms": self._row_ms,
                "slo_ms": self._slo_ms,
                "draining": self._draining,
                "admitted": self._admitted,
                "shed": dict(self._shed),
                "shed_total": sum(self._shed.values()),
            }


class DecodeAdmission(object):
    """Per-phase front door for :class:`~edl_tpu.serve.decode_engine.
    DecodeEngine`. Thread-safe; one instance per engine.

    The engine feeds it two service-time estimates (EWMAs it measures on
    the device loop): ``prefill_ms_per_token`` — prefill wall time
    NORMALIZED by the tokens it prefilled (per-seq EWMAs let one long
    prompt poison the projection into shedding short prompts; see
    :meth:`observe_prefill_ms`) — and ``itl_ms`` — wall time of one
    fused decode step, which IS the inter-token latency every occupied
    slot experiences. Admission then checks, in order:

    - ``draining``    — decommissioning; new sequences go elsewhere.
    - ``queue_full``  — the waiting (pre-prefill) queue is at
                        ``max_waiting``.
    - ``slots``       — zero free slots AND the waiting queue already
                        holds ``slot_slack`` sequences (default: one
                        full slot refill) — occupancy shedding: more
                        queueing cannot be served before slots turn
                        over.
    - ``ttft``        — TTFT projection: the prefill WORK ahead of this
                        sequence — queued prefill tokens (waiting
                        suffixes + the remainder of any half-prefilled
                        chunked sequence) plus its own
                        suffix-after-prefix-reuse — times the per-token
                        prefill EWMA exceeds ``ttft_slo_ms``. A prompt
                        whose prefix is cached projects only its
                        suffix, so reuse directly buys admission
                        headroom. Callers without token accounting fall
                        back to the coarse (waiting+1) x EWMA form.
    - ``itl``         — the measured ITL EWMA exceeds ``itl_slo_ms``
                        while slots are occupied: every admitted
                        sequence inflates EVERY resident sequence's
                        ITL, so the decode plane protects residents by
                        shedding arrivals.

    Same liveness rules as :class:`AdmissionController`: a cold engine
    (no estimate yet) admits freely, and an idle one (no waiting work /
    no occupied slots) never projection-sheds — the EWMAs only update
    while work flows, so shedding at idle would freeze a poisoned
    estimate forever. ``deadline`` accounts decode-phase evictions
    (sequence exceeded its budget mid-generation; the device loop calls
    :meth:`shed_evicted`)."""

    def __init__(self, max_waiting=64, ttft_slo_ms=None, itl_slo_ms=None,
                 slot_slack=None, ewma_alpha=0.2, clock=time.monotonic):
        self._max_waiting = int(max_waiting)
        self._ttft_slo_ms = (None if ttft_slo_ms is None
                             else float(ttft_slo_ms))
        self._itl_slo_ms = (None if itl_slo_ms is None
                            else float(itl_slo_ms))
        self._slot_slack = slot_slack  # None -> slots, resolved per call
        self._alpha = float(ewma_alpha)
        self._clock = clock
        self._lock = threading.Lock()
        self._prefill_ms_tok = None  # EWMA, prefill ms PER TOKEN
        self._itl_ms = None          # EWMA, one fused decode step
        self._draining = False
        self._admitted = 0
        self._shed = {r: 0 for r in DECODE_SHED_REASONS}

    def set_draining(self, flag=True):
        with self._lock:
            self._draining = bool(flag)

    @property
    def draining(self):
        with self._lock:
            return self._draining

    # -- estimates (fed by the engine's device loop) -----------------------

    def observe_prefill_ms(self, ms, tokens=1):
        """Fold one prefill interval into the PER-TOKEN EWMA. ``tokens``
        is how many prompt tokens that interval prefilled (the padded
        bucket's valid span; the chunk's valid span under chunking). A
        per-sequence EWMA would let one long prompt inflate the estimate
        ~bucket-fold and poison the TTFT projection into shedding SHORT
        prompts for the next ~1/alpha arrivals; normalizing makes the
        estimate prompt-length-invariant."""
        per_tok = float(ms) / max(1, int(tokens))
        with self._lock:
            self._prefill_ms_tok = (
                per_tok if self._prefill_ms_tok is None else
                self._alpha * per_tok
                + (1.0 - self._alpha) * self._prefill_ms_tok)

    def observe_itl_ms(self, ms):
        with self._lock:
            self._itl_ms = ms if self._itl_ms is None else (
                self._alpha * ms + (1.0 - self._alpha) * self._itl_ms)

    # -- the decision ------------------------------------------------------

    def admit(self, free_slots, waiting, occupied, slots,
              suffix_tokens=None, queued_prefill_tokens=None):
        """Admit one sequence or raise :class:`OverloadedError`.
        ``free_slots``/``occupied``/``slots`` describe the slot plane,
        ``waiting`` the pre-prefill queue, at the instant of arrival.
        ``suffix_tokens`` — tokens THIS prompt still needs prefilled
        after prefix reuse — and ``queued_prefill_tokens`` — prefill
        tokens already ahead of it (waiting suffixes + unprefilled
        chunk remainders) — switch the TTFT projection to token
        accounting; omitted, it falls back to the coarse per-sequence
        form."""
        with self._lock:
            if self._draining:
                raise self._shed_locked("draining", retry_after_s=0.1)
            if waiting >= self._max_waiting:
                raise self._shed_locked(
                    "queue_full", retry_after_s=self._turnover_s_locked())
            slack = (int(slots) if self._slot_slack is None
                     else int(self._slot_slack))
            if free_slots <= 0 and waiting >= slack:
                raise self._shed_locked(
                    "slots", retry_after_s=self._turnover_s_locked())
            if (self._ttft_slo_ms is not None
                    and self._prefill_ms_tok is not None):
                if suffix_tokens is not None:
                    # token-accurate projection; liveness: only sheds
                    # when prefill work is ALREADY queued ahead (an
                    # idle engine admits whatever the estimate says)
                    queued = int(queued_prefill_tokens or 0)
                    ttft = ((queued + int(suffix_tokens))
                            * self._prefill_ms_tok)
                    if queued > 0 and ttft > self._ttft_slo_ms:
                        raise self._shed_locked(
                            "ttft", retry_after_s=(
                                ttft - self._ttft_slo_ms) / 1000.0)
                elif waiting > 0:
                    ttft = (waiting + 1) * self._prefill_ms_tok
                    if ttft > self._ttft_slo_ms:
                        raise self._shed_locked(
                            "ttft", retry_after_s=(
                                ttft - self._ttft_slo_ms) / 1000.0)
            if (self._itl_slo_ms is not None and occupied > 0
                    and self._itl_ms is not None
                    and self._itl_ms > self._itl_slo_ms):
                raise self._shed_locked(
                    "itl", retry_after_s=self._turnover_s_locked())
            self._admitted += 1

    def _turnover_s_locked(self):
        # a slot frees after roughly one sequence tail: O(itl) per token;
        # without an estimate fall back to a fixed polite backoff
        if self._itl_ms is not None:
            return max(0.05, self._itl_ms / 100.0)
        return 0.2

    def _shed_locked(self, reason, retry_after_s=None):
        self._shed[reason] += 1
        _DECODE_SHED.labels(reason).inc()
        return errors.OverloadedError.shed(reason,
                                           retry_after_s=retry_after_s)

    def shed_evicted(self):
        """Account a decode-phase deadline eviction (the device loop
        already freed the slot)."""
        with self._lock:
            return self._shed_locked("deadline")

    def stats(self):
        with self._lock:
            return {
                "max_waiting": self._max_waiting,
                "prefill_ms_per_token": self._prefill_ms_tok,
                "itl_ms": self._itl_ms,
                "ttft_slo_ms": self._ttft_slo_ms,
                "itl_slo_ms": self._itl_slo_ms,
                "draining": self._draining,
                "admitted": self._admitted,
                "shed": dict(self._shed),
                "shed_total": sum(self._shed.values()),
            }
