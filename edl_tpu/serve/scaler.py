"""ServeScaler: the SLO-driven teacher-fleet autoscaler.

The serving-plane sibling of :class:`edl_tpu.obs.autopilot.Autopilot`,
with the same safety model — every decision is a journaled ``action/v1``
record in a bounded store journal, gated by per-kind cooldowns, burst
bounds, and streak hysteresis so the engine provably never flaps, with
a global ``off|dry|on`` mode where dry-run journals the IDENTICAL
action stream while applying nothing.

Signals, folded from the fleet's ``stats()`` RPCs each tick (the
admission controller enriches every teacher's stats with queue depth,
projected wait, and shed counters — serve/admission.py):

- **occupancy** — mean compiled-batch fill across live teachers;
- **slot occupancy** — worst KV-slot fill across decode engines
  (``decode_slot_frac`` from serve/decode_engine.py): a fleet can be
  decode-bound with near-empty predict batches, so slot pressure is a
  first-class overload signal;
- **queue pressure** — worst projected queue wait vs the predict SLO
  (fallback: queue fill fraction when no service estimate exists yet);
- **sheds** — any admission shed since the last tick is overload by
  definition (the front door is already refusing work), decode-phase
  sheds included;
- **burn** — the ``predict_p99`` multi-window burn-rate severity from
  :class:`edl_tpu.obs.slo.BurnRateEvaluator`, fed cumulative
  (total, bad) predict-latency counts by the host.

Scale-out fires after ``out_streak`` CONSECUTIVE overloaded ticks
(bounded by ``max_teachers``); scale-in after ``in_streak`` consecutive
idle ticks (zero sheds, low occupancy, no burn; bounded by
``min_teachers``) and decommissions the least-loaded teacher through
the drain-safe protocol (serve/drain.py) — the actuator owns the
actual drain, so a dry-run never touches the fleet. Opposite signals
reset each other's streaks, and each kind's cooldown spans several
in-streaks worth of ticks, so out→in oscillation cannot sustain.

Like the autopilot, this module is an obs-adjacent LEAF: the
coordination client and both actuators are injected, robustness
imports are lazy.
"""

import json
import os
import threading
import time
from collections import deque

from edl_tpu.obs import slo as slo_mod
from edl_tpu.utils.logger import logger

#: store service key for the serve-plane action journal
SERVICE_SERVE = "serve"

#: the single bounded action journal under SERVICE_SERVE
#: (leader-written, last-writer-wins — one scaler per fleet)
JOURNAL_KEY = "journal"

ENV_VAR = "EDL_TPU_SERVE_SCALER"
MODE_OFF = "off"
MODE_DRY = "dry"
MODE_ON = "on"

ACTION_KINDS = ("scale_out", "scale_in")


def mode_from_env(value=None):
    """``on`` applies, ``dry`` journals without applying, anything
    else is ``off`` (the default — zero behavior unless enabled)."""
    raw = (os.environ.get(ENV_VAR, MODE_OFF) if value is None else value)
    raw = str(raw).strip().lower()
    if raw in (MODE_ON, "1", "true", "enabled"):
        return MODE_ON
    if raw in (MODE_DRY, "dry_run", "dryrun"):
        return MODE_DRY
    return MODE_OFF


class ServeScaler(object):
    """``tick(stats_by_endpoint, predict_sample=None, now=None)`` is
    the whole runtime surface: the host (bench, launcher, or test)
    scrapes each teacher's ``stats()`` and calls it once per interval.
    The policy is a pure fold over the stats — identical inputs
    produce an identical decision stream regardless of mode, which is
    exactly what the dry≡on parity criterion asserts.

    Actuators (injected, optional — a decision without its actuator is
    journaled ``outcome: failed``):

    - ``scale_out_fn()`` — start one more teacher; returns its
      endpoint (or any JSON-able receipt).
    - ``scale_in_fn(endpoint)`` — drain-safe decommission of
      ``endpoint`` (serve.drain.decommission or equivalent).
    """

    def __init__(self, coord, pod_id, mode=None, interval=10.0,
                 scale_out_fn=None, scale_in_fn=None,
                 min_teachers=1, max_teachers=8,
                 occupancy_high=0.8, occupancy_low=0.3,
                 queue_wait_frac_high=1.0, out_streak=2, in_streak=4,
                 cooldowns=None, burst=3, burst_window_s=None,
                 burn_short_s=None, burn_long_s=None,
                 journal_cap=64, retry=None, clock=time.time):
        self._coord = coord
        self._pod_id = pod_id
        self._mode = mode_from_env(mode)
        self._interval = float(interval)
        self._scale_out_fn = scale_out_fn
        self._scale_in_fn = scale_in_fn
        self._min = max(0, int(min_teachers))
        self._max = max(self._min, int(max_teachers))
        self._occ_high = float(occupancy_high)
        self._occ_low = float(occupancy_low)
        self._wait_frac_high = float(queue_wait_frac_high)
        self._out_streak_need = max(1, int(out_streak))
        self._in_streak_need = max(1, int(in_streak))
        self._cooldowns = {
            # scale-in waits out several idle streaks AND any recent
            # scale-out, so a grow→shrink→grow loop cannot sustain
            "scale_out": 3.0 * self._interval,
            "scale_in": 6.0 * self._interval,
        }
        self._cooldowns.update(cooldowns or {})
        self._burst = max(1, int(burst))
        self._burst_window_s = (float(burst_window_s)
                                if burst_window_s is not None
                                else 60.0 * self._interval)
        self._journal_cap = max(1, int(journal_cap))
        self._clock = clock
        if retry is None:
            # lazy: robustness imports obs; serve sits next to obs
            from edl_tpu.robustness.policy import RetryPolicy
            retry = RetryPolicy(max_attempts=3, base_delay=0.05,
                                max_delay=0.5, jitter=0.0)
        self._retry = retry
        # the predict_p99 burn evaluator; windows default to a few
        # ticks so the bench's compressed timeline still burns
        self._burn = slo_mod.BurnRateEvaluator(
            slos=[s for s in slo_mod.DEFAULT_SLOS
                  if s.name == "predict_p99"],
            short_window=(burn_short_s if burn_short_s is not None
                          else 3.0 * self._interval),
            long_window=(burn_long_s if burn_long_s is not None
                         else 12.0 * self._interval),
            clock=clock)

        self._lock = threading.Lock()
        self._seq = None  # lazily anchored on the stored journal
        self._actions = []
        self._last_action_ts = {}
        self._recent = {k: deque() for k in ACTION_KINDS}
        self._out_streak = 0
        self._in_streak = 0
        self._last_shed_total = None

    # -- public surface ----------------------------------------------------

    @property
    def mode(self):
        return self._mode

    def actions(self):
        """Records journaled by THIS engine instance (in order)."""
        with self._lock:
            return list(self._actions)

    def tick(self, stats_by_endpoint, predict_sample=None, now=None):
        """One policy pass. ``stats_by_endpoint``: {endpoint: the
        teacher's ``stats()`` dict}. ``predict_sample``: optional
        cumulative ``(total, bad)`` predict-latency counts for the
        burn evaluator. Returns the ``action/v1`` records journaled
        this tick. Never raises — the host loop must survive any
        policy bug."""
        if self._mode == MODE_OFF:
            return []
        now = self._clock() if now is None else now
        try:
            return self._tick(stats_by_endpoint or {}, predict_sample,
                              now)
        except Exception:  # noqa: BLE001 — policy bug must not kill host
            logger.exception("serve scaler tick failed")
            return []

    # -- signal fold -------------------------------------------------------

    @staticmethod
    def _signals(stats_by_endpoint):
        live = {ep: s for ep, s in stats_by_endpoint.items()
                if isinstance(s, dict) and not s.get("draining")}
        occs, wait_fracs, shed_total = [], [], 0
        slot_fracs, reuse_fracs = [], []
        for s in live.values():
            occs.append(float(s.get("occupancy") or 0.0))
            slo_ms = s.get("slo_ms")
            wait = s.get("projected_wait_ms")
            if slo_ms and wait is not None:
                wait_fracs.append(float(wait) / float(slo_ms))
            elif s.get("queue_frac") is not None:
                wait_fracs.append(float(s["queue_frac"]))
            shed_total += int(s.get("shed_total") or 0)
            # the decode plane (serve/decode_engine.py): KV-slot
            # occupancy is the decode-phase analog of batch fill, and
            # its sheds are part of the same overload signal
            if s.get("decode_slot_frac") is not None:
                slot_fracs.append(float(s["decode_slot_frac"]))
            adm = s.get("decode_admission")
            if isinstance(adm, dict):
                shed_total += int(adm.get("shed_total") or 0)
            # prefix reuse discounts the prefill work a nominal token
            # of traffic actually costs — journaled so a scale decision
            # under cache-heavy traffic is explainable from the record
            pfx = s.get("decode_prefix")
            if isinstance(pfx, dict) and pfx.get("enabled"):
                reuse_fracs.append(float(pfx.get("reuse_frac") or 0.0))
        return {
            "teachers": len(live),
            "occupancy": (sum(occs) / len(occs)) if occs else 0.0,
            "wait_frac": max(wait_fracs) if wait_fracs else 0.0,
            "slot_frac": max(slot_fracs) if slot_fracs else 0.0,
            "prefix_reuse_frac": (sum(reuse_fracs) / len(reuse_fracs)
                                  if reuse_fracs else 0.0),
            "shed_total": shed_total,
        }

    def _tick(self, stats_by_endpoint, predict_sample, now):
        sig = self._signals(stats_by_endpoint)
        n = sig["teachers"]
        severity = None
        if predict_sample is not None:
            total, bad = predict_sample
            self._burn.observe("predict_p99", total, bad, now=now)
        for row in self._burn.evaluate(now=now):
            severity = row["severity"]
        prev_shed = self._last_shed_total
        self._last_shed_total = sig["shed_total"]
        sheds_delta = (0 if prev_shed is None
                       else max(0, sig["shed_total"] - prev_shed))

        overloaded = (sig["occupancy"] >= self._occ_high
                      or sig["slot_frac"] >= self._occ_high
                      or sig["wait_frac"] >= self._wait_frac_high
                      or sheds_delta > 0
                      or severity is not None)
        idle = (sig["occupancy"] <= self._occ_low
                and sig["slot_frac"] <= self._occ_low
                and sig["wait_frac"] < 0.5 * self._wait_frac_high
                and sheds_delta == 0
                and severity is None)

        if overloaded:
            self._out_streak += 1
            self._in_streak = 0
        elif idle:
            self._in_streak += 1
            self._out_streak = 0
        else:
            # hysteresis dead band: neither signal, both streaks decay
            self._out_streak = 0
            self._in_streak = 0

        why = ("occupancy %.2f, slots %.2f, wait %.2fx slo, reuse %.2f, "
               "%d sheds this tick, burn %s, %d teachers"
               % (sig["occupancy"], sig["slot_frac"], sig["wait_frac"],
                  sig["prefix_reuse_frac"], sheds_delta,
                  severity or "ok", n))
        cause = {"signals": sig, "sheds_delta": sheds_delta,
                 "burn_severity": severity}

        if (self._out_streak >= self._out_streak_need and n < self._max
                and self._gate_ok("scale_out", now)):
            self._out_streak = 0
            outcome, attempts, error, result = self._apply(
                "scale_out", self._scale_out_fn)
            reason = ("overloaded for %d consecutive ticks (%s); "
                      "scaling out to %d teachers"
                      % (self._out_streak_need, why, n + 1))
            return [self._record("scale_out", "fleet", reason, cause,
                                 outcome, attempts, error, result, now,
                                 extra={"teachers": n,
                                        "decision": "grow"})]

        if (self._in_streak >= self._in_streak_need and n > self._min
                and self._gate_ok("scale_in", now)):
            victim = self._victim(stats_by_endpoint)
            if victim is None:
                return []
            self._in_streak = 0
            outcome, attempts, error, result = self._apply(
                "scale_in", self._scale_in_fn, victim)
            reason = ("idle for %d consecutive ticks (%s); drain-safe "
                      "decommission of %s"
                      % (self._in_streak_need, why, victim))
            return [self._record("scale_in", victim, reason, cause,
                                 outcome, attempts, error, result, now,
                                 extra={"teachers": n,
                                        "decision": "shrink"})]
        return []

    @staticmethod
    def _victim(stats_by_endpoint):
        """Deterministic scale-in choice: least-loaded live teacher,
        endpoint order breaking ties — identical inputs pick the
        identical victim (the dry≡on parity contract)."""
        live = sorted((float(s.get("occupancy") or 0.0),
                       float(s.get("pending_rows") or 0), ep)
                      for ep, s in stats_by_endpoint.items()
                      if isinstance(s, dict) and not s.get("draining"))
        return live[0][2] if live else None

    # -- gating / apply / journal (the autopilot contract) -----------------

    def _gate_ok(self, kind, now):
        last = self._last_action_ts.get(kind)
        if last is not None and now - last < self._cooldowns.get(kind,
                                                                 0.0):
            return False
        ring = self._recent[kind]
        while ring and now - ring[0] > self._burst_window_s:
            ring.popleft()
        return len(ring) < self._burst

    def _apply(self, kind, actuator, *args):
        """Dry-run short-circuits (nothing applies); otherwise the
        actuator runs under the standard retry policy. The actuator
        itself owns any chaos exposure — scale-in's drain fires
        ``serve.drain`` inside the teacher (serve/drain.py), so a
        drill hits the REAL drain path, not a scaler shim."""
        if self._mode == MODE_DRY:
            return "dry_run", 0, None, None
        if actuator is None:
            return "failed", 0, "no actuator bound for %r" % kind, None
        attempts = [0]

        def once():
            attempts[0] += 1
            return actuator(*args)

        try:
            result = self._retry.call(once)
            if result is not None and not isinstance(
                    result, (str, int, float, bool, list, dict)):
                result = repr(result)
            return "applied", attempts[0], None, result
        except Exception as e:  # noqa: BLE001 — journaled, not raised
            return "failed", attempts[0], repr(e), None

    def _next_seq(self):
        # caller holds self._lock; anchor once on the stored journal so
        # a re-elected host's scaler continues the sequence
        if self._seq is None:
            self._seq = 0
            try:
                for a in load_actions(self._coord):
                    self._seq = max(self._seq, int(a.get("seq", 0)))
            except Exception:  # noqa: BLE001 — fresh store: start at 0
                pass
        self._seq += 1
        return self._seq

    def _record(self, kind, target, reason, cause, outcome, attempts,
                error, result, now, extra=None):
        with self._lock:
            seq = self._next_seq()
            action = {
                "schema": "action/v1",
                "id": "serve-act-%d" % seq,
                "seq": seq,
                "ts": now,
                "kind": kind,
                "mode": ("dry_run" if self._mode == MODE_DRY
                         else "applied"),
                "actor": self._pod_id,
                "target": target,
                "reason": reason,
                "cause": cause,
                "outcome": outcome,
                "attempts": attempts,
                "error": error,
                "result": result,
            }
            if extra:
                action.update(extra)
            self._actions.append(action)
            self._last_action_ts[kind] = now
            self._recent[kind].append(now)
        try:
            raw = self._coord.get_value(SERVICE_SERVE, JOURNAL_KEY) \
                or "[]"
            journal = json.loads(raw)
            if not isinstance(journal, list):
                journal = []
        except Exception:  # noqa: BLE001 — corrupt/absent: restart it
            journal = []
        journal = journal[-(self._journal_cap - 1):]
        journal.append(action)
        try:
            self._coord.set_server_permanent(SERVICE_SERVE, JOURNAL_KEY,
                                             json.dumps(journal))
        except Exception as e:  # noqa: BLE001 — best-effort by contract
            logger.debug("serve scaler journal write failed: %r", e)
        logger.warning("serve scaler %s: %s %s -> %s%s", self._mode,
                       kind, target, outcome,
                       (" (%s)" % error) if error else "")
        return action


def load_actions(coord, service=SERVICE_SERVE):
    """The stored serve-plane ``action/v1`` journal (oldest first)."""
    try:
        raw = coord.get_value(service, JOURNAL_KEY)
        if not raw:
            return []
        journal = json.loads(raw)
        if not isinstance(journal, list):
            return []
        return [a for a in journal
                if isinstance(a, dict) and a.get("schema") == "action/v1"]
    except Exception as e:  # noqa: BLE001 — absent store == no journal
        logger.debug("serve scaler journal read failed: %r", e)
        return []
