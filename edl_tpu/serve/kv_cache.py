"""Preallocated slot-based KV cache for continuous-batching decode.

The paged-attention insight (vLLM, Kwon et al. SOSP'23) applied at slot
granularity: the engine owns ONE device-resident cache pytree shaped
``[slots, max_len, heads, head_dim]`` per layer (the Flax "cache"
collection of ``models/gpt.py`` initialized at ``batch=slots``), and a
host-side free-slot allocator maps live sequences onto rows. Admitting
a sequence scatters its prefill cache into a free row; retiring one
just returns the row to the free list — no device work, because decode
correctness never reads a position that hasn't been written by the
CURRENT tenant:

- prefill overwrites the ENTIRE row ``[0:max_len]`` (the prefill cache
  from ``model.apply`` is full-length: prompt K/V in ``[0:prompt_len)``,
  zeros beyond), erasing any previous tenant, and
- the decode step at position ``i`` writes K/V at ``i`` BEFORE attending
  ``<= i``, so the zeros beyond the prompt are always replaced before
  they are ever attended.

Slot rows are therefore reused without zeroing, and the fused decode
step runs at a FIXED shape ``[slots, ...]`` whatever subset of rows is
live — membership churn costs a mask update, never a recompile.

Shared-prefix KV reuse (SGLang RadixAttention, Zheng et al. 2023) adds
a THIRD slot state: a retired sequence's row can be RETAINED as a
cached prefix instead of freed — :class:`PrefixCache` keeps a host-side
token trie mapping prompt prefixes to the slot rows holding their K/V,
so a later prompt sharing a stored prefix copies the row and prefills
only the suffix. Cached rows are evictable (LRU) the moment the
allocator runs dry, so reuse never reduces decode capacity — it only
recycles idle rows that would otherwise sit on the free list.
"""

import threading

import jax

from edl_tpu.obs import metrics as obs_metrics

_PREFIX_HITS = obs_metrics.counter(
    "edl_decode_prefix_hits_total",
    "prompt lookups that reused a cached KV prefix")
_PREFIX_EVICTIONS = obs_metrics.counter(
    "edl_decode_prefix_evictions_total",
    "cached prefix rows reclaimed by the slot allocator (LRU)")
_PREFIX_REUSE_TOKENS = obs_metrics.counter(
    "edl_decode_prefix_reuse_tokens_total",
    "prompt tokens whose prefill was skipped via prefix reuse")
_PREFIX_ROWS = obs_metrics.gauge(
    "edl_decode_prefix_cached_rows",
    "idle KV slot rows retained as cached prefixes")


class SlotKvCache(object):
    """``slots`` preallocated cache rows + a free-slot allocator.

    The device arrays live in ``self.cache`` (a Flax "cache" pytree with
    leading dim ``slots``); the allocator is host-side and thread-safe.
    The device loop is the only writer of ``self.cache``; ``alloc`` /
    ``free`` only move slot ids between the free list and the live set.

    Slots move through three states: free -> live (``alloc``), live ->
    free (``free``), and — for prefix reuse — live -> cached
    (``retain``) and cached -> free (``release``). Cached rows hold a
    retired sequence's K/V for the prefix trie; they are NOT allocatable
    until released, so a cached row's contents stay valid until the
    allocator (under pressure) evicts it via the trie's LRU.
    """

    def __init__(self, init_cache_fn, slots):
        if slots < 1:
            raise ValueError("need at least one slot, got %d" % slots)
        self.slots = int(slots)
        self.cache = init_cache_fn(self.slots)
        self._lock = threading.Lock()
        self._free = list(range(self.slots - 1, -1, -1))  # pop -> slot 0 first
        self._live = set()
        self._cached = set()

    def alloc(self):
        """A free slot id, or ``None`` when fully occupied."""
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop()
            self._live.add(slot)
            return slot

    def free(self, slot):
        with self._lock:
            if slot not in self._live:
                raise ValueError("slot %d is not live" % slot)
            self._live.discard(slot)
            self._free.append(slot)

    def retain(self, slot):
        """live -> cached: keep the row's K/V for prefix reuse instead
        of returning it to the free list."""
        with self._lock:
            if slot not in self._live:
                raise ValueError("slot %d is not live" % slot)
            self._live.discard(slot)
            self._cached.add(slot)
            _PREFIX_ROWS.set(len(self._cached))

    def release(self, slot):
        """cached -> free: the trie evicted this row; its contents are
        no longer reachable and the allocator may hand it out."""
        with self._lock:
            if slot not in self._cached:
                raise ValueError("slot %d is not cached" % slot)
            self._cached.discard(slot)
            self._free.append(slot)
            _PREFIX_ROWS.set(len(self._cached))

    @property
    def occupied(self):
        with self._lock:
            return len(self._live)

    @property
    def free_slots(self):
        with self._lock:
            return len(self._free)

    @property
    def cached_rows(self):
        with self._lock:
            return len(self._cached)

    def live(self):
        with self._lock:
            return sorted(self._live)

    def cached(self):
        with self._lock:
            return sorted(self._cached)

    def bytes(self):
        return sum(leaf.size * leaf.dtype.itemsize
                   for leaf in jax.tree_util.tree_leaves(self.cache))


class _TrieNode(object):
    __slots__ = ("kids", "slots")

    def __init__(self):
        self.kids = {}    # token -> _TrieNode
        self.slots = set()  # slot rows whose stored path passes here


class PrefixCache(object):
    """Host-side token trie: prompt prefixes -> slot rows holding their
    K/V (the RadixAttention index at slot granularity).

    Every completed prefill inserts its full prompt path; a lookup walks
    the trie and returns the DEEPEST stored prefix strictly shorter than
    the prompt (at least one suffix token must remain, because the
    first output token comes from the last prompt position's logits).
    Causality makes the reuse exact: K/V at position i depends only on
    tokens ``<= i``, so a row whose stored path shares the first d
    tokens holds bit-identical K/V for positions ``[0, d)``.

    One path per slot (a slot's row holds exactly one sequence's K/V);
    re-inserting a slot replaces its previous path. ``evict_lru``
    reclaims the least-recently-USED slot among the candidates the
    engine passes (its idle cached rows) — live rows are never victims.
    Thread-safe; the engine's device loop is the only inserter/evictor,
    but ``peek_len`` is called from submit threads for TTFT projection.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._root = _TrieNode()
        self._paths = {}   # slot -> tuple of prompt tokens
        self._stamp = {}   # slot -> last-use tick (LRU order)
        self._tick = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.reuse_tokens = 0

    def insert(self, tokens, slot):
        path = tuple(int(t) for t in tokens)
        with self._lock:
            self._forget_locked(slot)
            node = self._root
            for t in path:
                node = node.kids.setdefault(t, _TrieNode())
                node.slots.add(slot)
            self._paths[slot] = path
            self._tick += 1
            self._stamp[slot] = self._tick

    def lookup(self, tokens):
        """(slot, depth) of the deepest reusable stored prefix, or
        ``(None, 0)``. Counts the hit/miss and bumps the donor's LRU
        stamp (a reused row is hot — evict colder ones first)."""
        path = [int(t) for t in tokens]
        with self._lock:
            node = self._root
            best_slot, best_depth, depth = None, 0, 0
            for t in path[:max(0, len(path) - 1)]:
                node = node.kids.get(t)
                if node is None:
                    break
                depth += 1
                if node.slots:
                    # any slot through this node shares >= depth tokens;
                    # prefer the most recently used (coldest stay LRU)
                    best_slot = max(
                        node.slots, key=lambda s: self._stamp.get(s, 0))
                    best_depth = depth
            if best_slot is None:
                self.misses += 1
                return None, 0
            self.hits += 1
            self.reuse_tokens += best_depth
            self._tick += 1
            self._stamp[best_slot] = self._tick
        _PREFIX_HITS.inc()
        _PREFIX_REUSE_TOKENS.inc(best_depth)
        return best_slot, best_depth

    def peek_len(self, tokens):
        """Reusable prefix length for ``tokens`` WITHOUT counting a
        hit or touching LRU — the admission TTFT projection's view."""
        path = [int(t) for t in tokens]
        with self._lock:
            node = self._root
            best, depth = 0, 0
            for t in path[:max(0, len(path) - 1)]:
                node = node.kids.get(t)
                if node is None:
                    break
                depth += 1
                if node.slots:
                    best = depth
        return best

    def note_miss(self):
        """Count a lookup that never reached the trie (e.g. a faulted
        ``serve.decode.prefix_lookup`` falling back to cold prefill)."""
        with self._lock:
            self.misses += 1

    def has(self, slot):
        with self._lock:
            return slot in self._paths

    def forget(self, slot):
        """Drop ``slot``'s path (slot freed/evicted or being re-filled);
        no-op when the slot has no stored path."""
        with self._lock:
            self._forget_locked(slot)

    def _forget_locked(self, slot):
        path = self._paths.pop(slot, None)
        self._stamp.pop(slot, None)
        if path is None:
            return
        node, chain = self._root, []
        for t in path:
            nxt = node.kids.get(t)
            if nxt is None:
                break
            chain.append((node, t, nxt))
            nxt.slots.discard(slot)
            node = nxt
        for parent, t, child in reversed(chain):
            if not child.slots and not child.kids:
                del parent.kids[t]

    def evict_lru(self, candidates):
        """Forget the least-recently-used stored path among
        ``candidates`` (the engine's idle cached rows) and return its
        slot, or ``None`` when no candidate has a path."""
        pool = set(candidates)
        with self._lock:
            eligible = [s for s in self._paths if s in pool]
            if not eligible:
                return None
            victim = min(eligible, key=lambda s: self._stamp.get(s, 0))
            self._forget_locked(victim)
            self.evictions += 1
        _PREFIX_EVICTIONS.inc()
        return victim

    def stats(self):
        with self._lock:
            lookups = self.hits + self.misses
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "reuse_tokens": self.reuse_tokens,
                "stored_paths": len(self._paths),
                "hit_rate": (self.hits / lookups) if lookups else None,
            }
