"""Preallocated slot-based KV cache for continuous-batching decode.

The paged-attention insight (vLLM, Kwon et al. SOSP'23) applied at slot
granularity: the engine owns ONE device-resident cache pytree shaped
``[slots, max_len, heads, head_dim]`` per layer (the Flax "cache"
collection of ``models/gpt.py`` initialized at ``batch=slots``), and a
host-side free-slot allocator maps live sequences onto rows. Admitting
a sequence scatters its prefill cache into a free row; retiring one
just returns the row to the free list — no device work, because decode
correctness never reads a position that hasn't been written by the
CURRENT tenant:

- prefill overwrites the ENTIRE row ``[0:max_len]`` (the prefill cache
  from ``model.apply`` is full-length: prompt K/V in ``[0:prompt_len)``,
  zeros beyond), erasing any previous tenant, and
- the decode step at position ``i`` writes K/V at ``i`` BEFORE attending
  ``<= i``, so the zeros beyond the prompt are always replaced before
  they are ever attended.

Slot rows are therefore reused without zeroing, and the fused decode
step runs at a FIXED shape ``[slots, ...]`` whatever subset of rows is
live — membership churn costs a mask update, never a recompile.
"""

import threading

import jax


class SlotKvCache(object):
    """``slots`` preallocated cache rows + a free-slot allocator.

    The device arrays live in ``self.cache`` (a Flax "cache" pytree with
    leading dim ``slots``); the allocator is host-side and thread-safe.
    The device loop is the only writer of ``self.cache``; ``alloc`` /
    ``free`` only move slot ids between the free list and the live set.
    """

    def __init__(self, init_cache_fn, slots):
        if slots < 1:
            raise ValueError("need at least one slot, got %d" % slots)
        self.slots = int(slots)
        self.cache = init_cache_fn(self.slots)
        self._lock = threading.Lock()
        self._free = list(range(self.slots - 1, -1, -1))  # pop -> slot 0 first
        self._live = set()

    def alloc(self):
        """A free slot id, or ``None`` when fully occupied."""
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop()
            self._live.add(slot)
            return slot

    def free(self, slot):
        with self._lock:
            if slot not in self._live:
                raise ValueError("slot %d is not live" % slot)
            self._live.discard(slot)
            self._free.append(slot)

    @property
    def occupied(self):
        with self._lock:
            return len(self._live)

    @property
    def free_slots(self):
        with self._lock:
            return len(self._free)

    def live(self):
        with self._lock:
            return sorted(self._live)

    def bytes(self):
        return sum(leaf.size * leaf.dtype.itemsize
                   for leaf in jax.tree_util.tree_leaves(self.cache))
