"""SLO-guarded serving plane for the teacher fleet.

The robustness layer between raw traffic and the distill data plane
(docs/distill_dataplane.md §"The serving plane"):

- :mod:`~edl_tpu.serve.admission` — bounded admission queue, token
  -bucket rate limiting, and queue-wait-projection load shedding in
  front of :class:`~edl_tpu.distill.teacher_server.TeacherServer`;
  sheds are a typed :class:`~edl_tpu.utils.errors.OverloadedError`
  with a retry-after hint, never a timeout pile-up.
- :mod:`~edl_tpu.serve.scaler` — the leader-hosted SLO-driven
  autoscaler (journaled ``action/v1`` records, off|dry|on modes,
  cooldowns + hysteresis).
- :mod:`~edl_tpu.serve.drain` — the drain-safe decommission protocol:
  stop advertising → let the discovery TTL lapse → finish in-flight
  work → exit, with zero stranded requests.
- :mod:`~edl_tpu.serve.decode_engine` + :mod:`~edl_tpu.serve.kv_cache`
  — the autoregressive plane: slot-based KV cache with continuous
  batching at decode-step granularity, fronted by per-phase admission
  (:class:`~edl_tpu.serve.admission.DecodeAdmission`: TTFT projection
  for prefill, ITL + slot occupancy for decode).

Fault points ``serve.admit`` / ``serve.drain`` / ``serve.decode.step``
put all three halves under seeded chaos (docs/fault_tolerance.md).
"""

from edl_tpu.serve.admission import AdmissionController, DecodeAdmission
from edl_tpu.serve.decode_engine import DecodeEngine
from edl_tpu.serve.drain import decommission
from edl_tpu.serve.kv_cache import SlotKvCache
from edl_tpu.serve.scaler import ServeScaler, load_actions

__all__ = ["AdmissionController", "DecodeAdmission", "DecodeEngine",
           "ServeScaler", "SlotKvCache", "decommission", "load_actions"]
