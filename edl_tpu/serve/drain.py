"""Drain-safe teacher decommission: zero stranded requests by protocol.

The scale-in actuator. Order matters, and each step exists to close
one loss window:

1. **Stop advertising** — ``register.drain()`` revokes the TTL lease
   and never re-registers, so discovery stops handing the endpoint to
   NEW clients immediately.
2. **Let the discovery TTL lapse** — clients that already hold the
   endpoint keep it until their next table refresh; waiting out the
   TTL (plus one heartbeat) means no client still routes here when
   admission closes.
3. **Finish in-flight work** — ``teacher.drain()`` flips admission to
   ``draining`` (new predicts get a typed OverloadedError the reader
   requeues elsewhere — a race with a stale table loses nothing) and
   waits for the device queue and every admitted row to resolve.
4. **Exit** — ``teacher.stop()`` tears the RPC server down only after
   the queue is provably empty.

The ``serve.drain`` fault point fires inside ``teacher.drain()``
(teacher_server.py), so a chaos drill hits the real drain path; the
teacher-kill-mid-predict drill (tests/test_serve.py) SIGKILL-semantics
-stops the server instead and asserts the reader's requeue still
loses zero predicts — the protocol is the optimization, the reader's
delivery guarantee is the backstop.
"""

from edl_tpu.robustness.policy import Deadline
from edl_tpu.utils.logger import logger


def decommission(teacher, register=None, ttl_s=0.0, deadline_s=30.0):
    """Run the four-step drain protocol. Returns the teacher's drain
    report (``{"drained": bool, "pending_rows": int, ...}``) with the
    protocol steps annotated. Raises nothing on a slow drain — a
    ``drained: False`` report is the caller's signal that in-flight
    work outlived ``deadline_s`` (the journaled outcome, not an
    exception mid-actuator)."""
    deadline = Deadline(deadline_s)
    endpoint = teacher.endpoint
    if register is not None:
        register.drain()
    if ttl_s:
        # step 2: wait out the discovery TTL so no live table names us
        Deadline(min(float(ttl_s), deadline.remaining() or float(ttl_s))
                 ).sleep(float(ttl_s))
    report = teacher.drain(deadline_s=deadline.remaining(cap=deadline_s))
    teacher.stop()
    report["ttl_waited_s"] = float(ttl_s)
    report["advertised"] = register is not None
    logger.info("decommissioned teacher %s: %r", endpoint, report)
    return report
