"""Serialized, target-specific `make` for the in-tree native components.

Multiple trainer processes starting on one VM all try to ensure their
native binaries at import time; two concurrent compilers writing the
same output file produce a truncated binary/library. An exclusive flock
on a per-directory lockfile serializes them (the losers find the target
up to date), and building the SPECIFIC target keeps an unrelated
component's compile error from blocking this one.
"""

import fcntl
import os
import subprocess

from edl_tpu.utils.logger import logger


def locked_make(native_dir, target, what="native component"):
    lock_path = os.path.join(native_dir, ".build.lock")
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            result = subprocess.run(["make", target], cwd=native_dir,
                                    check=True, capture_output=True,
                                    text=True)
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)
    if "up to date" not in result.stdout:
        logger.info("built %s in %s", what, native_dir)
    return os.path.join(native_dir, target)
