"""Force a virtual n-device CPU platform for hermetic multi-chip tests.

The axon TPU plugin's sitecustomize force-selects platform "axon" when
PALLAS_AXON_POOL_IPS is set, overriding $JAX_PLATFORMS; and XLA only
honours --xla_force_host_platform_device_count before backends
initialize.  Both tests/conftest.py and the driver-facing
``__graft_entry__.dryrun_multichip`` need the same recipe, so it lives
here (no jax import — callers must apply it before jax initializes).
"""


def force_cpu_env(env, n_devices):
    """Mutate ``env`` (a dict, e.g. os.environ or a subprocess env copy)
    so that a fresh Python process sees ``n_devices`` virtual CPU devices
    and never registers the axon TPU plugin. Returns ``env``."""
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=%d" % n_devices)
    env["XLA_FLAGS"] = " ".join(flags)
    return env
