"""Unique id generation (reference parity: edl/utils/unique_name.py)."""

import itertools
import threading
import uuid

_lock = threading.Lock()
_counters = {}


def generate(prefix=""):
    """Monotonic per-prefix counter name, e.g. generate("reader") -> reader_0."""
    with _lock:
        c = _counters.setdefault(prefix, itertools.count())
        return "%s_%d" % (prefix, next(c))


def uid():
    return uuid.uuid4().hex
