"""Env-gated stopwatch profiling + jax.profiler trace helper.

Reference parity: edl/distill/timeline.py:20-46 — a Nop/Real stopwatch pair
switched by an env var, recording per-pid op latencies to stderr. Here the
stopwatch is backed by the unified metrics registry (``edl_timeline_op_ms``
histogram, labeled by op) so timeline spans land on the same fleet
snapshot as every other metric; EDL_TPU_PROFILE=1 (or the reference's
DISTILL_READER_PROFILE=1) additionally keeps the legacy stderr line sink.
jax_trace() adds the TPU-native path: a jax.profiler trace context
writing TensorBoard-readable dumps.

The environment is read ONCE, at first :func:`get_timeline` call; the
instance is cached per process (hot loops used to re-read os.environ on
every construction). Tests that flip the env call :func:`reset`.
"""

import contextlib
import os
import sys
import time

from edl_tpu.obs import metrics as obs_metrics

_OP_MS = obs_metrics.histogram(
    "edl_timeline_op_ms", "env-gated stopwatch span latencies",
    labels=("op",))

_cached = None


class TimeLine(object):
    """Registry-backed stopwatch. ``verbose`` adds the legacy
    ``[timeline] pid= op= ms=`` stderr lines (the profile-env sink)."""

    def __init__(self, verbose=False, out=None):
        self._pid = os.getpid()
        self._last = time.monotonic()
        self._verbose = verbose
        self._out = out or sys.stderr

    def _emit(self, op, ms):
        _OP_MS.labels(op).observe(ms)
        if self._verbose:
            self._out.write("[timeline] pid=%d op=%s ms=%.3f\n"
                            % (self._pid, op, ms))

    def record(self, op):
        """Lap timer: time since the previous record()."""
        now = time.monotonic()
        self._emit(op, (now - self._last) * 1000)
        self._last = now

    @contextlib.contextmanager
    def span(self, op):
        t0 = time.monotonic()
        try:
            yield
        finally:
            self._emit(op, (time.monotonic() - t0) * 1000)


# legacy aliases: pre-registry callers constructed these directly
_RealTimeLine = TimeLine


class _NopTimeLine(TimeLine):
    """Kept for API compatibility; records to the registry like every
    timeline now (near-zero cost, and EDL_TPU_OBS=0 disables it), just
    never to stderr."""


def enabled():
    return (os.environ.get("EDL_TPU_PROFILE") == "1"
            or os.environ.get("DISTILL_READER_PROFILE") == "1")


def get_timeline(out=None):
    """The process's shared timeline (env read once, instance cached).
    Passing ``out`` bypasses the cache — explicit sinks are for tests."""
    global _cached
    if out is not None:
        return TimeLine(verbose=True, out=out)
    if _cached is None:
        _cached = TimeLine(verbose=True) if enabled() else _NopTimeLine()
    return _cached


def reset():
    """Drop the cached timeline so the next get_timeline() re-reads the
    environment (test hook)."""
    global _cached
    _cached = None


@contextlib.contextmanager
def jax_trace(logdir=None):
    """jax.profiler trace context, active iff EDL_TPU_PROFILE_DIR (or the
    ``logdir`` arg) is set — the TPU-native replacement for the reference's
    Paddle profiler window (train_with_fleet.py:521-530)."""
    logdir = logdir or os.environ.get("EDL_TPU_PROFILE_DIR")
    if not logdir:
        yield
        return
    import jax
    with jax.profiler.trace(logdir):
        yield
