"""Env-gated stopwatch profiling + jax.profiler trace helper.

Reference parity: edl/distill/timeline.py:20-46 — a Nop/Real stopwatch pair
switched by an env var, recording per-pid op latencies to stderr. Here the
switch is EDL_TPU_PROFILE=1 (and the distill plane also accepts the
reference's DISTILL_READER_PROFILE=1). jax_trace() adds the TPU-native
path: a jax.profiler trace context writing TensorBoard-readable dumps.
"""

import contextlib
import os
import sys
import time


class _NopTimeLine(object):
    def record(self, op):
        pass

    @contextlib.contextmanager
    def span(self, op):
        yield


class _RealTimeLine(object):
    def __init__(self, out=None):
        self._pid = os.getpid()
        self._last = time.monotonic()
        self._out = out or sys.stderr

    def record(self, op):
        now = time.monotonic()
        self._out.write("[timeline] pid=%d op=%s ms=%.3f\n"
                        % (self._pid, op, (now - self._last) * 1000))
        self._last = now

    @contextlib.contextmanager
    def span(self, op):
        t0 = time.monotonic()
        try:
            yield
        finally:
            self._out.write("[timeline] pid=%d op=%s ms=%.3f\n"
                            % (self._pid, op,
                               (time.monotonic() - t0) * 1000))


def enabled():
    return (os.environ.get("EDL_TPU_PROFILE") == "1"
            or os.environ.get("DISTILL_READER_PROFILE") == "1")


def get_timeline(out=None):
    return _RealTimeLine(out) if enabled() else _NopTimeLine()


@contextlib.contextmanager
def jax_trace(logdir=None):
    """jax.profiler trace context, active iff EDL_TPU_PROFILE_DIR (or the
    ``logdir`` arg) is set — the TPU-native replacement for the reference's
    Paddle profiler window (train_with_fleet.py:521-530)."""
    logdir = logdir or os.environ.get("EDL_TPU_PROFILE_DIR")
    if not logdir:
        yield
        return
    import jax
    with jax.profiler.trace(logdir):
        yield
