"""Network helpers: free-port finder and TCP liveness probe.

Reference parity: edl/utils/network_utils.py:29 (find_free_ports) and
edl/discovery/server_alive.py:19-34 (is_server_alive).
"""

import contextlib
import socket


def get_host_ip():
    try:
        with contextlib.closing(
                socket.socket(socket.AF_INET, socket.SOCK_DGRAM)) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def find_free_port():
    with contextlib.closing(socket.socket(socket.AF_INET,
                                          socket.SOCK_STREAM)) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("", 0))
        return s.getsockname()[1]


def find_free_ports(n):
    ports = set()
    while len(ports) < n:
        ports.add(find_free_port())
    return list(ports)


def is_server_alive(endpoint, timeout=3.0):
    """True iff a TCP connect to "host:port" succeeds within timeout."""
    host, port = endpoint.rsplit(":", 1)
    try:
        with contextlib.closing(
                socket.create_connection((host, int(port)), timeout=timeout)):
            return True
    except OSError:
        return False
