"""Reflection-based JSON codec for plain-attribute model objects.

Reference parity: edl/utils/json_serializable.py:26 (Serializable). Objects
round-trip through ``to_json``/``from_json`` by reflecting over ``__dict__``;
nested Serializable members and lists of them are handled recursively via a
``_json_types`` hint: {attr_name: cls} or {attr_name: [cls]} for lists.
"""

import json


class Serializable(object):
    _json_types = {}

    def to_dict(self):
        out = {}
        for k, v in self.__dict__.items():
            if isinstance(v, Serializable):
                out[k] = v.to_dict()
            elif isinstance(v, (list, tuple)) and v and isinstance(
                    v[0], Serializable):
                out[k] = [x.to_dict() for x in v]
            else:
                out[k] = v
        return out

    def from_dict(self, d):
        for k, v in d.items():
            hint = self._json_types.get(k)
            if hint is None:
                setattr(self, k, v)
            elif isinstance(hint, list):
                setattr(self, k, [hint[0]().from_dict(x) for x in v])
            else:
                setattr(self, k, hint().from_dict(v))
        return self

    def to_json(self):
        return json.dumps(self.to_dict(), sort_keys=True)

    def from_json(self, s):
        return self.from_dict(json.loads(s))

    def __eq__(self, other):
        return type(self) is type(other) and self.to_dict() == other.to_dict()

    def __ne__(self, other):
        return not self.__eq__(other)

    def __str__(self):
        return self.to_json()
