"""Exception taxonomy and the retry-until-timeout resilience idiom.

Reference parity: edl/utils/exceptions.py (17 Edl* types, serialize/deserialize
by class name) and edl/utils/error_utils.py:22-39 (@handle_errors_until_timeout).
"""

import functools
import re as _re


class EdlError(Exception):
    """Base class for all framework errors; retryable by default."""


class DeserializeError(EdlError):
    pass


class ConnectError(EdlError):
    pass


class RpcError(EdlError):
    pass


class NotFoundError(EdlError):
    pass


class LeaseExpiredError(EdlError):
    pass


class KeyExistsError(EdlError):
    pass


class TxnFailedError(EdlError):
    pass


class NotLeaderError(EdlError):
    pass


class BarrierError(EdlError):
    pass


class JobFailedError(EdlError):
    """The job was marked FAILED while this actor was waiting on it."""
    pass


class ClusterChangedError(EdlError):
    pass


class RankError(EdlError):
    pass


class StatusError(EdlError):
    pass


class TrainProcessError(EdlError):
    pass


class DataAccessError(EdlError):
    pass


class FeedSpecError(DataAccessError):
    """A predict feed violates the teacher's declared spec (missing
    feed, batch mismatch, empty, over max_batch). Subclass of
    DataAccessError so the reader's poisoned-task path surfaces it to
    the consumer in order instead of retrying a permanently bad feed.
    ``spec``/``shape`` name the offending feed; both are folded into
    the message so they survive the wire (only the message string is
    serialized)."""

    def __init__(self, message, spec=None, shape=None):
        if spec is not None:
            message = "%s [spec=%s shape=%s]" % (message, spec, shape)
        super(FeedSpecError, self).__init__(message)
        self.spec = spec if spec is not None else self._parse("spec")
        self.shape = shape if shape is not None else self._parse("shape")

    def _parse(self, field):
        # rebuilt from the wire: recover the field from the message
        m = _re.search(r"\[spec=(\S+) shape=(.*?)\]$", str(self))
        if m is None:
            return None
        return m.group(1) if field == "spec" else m.group(2)


class OverloadedError(EdlError):
    """The serving tier shed this request — admission queue full, rate
    limited, past its deadline, projected queue wait over the SLO, or
    the server is draining. Retryable AGAINST ANOTHER SERVER: the
    reader requeues the task and opens the endpoint's breaker so it
    backs off instead of hammering. Carries a ``retry_after_s=`` hint
    in the message (messages are all that survive serialization)."""

    @classmethod
    def shed(cls, reason, retry_after_s=None):
        msg = "overloaded: %s" % reason
        if retry_after_s is not None:
            msg += " (retry_after_s=%.3f)" % max(0.0, retry_after_s)
        return cls(msg)

    @property
    def retry_after_s(self):
        m = _re.search(r"retry_after_s=([0-9.]+)", str(self))
        if m is None:
            return None
        try:
            return float(m.group(1))
        except ValueError:
            return None


class DecodeStepError(EdlError):
    """A fused decode step failed for this sequence (device fault mid-
    generation). The sequence's slot has been freed and its partial
    output discarded; the engine itself keeps running — only the
    sequences that were active in the faulted step see this error.
    Retryable by resubmitting the prompt (generation restarts from the
    prefill; there is no partial-state resume)."""


class DataEndError(EdlError):
    """All data has been consumed for this epoch."""


class StopError(EdlError):
    """A component was asked to stop; not retryable."""


class TimeoutError_(EdlError):
    """Raised when handle_errors_until_timeout gives up."""


class DeadlineExceededError(TimeoutError_):
    """A Deadline budget (edl_tpu.robustness.policy) ran out. Subclass
    of TimeoutError_ so existing timeout handling catches it."""


class CircuitOpenError(EdlError):
    """A CircuitBreaker is open for the target endpoint; the call was
    refused without touching the wire."""


class PreemptedError(EdlError):
    """The trainer was preempted (SIGTERM) and saved an emergency
    checkpoint; the process should exit so the restart resumes from it."""


class StaleStateError(EdlError):
    """A peer StateServer no longer holds the requested snapshot version
    (a newer save superseded it mid-fetch). The fetcher drops the peer
    and falls back — alternates first, then the shared FS."""


class PeerRestoreError(EdlError):
    """No usable peer path for a placed restore (no live peers, none at
    the requested version, or the FS per-span fallback is unavailable);
    the caller restores wholesale from the shared FS."""


class RedundancyError(EdlError):
    """The erasure-coded parity rung could not rebuild the requested
    state (no live holders, insufficient/stale shards, decode
    failure). Carries a ``reason`` attribute when known (stale_version,
    insufficient_partners); the caller falls through to the FS rung —
    the parity tier is strictly best-effort."""


class EmbedLookupError(EdlError):
    """A sharded embedding gather could not complete after the retry
    budget (owner dead, persistent fault, or a shape-corrupt response
    — a short/zero-row answer is promoted to this error, NEVER padded
    with silent zeros). The training step that needed the rows fails
    loudly instead of learning on fabricated embeddings."""


class EmbedWritebackError(EdlError):
    """A sparse embedding optimizer write-back could not be applied
    after the retry budget. The owner either applied the update or
    never saw it (the writeback RPC is one fused subtract); the caller
    must treat the step as failed rather than proceed with the table
    and cache divergent."""


class LiveResizeError(EdlError):
    """The in-place live resize could not complete (out of scope,
    drain/reshard failure, rolled back). The trainer is left on its
    OLD mesh, numerically untouched; the caller falls back to the
    stop-resume ladder."""


_NAME_TO_CLS = None


def _name_to_cls():
    global _NAME_TO_CLS
    if _NAME_TO_CLS is None:
        _NAME_TO_CLS = {
            c.__name__: c for c in list(globals().values())
            if isinstance(c, type) and issubclass(c, EdlError)
        }
    return _NAME_TO_CLS


def serialize_error(exc):
    """Encode an exception as (class_name, detail) for the RPC envelope."""
    return type(exc).__name__, str(exc)


def deserialize_error(name, detail):
    """Rebuild an exception from its class name; unknown names → RpcError."""
    cls = _name_to_cls().get(name)
    if cls is None:
        return RpcError("%s: %s" % (name, detail))
    return cls(detail)


def handle_errors_until_timeout(func):
    """Retry ``func`` on EdlError every ``interval`` seconds until ``timeout``.

    The wrapped function must be called with a ``timeout`` kwarg (seconds);
    optional ``interval`` kwarg (default 1s). StopError is never retried.
    Mirrors the universal resilience idiom of the reference
    (edl/utils/error_utils.py:22-39, which used a 3s fixed interval).
    """

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        # local import: robustness.policy imports this module
        from edl_tpu.robustness.policy import Deadline, RetryPolicy
        timeout = kwargs.pop("timeout")
        interval = kwargs.pop("interval", 1.0)
        policy = RetryPolicy(base_delay=interval, max_delay=interval,
                             multiplier=1.0, jitter=0.25)
        deadline = Deadline(timeout)
        attempt = 0
        while True:
            attempt += 1
            try:
                return func(*args, **kwargs)
            except StopError:
                raise
            except EdlError as e:
                if deadline.expired():
                    raise TimeoutError_(
                        "%s timed out after %ss; last error: %r"
                        % (func.__name__, timeout, e))
                # clipped to the remaining budget; one final attempt
                # runs after the last (possibly shortened) backoff
                policy.sleep(attempt, deadline)

    return wrapper
