"""Uniform logging setup (reference parity: edl/utils/log_utils.py:21-33)."""

import logging
import os
import sys

_FMT = "[%(asctime)s %(levelname)s %(process)d %(filename)s:%(lineno)d] %(message)s"


def get_logger(name="edl_tpu", level=None, log_file=None):
    level = level or os.environ.get("EDL_TPU_LOG_LEVEL", "INFO")
    logger = logging.getLogger(name)
    if getattr(logger, "_edl_configured", False):
        return logger
    logger.setLevel(getattr(logging, str(level).upper(), logging.INFO))
    handler = (logging.FileHandler(log_file, mode="a")
               if log_file else logging.StreamHandler(sys.stderr))
    handler.setFormatter(logging.Formatter(_FMT))
    logger.addHandler(handler)
    logger.propagate = False
    logger._edl_configured = True
    return logger


logger = get_logger()
