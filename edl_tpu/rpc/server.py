"""Threaded RPC server: method-name dispatch over framed msgpack TCP.

Replaces the reference's three gRPC services (PodServer, DataServer,
DiscoveryService — protos/*.proto) and its raw epoll server with one
substrate. Handlers raise EdlError subclasses; the error envelope carries the
class name so clients re-raise the same type (reference parity:
edl/utils/exceptions.py:93-114 serialize/deserialize).

Pipelining: a request whose envelope carries ``"pl": 1`` announces that
its sender matches responses by id and tolerates out-of-order replies.
Those requests are dispatched to a bounded worker pool and their
responses written whenever they finish, under a per-connection write
lock so frames never interleave. Requests without the flag (every
pre-pipelining client) are served inline on the connection thread —
strict request-reply order, byte-for-byte the old behavior. Servers
advertise the capability via the auto-registered ``__features__``
method (and the teacher server mirrors it into ``get_feed_fetch``).
"""

import os
import socket
import socketserver
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from edl_tpu.obs import events as obs_events
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace as obs_trace
from edl_tpu.robustness import faults
from edl_tpu.rpc import framing
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger

#: capabilities every in-tree server advertises through __features__.
#: obs.trace: requests may carry a ``"tr": [trace_id, span_id]`` header
#: and the dispatch runs under a server span adopting it as parent.
#: obs.metrics: the ``__metrics__`` method serves this process's
#: registry snapshot / Prometheus text.
#: obs.profile: the ``__profile__`` method captures an on-demand
#: chrome-trace window (jax.profiler when available, else the tracer
#: ring) — ``job_doctor --profile`` fans it out fleet-wide.
FEATURES = ("rpc.pipeline", "obs.trace", "obs.metrics", "obs.profile")

_REQS = obs_metrics.counter(
    "edl_rpc_server_requests_total", "requests dispatched",
    labels=("method",))
_ERRS = obs_metrics.counter(
    "edl_rpc_server_errors_total", "requests answered with an error "
    "envelope", labels=("method",))
_HANDLE_MS = obs_metrics.histogram(
    "edl_rpc_server_handle_ms", "request wall time: dequeue to "
    "response written", labels=("method",))
_INFLIGHT = obs_metrics.gauge(
    "edl_rpc_server_inflight", "requests currently executing")

# per-connection cap on pooled requests in flight: when a client
# pipelines deeper than this the read loop stops pulling frames and TCP
# backpressure does the rest — one flooding connection cannot occupy
# the whole worker pool
MAX_CONN_INFLIGHT = 32


def uds_path_for_port(port):
    """Conventional AF_UNIX path for a server's TCP port: same-host
    clients auto-dial it (kernel loopback TCP measured 997 MB/s vs UDS
    1381 MB/s on the v2 tensor-frame path, r5). uid-scoped so multiple
    users can't collide; the file itself is chmod 0600."""
    return "/tmp/edl_tpu_rpc_%d_%d.sock" % (os.getuid(), port)


def _metrics_method(fmt="json", events_since=0):
    """Auto-registered ``__metrics__``: this process's observability
    surface. ``fmt="prom"`` returns Prometheus text exposition;
    ``fmt="json"`` returns the registry snapshot plus the event
    timeline (incrementally, via ``events_since`` id watermark)."""
    if fmt == "prom":
        return obs_metrics.REGISTRY.prometheus_text()
    return {"metrics": obs_metrics.REGISTRY.snapshot(),
            "events": obs_events.EVENTS.snapshot(since_id=events_since)}


#: cap on trace events shipped per __profile__ response: a busy device
#: window can emit hundreds of thousands; the RPC reply must stay
#: deliverable through the framing limits
MAX_PROFILE_EVENTS = 20000

#: cap on the requested capture window
MAX_PROFILE_S = 60.0


def _try_jax_profile(duration_s):
    """Capture ``duration_s`` of ``jax.profiler`` activity into a temp
    dir and parse the chrome trace back out. Returns the trace dict or
    None wherever any part is unavailable (no jax, no profiler plugin,
    no trace file emitted) — callers fall back to the tracer ring."""
    import glob
    import gzip
    import shutil
    import tempfile
    try:
        import jax
        tmp = tempfile.mkdtemp(prefix="edl_profile_")
        try:
            jax.profiler.start_trace(tmp)
            time.sleep(duration_s)
            jax.profiler.stop_trace()
            paths = sorted(glob.glob(
                os.path.join(tmp, "**", "*.trace.json.gz"),
                recursive=True))
            if not paths:
                return None
            with gzip.open(paths[-1], "rt") as f:
                import json
                doc = json.load(f)
            events = doc.get("traceEvents") or []
            if len(events) > MAX_PROFILE_EVENTS:
                events = events[:MAX_PROFILE_EVENTS]
            return {"traceEvents": events, "displayTimeUnit": "ms"}
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    except BaseException as e:  # noqa: BLE001 — any failure => fallback
        logger.debug("jax.profiler capture unavailable: %r", e)
        return None


def _profile_method(duration_s=2.0, source="auto"):
    """Auto-registered ``__profile__``: on-demand profiling of THIS
    process. ``source``: "auto" tries ``jax.profiler`` first and falls
    back to the span tracer's ring; "tracer" skips straight to the
    ring (cheap — no device profiling session). Returns a
    ``profile/v1`` doc whose ``trace`` is chrome-trace JSON either
    way, so ``job_doctor --profile`` merges pods into one Perfetto
    file without caring which path answered."""
    duration_s = max(0.0, min(float(duration_s), MAX_PROFILE_S))
    trace = None
    used = "tracer_ring"
    if source == "auto":
        trace = _try_jax_profile(duration_s)
        if trace is not None:
            used = "jax.profiler"
    if trace is None:
        # ring fallback: wait out the window so activity DURING it is
        # in the ring, then snapshot (older spans ride along — the
        # ring is bounded, not windowed)
        if duration_s > 0:
            time.sleep(duration_s)
        trace = obs_trace.TRACER.chrome_trace()
    return {"schema": "profile/v1", "ts": time.time(),
            "pid": os.getpid(), "duration_s": duration_s,
            "source": used, "trace": trace}


def _default_workers():
    env = os.environ.get("EDL_TPU_RPC_WORKERS")
    if env is not None:
        return int(env)
    return min(16, (os.cpu_count() or 4) * 2)


class _Handler(socketserver.BaseRequestHandler):
    def setup(self):
        self.server.connections.add(self.request)

    def finish(self):
        self.server.connections.discard(self.request)

    def handle(self):
        framing.set_keepalive(self.request)
        if faults.PLANE is not None:
            # accept-path chaos: a drop here severs the fresh connection
            # before any request is served (error/delay act in fire())
            f = faults.PLANE.fire("rpc.server.conn")
            if f is not None:
                return
        wlock = threading.Lock()  # at most one frame mid-write per conn
        sem = threading.BoundedSemaphore(MAX_CONN_INFLIGHT)
        pool = self.server.pool
        while True:
            try:
                req = framing.read_frame(self.request)
            except (ConnectionError, OSError, framing.FramingError):
                return
            if req.get("pl") and pool is not None:
                sem.acquire()
                try:
                    pool.submit(self._serve_pooled, req, wlock, sem)
                    continue
                except RuntimeError:  # pool shut down mid-stop
                    sem.release()
            if not self._serve_one(req, wlock):
                return

    def _serve_pooled(self, req, wlock, sem):
        try:
            # a dead connection surfaces as a write failure inside
            # _serve_one; the read loop notices on its own recv
            self._serve_one(req, wlock)
        finally:
            sem.release()

    def _serve_one(self, req, wlock):
        """Execute one request and write its response; False means the
        connection is gone and the read loop should exit."""
        resp = {"id": req.get("id")}
        t0 = time.monotonic()
        _INFLIGHT.inc()
        try:
            method = req["method"]
            if faults.PLANE is not None:
                # inside the try: an injected error comes back to the
                # client as a typed error envelope for that method
                f = faults.PLANE.fire("rpc.server.request",
                                      method=method)
                if f is not None and f.kind == "drop":
                    return True  # swallow: the client waits until timeout
            fn = self.server.methods.get(method)
            if fn is None:
                raise errors.RpcError("no such method: %s" % method)
            resp["ok"] = True
            # the server span adopts the envelope's trace header as
            # parent and activates the context, so a nested RPC issued
            # inside the handler carries the same trace onward
            with obs_trace.server_span("rpc/%s" % method,
                                       req.get("tr")):
                resp["result"] = fn(*req.get("args", []),
                                    **req.get("kwargs", {}))
        except Exception as e:  # noqa: BLE001 — envelope every failure
            if not isinstance(e, errors.EdlError):
                logger.exception("rpc handler %s failed",
                                 req.get("method"))
            name, detail = errors.serialize_error(e)
            resp["ok"] = False
            resp["error"] = {"name": name, "detail": detail}
            _ERRS.labels(str(req.get("method"))).inc()
        finally:
            _INFLIGHT.dec()
            method_lbl = str(req.get("method"))
            _REQS.labels(method_lbl).inc()
            _HANDLE_MS.labels(method_lbl).observe(
                (time.monotonic() - t0) * 1e3)
        try:
            with wlock:
                try:
                    framing.write_frame(self.request, resp)
                except (TypeError, ValueError, framing.FramingError) as e:
                    # result not wire-encodable → error envelope, keep
                    # the connection (packb fails before any byte is
                    # sent, so the stream cannot be torn mid-frame)
                    framing.write_frame(self.request, {
                        "id": resp.get("id"), "ok": False,
                        "error": {"name": "RpcError",
                                  "detail": "unencodable response: %s"
                                  % e}})
        except (ConnectionError, OSError):
            return False
        return True


class _TCPServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True
    request_queue_size = 128

    def __init__(self, *a, **k):
        super().__init__(*a, **k)
        self.connections = set()
        self.pool = None


if hasattr(socketserver, "ThreadingUnixStreamServer"):
    class _UDSServer(socketserver.ThreadingUnixStreamServer):
        daemon_threads = True
        request_queue_size = 128

        def __init__(self, *a, **k):
            super().__init__(*a, **k)
            self.connections = set()
            self.pool = None
else:  # non-POSIX: TCP only
    _UDSServer = None


class RpcServer(object):
    """Register callables by name, serve them on host:port.

    port=0 picks a free port; the bound port is available as ``.port`` after
    ``start()`` (reference parity: pod_server started on port 0 then wrote the
    real port back into the pod — edl/utils/pod_server.py:130-147).

    ``workers``: size of the pooled-dispatch executor for pipelined
    requests (default: EDL_TPU_RPC_WORKERS or 2×cores capped at 16;
    0 disables pooling — every request is served inline in strict
    request-reply order, the pre-pipelining behavior).
    """

    def __init__(self, host="0.0.0.0", port=0, workers=None):
        self._host = host
        self._port = port
        self._server = None
        self._thread = None
        self._pool = None
        self._workers = _default_workers() if workers is None else workers
        self.methods = {}
        self.register("__features__", lambda: list(FEATURES))
        self.register("__identity__", self._identity)
        self.register("__metrics__", _metrics_method)
        self.register("__profile__", _profile_method)

    def _identity(self):
        """Who answers on this listener: the bind host + bound TCP
        port. UDS paths are keyed by port number alone, so two servers
        bound to distinct addresses sharing a port number collide on
        the socket path — clients probe this after a UDS connect and
        fall back to TCP when the answer isn't the server they dialed."""
        return {"host": self._host, "port": self.port}

    def register(self, name, fn):
        self.methods[name] = fn
        return self

    def register_object(self, obj, prefix=""):
        """Expose every public method of ``obj`` as ``prefix + name``."""
        for name in dir(obj):
            if name.startswith("_"):
                continue
            fn = getattr(obj, name)
            if callable(fn):
                self.register(prefix + name, fn)
        return self

    def start(self):
        if self._workers > 0:
            self._pool = ThreadPoolExecutor(
                max_workers=self._workers,
                thread_name_prefix="rpc-worker")
        self._server = _TCPServer((self._host, self._port), _Handler)
        self._server.methods = self.methods
        self._server.pool = self._pool
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.1},
            daemon=True, name="rpc-server")
        self._thread.start()
        self._start_uds()
        return self

    def _start_uds(self):
        """Best-effort same-host fast path: a second listener on the
        conventional AF_UNIX path for our TCP port. Failure never
        blocks the TCP server."""
        self._uds_server = None
        self._uds_path = None
        self._uds_lock_fd = None
        if _UDSServer is None or os.environ.get("EDL_TPU_DISABLE_UDS"):
            return
        path = uds_path_for_port(self.port)
        # Sidecar lockfile closes the probe→unlink→bind TOCTOU: two
        # servers can legitimately race for one path (distinct bind
        # addresses share a port number), and between our liveness
        # probe and our bind the other could unlink the file we just
        # created. flock is advisory but both racers are THIS code, so
        # whoever holds the lock owns the path for its lifetime. The
        # lockfile is never unlinked (unlink+recreate would hand out a
        # second lockable inode and resurrect the race).
        lock_fd = None
        try:
            import fcntl
            lock_fd = os.open(path + ".lock",
                              os.O_CREAT | os.O_RDWR, 0o600)
            fcntl.flock(lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except (OSError, ImportError) as e:
            if lock_fd is not None:
                os.close(lock_fd)
            logger.warning("uds path %s lock held elsewhere (%r); "
                           "tcp only", path, e)
            return
        # A LIVE listener may still own the path without holding the
        # lock (pre-lockfile server generations). Probe-connect —
        # only a dead (stale) socket may be unlinked and taken.
        if os.path.lexists(path):
            probe = socket.socket(socket.AF_UNIX)
            try:
                probe.settimeout(1.0)
                probe.connect(path)
                logger.warning("uds path %s owned by a live server; "
                               "tcp only", path)
                os.close(lock_fd)
                return
            except OSError:
                pass  # stale — safe to take
            finally:
                probe.close()
        srv = None
        # umask, not post-bind chmod: the listener accepts connections
        # the moment bind+listen complete inside __init__, so the file
        # must never exist with permissive bits
        old_umask = os.umask(0o177)
        try:
            if os.path.lexists(path):
                os.unlink(path)
            srv = _UDSServer(path, _Handler)
            srv.methods = self.methods
            srv.pool = self._pool
            self._uds_thread = threading.Thread(
                target=srv.serve_forever, kwargs={"poll_interval": 0.1},
                daemon=True, name="rpc-server-uds")
            self._uds_thread.start()
            self._uds_server = srv
            self._uds_path = path
            self._uds_lock_fd = lock_fd  # held until stop()
        except Exception as e:  # noqa: BLE001 — fast path is optional
            logger.warning("uds listener unavailable (%r); tcp only", e)
            if srv is not None:  # bound but thread never started
                try:
                    srv.server_close()
                    os.unlink(path)
                except OSError:
                    pass
            os.close(lock_fd)
        finally:
            os.umask(old_umask)

    @property
    def port(self):
        return self._server.server_address[1]

    @property
    def endpoint(self):
        host = self._host if self._host != "0.0.0.0" else "127.0.0.1"
        return "%s:%d" % (host, self.port)

    def stop(self):
        # UDS teardown FIRST: once TCP server_close releases the port,
        # a rapid successor can bind it and recreate the same socket
        # path — unlinking after that would delete the successor's
        # live fast-path file
        if getattr(self, "_uds_server", None) is not None:
            self._uds_server.shutdown()
            for sock in list(self._uds_server.connections):
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            self._uds_server.server_close()
            self._uds_server = None
            try:
                os.unlink(self._uds_path)
            except OSError:
                pass
        if getattr(self, "_uds_lock_fd", None) is not None:
            # releases the flock; the lockfile itself stays (see
            # _start_uds — unlinking it would reopen the bind race)
            os.close(self._uds_lock_fd)
            self._uds_lock_fd = None
        if self._server is not None:
            self._server.shutdown()
            # sever live connections so a stop behaves like a real process
            # death — clients must reconnect, not keep talking to a zombie
            for sock in list(self._server.connections):
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            self._server.server_close()
            self._server = None
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._pool = None
