"""Threaded RPC server: method-name dispatch over framed msgpack TCP.

Replaces the reference's three gRPC services (PodServer, DataServer,
DiscoveryService — protos/*.proto) and its raw epoll server with one
substrate. Handlers raise EdlError subclasses; the error envelope carries the
class name so clients re-raise the same type (reference parity:
edl/utils/exceptions.py:93-114 serialize/deserialize).
"""

import socket
import socketserver
import threading

from edl_tpu.rpc import framing
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger


class _Handler(socketserver.BaseRequestHandler):
    def setup(self):
        self.server.connections.add(self.request)

    def finish(self):
        self.server.connections.discard(self.request)

    def handle(self):
        framing.set_keepalive(self.request)
        while True:
            try:
                req = framing.read_frame(self.request)
            except (ConnectionError, OSError, framing.FramingError):
                return
            resp = {"id": req.get("id")}
            try:
                method = req["method"]
                fn = self.server.methods.get(method)
                if fn is None:
                    raise errors.RpcError("no such method: %s" % method)
                resp["ok"] = True
                resp["result"] = fn(*req.get("args", []),
                                    **req.get("kwargs", {}))
            except Exception as e:  # noqa: BLE001 — envelope every failure
                if not isinstance(e, errors.EdlError):
                    logger.exception("rpc handler %s failed",
                                     req.get("method"))
                name, detail = errors.serialize_error(e)
                resp["ok"] = False
                resp["error"] = {"name": name, "detail": detail}
            try:
                try:
                    framing.write_frame(self.request, resp)
                except (TypeError, ValueError, framing.FramingError) as e:
                    # result not wire-encodable → error envelope, keep
                    # the connection (packb fails before any byte is
                    # sent, so the stream cannot be torn mid-frame)
                    framing.write_frame(self.request, {
                        "id": resp.get("id"), "ok": False,
                        "error": {"name": "RpcError",
                                  "detail": "unencodable response: %s"
                                  % e}})
            except (ConnectionError, OSError):
                return


class _TCPServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True
    request_queue_size = 128

    def __init__(self, *a, **k):
        super().__init__(*a, **k)
        self.connections = set()


class RpcServer(object):
    """Register callables by name, serve them on host:port.

    port=0 picks a free port; the bound port is available as ``.port`` after
    ``start()`` (reference parity: pod_server started on port 0 then wrote the
    real port back into the pod — edl/utils/pod_server.py:130-147).
    """

    def __init__(self, host="0.0.0.0", port=0):
        self._host = host
        self._port = port
        self._server = None
        self._thread = None
        self.methods = {}

    def register(self, name, fn):
        self.methods[name] = fn
        return self

    def register_object(self, obj, prefix=""):
        """Expose every public method of ``obj`` as ``prefix + name``."""
        for name in dir(obj):
            if name.startswith("_"):
                continue
            fn = getattr(obj, name)
            if callable(fn):
                self.register(prefix + name, fn)
        return self

    def start(self):
        self._server = _TCPServer((self._host, self._port), _Handler)
        self._server.methods = self.methods
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.1},
            daemon=True, name="rpc-server")
        self._thread.start()
        return self

    @property
    def port(self):
        return self._server.server_address[1]

    @property
    def endpoint(self):
        host = self._host if self._host != "0.0.0.0" else "127.0.0.1"
        return "%s:%d" % (host, self.port)

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            # sever live connections so a stop behaves like a real process
            # death — clients must reconnect, not keep talking to a zombie
            for sock in list(self._server.connections):
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            self._server.server_close()
            self._server = None
