"""Wire framing for the in-tree RPC substrate.

Frame = 8-byte header (4 magic bytes + uint32 big-endian body length) followed
by a msgpack-encoded body. The magic doubles as a protocol-version check.

This replaces both gRPC and the reference's hand-rolled epoll TCP protocol
(reference: edl/distill/redis/balance_server.py:41-124 framed `!4si` + JSON);
msgpack is used instead of JSON so tensor batches can ride the same frames.
"""

import struct
import socket

import msgpack

MAGIC = b"\xed\x17\x00\x01"
_HEADER = struct.Struct("!4sI")
MAX_FRAME = 1 << 30  # 1 GB, matching the reference pod server's max message


class FramingError(Exception):
    pass


def _pack_body(obj):
    body = msgpack.packb(obj, use_bin_type=True)
    if len(body) > MAX_FRAME:
        raise FramingError("frame too large: %d" % len(body))
    return body


def pack_frame(obj):
    body = _pack_body(obj)
    return _HEADER.pack(MAGIC, len(body)) + body


def recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


def read_frame(sock):
    header = recv_exact(sock, _HEADER.size)
    magic, length = _HEADER.unpack(header)
    if magic != MAGIC:
        raise FramingError("bad magic %r" % magic)
    if length > MAX_FRAME:
        raise FramingError("frame too large: %d" % length)
    body = recv_exact(sock, length)
    return msgpack.unpackb(body, raw=False)


def write_frame(sock, obj):
    # vectored send: concatenating header+body (pack_frame) copies the
    # whole body, which for tensor batches is tens of MB per call —
    # measurable on the distill feed path (NOTES r5 distill curve).
    # sendmsg ships both buffers in ONE syscall/segment with no copy;
    # it may short-write, so drain any remainder without re-copying.
    body = _pack_body(obj)
    header = _HEADER.pack(MAGIC, len(body))
    sent = sock.sendmsg([header, body])
    total = len(header) + len(body)
    if sent < len(header):
        sock.sendall(header[sent:])
        sock.sendall(body)
    elif sent < total:
        sock.sendall(memoryview(body)[sent - len(header):])


def set_keepalive(sock):
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
