"""Wire framing for the in-tree RPC substrate.

Frame = 8-byte header (4 magic bytes + uint32 big-endian body length) followed
by a msgpack-encoded body. The magic doubles as a protocol-version check.

This replaces both gRPC and the reference's hand-rolled epoll TCP protocol
(reference: edl/distill/redis/balance_server.py:41-124 framed `!4si` + JSON);
msgpack is used instead of JSON so tensor batches can ride the same frames.
"""

import os
import struct
import socket

import msgpack
import numpy as np

from edl_tpu.robustness import faults

MAGIC = b"\xed\x17\x00\x01"
# v2 "tensor frame": ndarrays are stripped out of the msgpack body and
# shipped as RAW out-of-band segments vectored into the same sendmsg
# call, straight from the numpy buffers; the receiver recv_into()s
# preallocated arrays. No tobytes() copy, no 38 MB msgpack bin pack, no
# unpack copy — the single-teacher distill feed ceiling measured 243
# MB/s through v1 (r5 microbench) against a ~1.5 GB/s kernel loopback.
# Emitted ONLY when a payload contains ndarrays, so array-free peers
# (the C++ store pins v1's magic) never see it.
MAGIC_V2 = b"\xed\x17\x00\x02"
_HEADER = struct.Struct("!4sI")
MAX_FRAME = 1 << 30  # 1 GB, matching the reference pod server's max message
_ND_REF = "__ndref__"


class FramingError(Exception):
    pass


def _pack_body(obj):
    body = msgpack.packb(obj, use_bin_type=True)
    if len(body) > MAX_FRAME:
        raise FramingError("frame too large: %d" % len(body))
    return body


def pack_frame(obj):
    body = _pack_body(obj)
    return _HEADER.pack(MAGIC, len(body)) + body


def recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


def _recv_into(sock, view):
    while len(view):
        n = sock.recv_into(view)
        if n == 0:
            raise ConnectionError("peer closed connection")
        view = view[n:]


def _apply_write_fault(fault, sock):
    """Site handler for rpc.frame.write chaos; True = frame consumed."""
    if fault.kind == "drop":
        return True  # silently swallowed: the peer waits until timeout
    if fault.kind == "corrupt":
        # a garbage magic makes the receiver fail the frame cleanly
        # (FramingError) instead of misparsing bytes
        sock.sendall(_HEADER.pack(b"\xde\xad\x00\x00", 0))
        return True
    if fault.kind == "half_close":
        try:
            sock.shutdown(socket.SHUT_WR)
        except OSError:
            pass
        return True
    return False


def read_frame(sock):
    if faults.PLANE is not None:
        f = faults.PLANE.fire("rpc.frame.read")
        if f is not None:
            # every site kind on the read side degrades to "this
            # connection just died under us"
            raise ConnectionError("fault: frame lost at rpc.frame.read")
    header = recv_exact(sock, _HEADER.size)
    magic, length = _HEADER.unpack(header)
    if magic not in (MAGIC, MAGIC_V2):
        raise FramingError("bad magic %r" % magic)
    if length > MAX_FRAME:
        raise FramingError("frame too large: %d" % length)
    body = recv_exact(sock, length)
    obj = msgpack.unpackb(body, raw=False)
    if magic == MAGIC:
        return obj
    # v2: body was only the meta; raw array payloads follow in order.
    # recv straight into owned, writable arrays — zero user-space
    # copies beyond the kernel's.
    refs = []

    def collect(o):
        if isinstance(o, dict):
            if _ND_REF in o and isinstance(o[_ND_REF], int):
                refs.append(o)
                return
            for v in o.values():
                collect(v)
        elif isinstance(o, list):
            for v in o:
                collect(v)

    # every malformed-meta path must surface as FramingError BEFORE any
    # payload byte is read or allocation happens — the RPC client only
    # treats FramingError/ConnectionError as close-the-socket errors,
    # and sizes are validated with python ints (no int64 overflow)
    try:
        tree, lens = obj["tree"], obj["lens"]
        collect(tree)
        refs.sort(key=lambda r: r[_ND_REF])
        if [r[_ND_REF] for r in refs] != list(range(len(lens))):
            raise FramingError(
                "tensor frame meta mismatch: refs %r vs %d payloads"
                % ([r[_ND_REF] for r in refs], len(lens)))
        total = 0
        plan = []
        for ref, nbytes in zip(refs, lens):
            dtype = np.dtype(ref["dtype"])
            if dtype.hasobject or dtype.kind not in "biufcmMSUV":
                # an object dtype would recv_into() attacker bytes
                # straight into PyObject pointer slots — only plain-
                # old-data dtypes may cross the wire
                raise FramingError(
                    "non-POD tensor dtype refused: %r" % ref["dtype"])
            shape = tuple(int(d) for d in ref["shape"])
            if any(d < 0 for d in shape) or not isinstance(nbytes, int):
                raise FramingError("bad tensor meta: %r" % (ref,))
            want = dtype.itemsize
            for d in shape:
                want *= d  # python ints: no overflow wraparound
            if want != nbytes:
                raise FramingError(
                    "tensor frame shape/size mismatch: %r x %s = %d "
                    "!= %d" % (shape, dtype, want, nbytes))
            total += nbytes
            plan.append((dtype, shape))
        if total > MAX_FRAME:
            raise FramingError("tensor payload too large")
    except FramingError:
        raise
    except Exception as e:  # KeyError/TypeError/ValueError/...
        raise FramingError("malformed tensor frame meta: %r" % e)
    # the allocation/recv loop: any non-OSError failure here (a stray
    # ValueError from a hostile shape, a MemoryError) leaves unread
    # payload bytes on the socket — surface it as FramingError so the
    # RPC client closes the desynced connection instead of misparsing
    # stale bytes on its next call
    try:
        arrays = []
        for dtype, shape in plan:
            # datetime64/timedelta64 lack the buffer protocol: receive
            # into an i8 view and reinterpret (mirrors the send side)
            wire = np.dtype("i8") if dtype.kind in "mM" else dtype
            arr = np.empty(shape, wire)
            if arr.nbytes:  # memoryview.cast refuses zero-in-shape views
                _recv_into(sock, memoryview(arr).cast("B"))
            arrays.append(arr.view(dtype) if wire is not dtype else arr)
        return _fill_arrays(obj["tree"], arrays)
    except (FramingError, OSError):  # ConnectionError is an OSError
        raise
    except Exception as e:
        raise FramingError("tensor frame recv failed: %r" % e)


def _has_arrays(obj):
    """Short-circuit probe so array-free control RPCs skip the
    stripping rebuild entirely."""
    if isinstance(obj, (np.ndarray, np.generic)):
        return True
    if isinstance(obj, dict):
        return any(_has_arrays(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return any(_has_arrays(v) for v in obj)
    return False


def _strip_arrays(obj, bufs):
    """Replace every ndarray in the pytree with a {_ND_REF, dtype,
    shape} stub and append its (contiguous) buffer to ``bufs``.
    datetime64/timedelta64 have no buffer protocol — ship their bytes
    as an i8 view; the recorded dtype restores them on receive."""
    if isinstance(obj, np.ndarray):
        arr = np.ascontiguousarray(obj)
        ref = {_ND_REF: len(bufs), "dtype": arr.dtype.str,
               "shape": list(arr.shape)}
        bufs.append(arr.view("i8") if arr.dtype.kind in "mM" else arr)
        return ref
    if isinstance(obj, np.generic):
        return _strip_arrays(np.asarray(obj), bufs)
    if isinstance(obj, dict):
        if _ND_REF in obj:
            # the sentinel is reserved on the wire: a colliding user
            # key would be misparsed as an array stub by the receiver
            raise FramingError(
                "payload dict uses the reserved key %r" % _ND_REF)
        return {k: _strip_arrays(v, bufs) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_strip_arrays(v, bufs) for v in obj]
    return obj


def _fill_arrays(obj, arrays):
    if isinstance(obj, dict):
        if _ND_REF in obj and isinstance(obj[_ND_REF], int):
            return arrays[obj[_ND_REF]]
        return {k: _fill_arrays(v, arrays) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_fill_arrays(v, arrays) for v in obj]
    return obj


def _drain(sock, segments, sent):
    """Finish a short sendmsg write without re-concatenating."""
    for seg in segments:
        if sent >= len(seg):
            sent -= len(seg)
            continue
        sock.sendall(memoryview(seg)[sent:])
        sent = 0


# escape hatch for mixed fleets: a pre-v2 receiver hard-fails on
# MAGIC_V2 ("bad magic"), so during a rolling upgrade set this on the
# NEW senders until every receiver is current. In-tree deployments
# upgrade atomically; the env var exists for anyone who doesn't.
# Read PER CALL (like the UDS knob) so a long-lived process can be
# flipped without a restart.
def _v2_disabled():
    return bool(os.environ.get("EDL_TPU_DISABLE_TENSOR_FRAMES"))

# Linux IOV_MAX is 1024: sendmsg rejects longer segment vectors with
# EMSGSIZE, so wide pytrees (one segment per array) go out in groups.
_IOV_CAP = 1000


def write_frame(sock, obj):
    # vectored send: concatenating header+body (pack_frame) copies the
    # whole body, which for tensor batches is tens of MB per call —
    # measurable on the distill feed path (NOTES r5 distill curve).
    # sendmsg ships all segments in ONE syscall with no copy; it may
    # short-write, so drain any remainder without re-copying.
    if faults.PLANE is not None:
        f = faults.PLANE.fire("rpc.frame.write")
        if f is not None and _apply_write_fault(f, sock):
            return
    bufs = []
    disabled = _v2_disabled()
    if not disabled and _has_arrays(obj):
        stripped = _strip_arrays(obj, bufs)
    if not bufs:
        if disabled and _has_arrays(obj):
            from .ndarray import encode_tree
            obj = encode_tree(obj)  # v1 tagged form, pre-v2 compatible
        body = _pack_body(obj)
        segments = [_HEADER.pack(MAGIC, len(body)), body]
    else:
        meta = _pack_body({"tree": stripped,
                           "lens": [b.nbytes for b in bufs]})
        if sum(b.nbytes for b in bufs) > MAX_FRAME:
            raise FramingError("tensor payload too large")
        segments = [_HEADER.pack(MAGIC_V2, len(meta)), meta]
        # memoryview.cast refuses zero-in-shape views; empty arrays
        # contribute zero wire bytes anyway
        segments += [memoryview(b).cast("B") for b in bufs
                     if b.nbytes]
    for lo in range(0, len(segments), _IOV_CAP):
        group = segments[lo:lo + _IOV_CAP]
        sent = sock.sendmsg(group)
        if sent < sum(len(s) for s in group):
            _drain(sock, group, sent)


def set_keepalive(sock):
    if sock.family == getattr(socket, "AF_UNIX", object()):
        return  # no TCP options on unix sockets; liveness is kernel-local
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
