"""Shared, thread-safe RPC client pool keyed by endpoint.

The elastic data plane used to dial a **fresh TCP connection per stolen
batch** (ElasticReader._fetch constructed and closed an RpcClient around
every ``get_batch``), and the distill reader redialed every teacher on
every worker restart. RpcClient is already thread-safe and pipelined
(locked send path, per-connection reader thread matching responses by
envelope id), so one client per endpoint can carry every caller in the
process — the pool makes that sharing explicit and adds the two
lifecycle behaviors connection reuse needs:

- **idle reaping**: a client that has moved no traffic for ``idle_ttl``
  seconds is closed and dropped by a lazy daemon reaper, so a fleet
  that shrank does not leak sockets to departed peers;
- **retire-on-error**: a caller that sees a transport error retires the
  endpoint — the client is closed, dropped, and its cached feature set
  invalidated, so the next caller redials fresh (the peer may have
  restarted as a different generation).

``channel`` separates traffic classes onto distinct connections to the
same endpoint: a long-poll (``ds_get_assignment(wait_ms=...)``) is
served inline on its own server connection thread, so putting it on its
own channel keeps it from head-of-line-blocking bulk ``get_batches``
frames — without touching the shared worker pool on either side.

Leases: ``lease(endpoint)`` (a context manager) marks the client in
active use; the reaper never closes a leased client, so a caller
holding a lease across a long blocking call cannot have the socket
closed out from under it. Plain ``get()`` is the cheap path for
fire-and-forget callers (heartbeats) that tolerate a redial.
"""

import contextlib
import threading
import time

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.rpc.client import RpcClient
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger

_POOL_OPEN = obs_metrics.gauge(
    "edl_rpc_pool_open", "pooled clients currently open")
_POOL_DIALS = obs_metrics.counter(
    "edl_rpc_pool_dials_total", "pooled clients ever created (churn)")
_POOL_REAPS = obs_metrics.counter(
    "edl_rpc_pool_reaps_total", "idle clients reaped")
_POOL_RETIRES = obs_metrics.counter(
    "edl_rpc_pool_retires_total", "clients retired after transport "
    "errors")


class _Entry(object):
    __slots__ = ("client", "last_used", "leases")

    def __init__(self, client):
        self.client = client
        self.last_used = time.monotonic()
        self.leases = 0


class ClientPool(object):
    """``timeout``/``retry`` are passed through to every RpcClient the
    pool creates. ``idle_ttl`` bounds how long an unused connection is
    kept; ``reap_interval`` (default ``idle_ttl/4``) is the reaper's
    wake cadence."""

    def __init__(self, timeout=30.0, idle_ttl=120.0, reap_interval=None,
                 retry=None):
        self._timeout = timeout
        self._retry = retry
        self._idle_ttl = float(idle_ttl)
        self._reap_interval = (max(0.05, self._idle_ttl / 4.0)
                               if reap_interval is None
                               else float(reap_interval))
        self._lock = threading.Lock()
        self._entries = {}   # (endpoint, channel) -> _Entry
        self._features = {}  # endpoint -> tuple of advertised features
        self._stop = threading.Event()
        self._reaper = None
        self.dials = 0       # clients ever created (churn metric)
        self.reaps = 0       # idle clients closed by the reaper
        self.retires = 0     # clients dropped after transport errors

    # -- checkout ----------------------------------------------------------

    def get(self, endpoint, channel=None):
        """The shared client for ``endpoint`` (dialing lazily). The
        returned client may be reaped once idle; hold a :meth:`lease`
        around long blocking calls instead."""
        entry = self._checkout(endpoint, channel)
        with self._lock:
            entry.leases -= 1
        return entry.client

    @contextlib.contextmanager
    def lease(self, endpoint, channel=None):
        """Context manager yielding the shared client, protected from
        the idle reaper for the duration."""
        entry = self._checkout(endpoint, channel)
        try:
            yield entry.client
        finally:
            with self._lock:
                entry.leases -= 1
                entry.last_used = time.monotonic()

    def _checkout(self, endpoint, channel):
        key = (endpoint, channel)
        with self._lock:
            if self._stop.is_set():
                raise errors.StatusError("client pool is closed")
            entry = self._entries.get(key)
            if entry is None:
                entry = _Entry(RpcClient(endpoint, timeout=self._timeout,
                                         retry=self._retry))
                self._entries[key] = entry
                self.dials += 1
                _POOL_DIALS.inc()
                _POOL_OPEN.set(len(self._entries))
            entry.last_used = time.monotonic()
            entry.leases += 1
            if self._reaper is None:
                self._reaper = threading.Thread(
                    target=self._reap_loop, daemon=True,
                    name="rpc-pool-reaper")
                self._reaper.start()
        return entry

    # -- convenience call surface -----------------------------------------

    def call(self, endpoint, method, *args, channel=None, **kwargs):
        """Blocking call on the shared client, leased for the duration
        (safe across long-polls)."""
        with self.lease(endpoint, channel=channel) as client:
            return client.call(method, *args, **kwargs)

    def call_async(self, endpoint, method, *args, channel=None, **kwargs):
        """Pipelined call on the shared client. The lease covers only
        the send; the response rides the connection's reader thread
        (idle_ttl is orders of magnitude above any call timeout, so a
        pending future cannot be reaped out from under the caller)."""
        with self.lease(endpoint, channel=channel) as client:
            return client.call_async(method, *args, **kwargs)

    def features(self, endpoint):
        """The endpoint's advertised ``__features__``, probed once and
        cached until the endpoint is retired. Empty tuple for
        pre-pipelining peers (no such method) — never raises for a
        feature-less server, but transport failures propagate."""
        with self._lock:
            cached = self._features.get(endpoint)
        if cached is not None:
            return cached
        with self.lease(endpoint) as client:
            feats = client.server_features()
        with self._lock:
            self._features[endpoint] = feats
        return feats

    # -- lifecycle ---------------------------------------------------------

    def retire(self, endpoint, channel=None):
        """Drop and close the endpoint's client(s) after a transport
        error; the cached feature set is invalidated too (the peer may
        have restarted as a different generation). ``channel=None``
        retires EVERY channel to the endpoint — a dead peer is dead on
        all of them."""
        with self._lock:
            if channel is None:
                keys = [k for k in self._entries if k[0] == endpoint]
            else:
                keys = [(endpoint, channel)]
            dropped = [self._entries.pop(k) for k in keys
                       if k in self._entries]
            self._features.pop(endpoint, None)
            self.retires += len(dropped)
            _POOL_RETIRES.inc(len(dropped))
            _POOL_OPEN.set(len(self._entries))
        for entry in dropped:
            entry.client.close()

    def _reap_loop(self):
        while not self._stop.wait(self._reap_interval):
            now = time.monotonic()
            with self._lock:
                idle = [k for k, e in self._entries.items()
                        if e.leases <= 0
                        and now - e.last_used > self._idle_ttl]
                dropped = [self._entries.pop(k) for k in idle]
                self.reaps += len(dropped)
                _POOL_REAPS.inc(len(dropped))
                _POOL_OPEN.set(len(self._entries))
            for entry in dropped:
                logger.debug("pool: reaping idle client for %s",
                             entry.client.endpoint)
                entry.client.close()

    def stats(self):
        with self._lock:
            stats = {"open": len(self._entries), "dials": self.dials,
                     "reaps": self.reaps, "retires": self.retires}
        return obs_metrics.mirror_stats("edl_rpc_pool", stats)

    def close(self):
        """Close every client and stop the reaper. Idempotent; in-flight
        calls on pooled clients fail with ConnectError — intentional, so
        an owner's stop() promptly unblocks its fetch threads."""
        with self._lock:
            if self._stop.is_set():
                return
            self._stop.set()
            dropped = list(self._entries.values())
            self._entries.clear()
            self._features.clear()
            reaper = self._reaper
        for entry in dropped:
            entry.client.close()
        if reaper is not None:
            reaper.join(timeout=self._reap_interval + 5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
