"""Blocking RPC client with reconnect, per-endpoint channel cache.

Reference parity: edl/utils/client.py + data_server_client.py channel cache;
errors re-raise by class name (edl/utils/exceptions.py:93-103).
"""

import itertools
import socket
import threading

from edl_tpu.rpc import framing
from edl_tpu.utils import errors


class RpcClient(object):
    def __init__(self, endpoint, timeout=60.0):
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self.endpoint = endpoint
        self._timeout = timeout
        self._sock = None
        self._ids = itertools.count()
        self._lock = threading.Lock()

    def _connect(self):
        if self._sock is None:
            try:
                self._sock = socket.create_connection(
                    self._addr, timeout=self._timeout)
                framing.set_keepalive(self._sock)
            except OSError as e:
                self._sock = None
                raise errors.ConnectError(
                    "connect %s:%s failed: %s" % (*self._addr, e))

    def _close_locked(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def close(self):
        with self._lock:
            self._close_locked()

    def call(self, method, *args, timeout=None, **kwargs):
        """Invoke ``method`` remotely; one in-flight request per client."""
        with self._lock:
            self._connect()
            req = {"id": next(self._ids), "method": method,
                   "args": list(args), "kwargs": kwargs}
            try:
                self._sock.settimeout(timeout or self._timeout)
                framing.write_frame(self._sock, req)
                resp = framing.read_frame(self._sock)
            except (OSError, ConnectionError, framing.FramingError) as e:
                # already holding self._lock — must NOT re-enter close()
                self._close_locked()
                raise errors.ConnectError(
                    "rpc %s to %s failed: %s" % (method, self.endpoint, e))
            if resp.get("ok"):
                return resp.get("result")
            err = resp.get("error", {})
            raise errors.deserialize_error(
                err.get("name", "RpcError"), err.get("detail", ""))


def call(endpoint, method, *args, **kwargs):
    """One-shot convenience call (opens and closes a connection)."""
    c = RpcClient(endpoint)
    try:
        return c.call(method, *args, **kwargs)
    finally:
        c.close()
