"""Pipelined RPC client with reconnect and per-endpoint channel cache.

One connection now carries MANY requests in flight: the send path is
serialized by a lock, a per-connection reader thread matches response
frames back to callers by the envelope ``id``, and :meth:`RpcClient.
call_async` hands the caller an :class:`RpcFuture`. The blocking
:meth:`RpcClient.call` is ``call_async(...).result()`` with the exact
pre-pipelining semantics (per-call timeout, deadline budget capping,
retry-on-ConnectError with idempotency gating, fault points).

Ordering/compat: responses are matched by id, never by arrival order,
so this client interoperates with both the pooled out-of-order server
and a strict request-reply peer (which simply answers in order).
Requests sent via ``call_async`` carry ``"pl": 1`` so the server knows
the sender tolerates out-of-order responses; plain ``call`` requests
omit it and are served inline exactly as before.

Reference parity: edl/utils/client.py + data_server_client.py channel
cache; errors re-raise by class name (edl/utils/exceptions.py:93-103).
"""

import itertools
import os
import select
import socket
import threading
import time

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace as obs_trace
from edl_tpu.robustness import faults
from edl_tpu.rpc import framing
from edl_tpu.utils import errors

_CALL_MS = obs_metrics.histogram(
    "edl_rpc_client_call_ms", "request send to response resolve",
    labels=("method",))
_INFLIGHT = obs_metrics.gauge(
    "edl_rpc_client_inflight", "requests awaiting a response")
_RETRIES = obs_metrics.counter(
    "edl_rpc_client_retries_total", "transport-failure retries",
    labels=("method",))
_CALL_ERRS = obs_metrics.counter(
    "edl_rpc_client_errors_total", "calls resolved with an error",
    labels=("method",))
_DIALS = obs_metrics.counter(
    "edl_rpc_client_connects_total", "connections dialed")

_LOCAL_HOSTS = None
_LOCAL_LOCK = threading.Lock()


def _local_hosts():
    """Addresses that mean "this machine" — loopback plus this host's
    own IP (a same-host peer usually advertises the real IP). Cached
    only once the real IP resolves: get_host_ip falls back to loopback
    before the network settles, and freezing that would silently
    disable the fast path for real-IP endpoints forever."""
    global _LOCAL_HOSTS
    with _LOCAL_LOCK:
        if _LOCAL_HOSTS is not None:
            return _LOCAL_HOSTS
        hosts = {"127.0.0.1", "localhost", "::1", "0.0.0.0"}
        try:
            from edl_tpu.utils.network import get_host_ip
            ip = get_host_ip()
        except Exception:  # noqa: BLE001 — fast path is optional
            ip = None
        if ip and not ip.startswith("127."):
            hosts.add(ip)
            _LOCAL_HOSTS = hosts  # resolved: safe to freeze
        return hosts


class RpcFuture(object):
    """The pending response of one pipelined call.

    ``result(timeout)`` keeps the old blocking-call contract: a typed
    server error re-raises as its class; a transport failure (or a
    response that never arrives within the budget) tears the connection
    down and raises ConnectError, failing every other call in flight on
    the same connection — exactly what a died socket did before.
    """

    __slots__ = ("_client", "_conn", "method", "_budget", "_sent_at",
                 "_event", "_value", "_error", "_span", "_counted")

    def __init__(self, client, conn, method, budget):
        self._client = client
        self._conn = conn
        self.method = method
        self._budget = budget
        self._sent_at = time.monotonic()
        self._event = threading.Event()
        self._value = None
        self._error = None
        self._span = None     # client trace span, closed at resolve
        self._counted = False  # in-flight gauge held (set post-send)

    def _resolve(self, value=None, error=None):
        if self._event.is_set():
            return
        self._value, self._error = value, error
        _CALL_MS.labels(self.method).observe(
            (time.monotonic() - self._sent_at) * 1e3)
        if self._counted:
            _INFLIGHT.dec()
            self._counted = False
        if error is not None:
            _CALL_ERRS.labels(self.method).inc()
        obs_trace.end_span(self._span, ok=error is None)
        self._span = None
        self._event.set()

    def done(self):
        return self._event.is_set()

    def wait(self, timeout=None):
        """Non-destructive wait; True iff the response has arrived."""
        return self._event.wait(timeout)

    def remaining(self):
        """Seconds left of this call's send-time budget (None = unbounded)."""
        if self._budget is None:
            return None
        return self._budget - (time.monotonic() - self._sent_at)

    def result(self, timeout=-1):
        """Block for the response. ``timeout=-1`` (default) means "the
        budget computed at send time", mirroring what the socket
        timeout enforced for serial calls."""
        if timeout == -1:
            timeout = self.remaining()
        if not self._event.wait(timeout):
            # no response within budget: the connection is torn down
            # (same observable behavior as the old per-call socket
            # timeout) unless the response raced the teardown in
            self._client._kill_conn(
                self._conn,
                errors.ConnectError(
                    "rpc %s to %s failed: no response within %.1fs"
                    % (self.method, self._client.endpoint,
                       timeout if timeout is not None else -1.0)))
            if not self._event.is_set():
                raise errors.ConnectError(
                    "rpc %s to %s timed out after %.1fs"
                    % (self.method, self._client.endpoint,
                       timeout if timeout is not None else -1.0))
        if self._error is not None:
            raise self._error
        return self._value


class _Conn(object):
    """One live connection: the socket, the pending-by-id map, and the
    reader thread."""

    __slots__ = ("sock", "transport", "wlock", "plock",
                 "pending", "dead", "reader")

    def __init__(self, sock, transport):
        self.sock = sock
        self.transport = transport
        self.wlock = threading.Lock()   # serializes write_frame
        self.plock = threading.Lock()   # guards pending/dead
        self.pending = {}               # id -> RpcFuture
        self.dead = False
        self.reader = None


class RpcClient(object):
    def __init__(self, endpoint, timeout=60.0, retry=None):
        """``retry``: an optional robustness.policy.RetryPolicy; when
        set, calls marked ``idempotent=True`` (and any call that failed
        before its request hit the wire) reconnect and retry with
        jittered backoff instead of failing fast."""
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self.endpoint = endpoint
        self._timeout = timeout
        self._retry = retry
        self._conn = None
        self._ids = itertools.count()
        self._lock = threading.Lock()   # guards _conn (re)creation
        self._features = None  # peer's __features__, probed lazily
        self.transport = None  # "uds" | "tcp" after connect

    def _try_uds(self):
        """Same-host fast path (r5: 1381 vs 997 MB/s on tensor
        frames): dial the server's conventional AF_UNIX path if it
        exists, is OURS (0600 + uid check — /tmp is world-writable,
        a squatter must not receive our payloads), and answers.
        Any failure falls back to TCP silently."""
        if os.environ.get("EDL_TPU_DISABLE_UDS") \
                or not hasattr(socket, "AF_UNIX") \
                or self._addr[0] not in _local_hosts():
            return None
        import stat as stat_mod

        from edl_tpu.rpc.server import uds_path_for_port
        path = uds_path_for_port(self._addr[1])
        s = None
        try:
            # lstat + S_ISSOCK: a symlink planted in world-writable
            # /tmp must not redirect us (stat would follow it)
            st = os.lstat(path)
            if st.st_uid != os.getuid() \
                    or not stat_mod.S_ISSOCK(st.st_mode):
                return None
            s = socket.socket(socket.AF_UNIX)
            s.settimeout(self._timeout)
            s.connect(path)
            if not self._verify_uds_identity(s):
                s.close()
                return None
            return s
        except OSError:
            if s is not None:
                s.close()  # no fd leak on stale-file fallback
            return None

    def _verify_uds_identity(self, sock):
        """The UDS path is keyed by port NUMBER alone, but two servers
        bound to distinct specific addresses (127.0.0.1 vs the real IP)
        can legitimately share a port number — whichever started first
        owns the socket path, and it may not be the server we dialed.
        Ask who answers before trusting the fast path; any failure or
        mismatch means "use TCP", which always reaches the right peer."""
        try:
            framing.write_frame(sock, {"id": -1,
                                       "method": "__identity__"})
            resp = framing.read_frame(sock)
            if not resp.get("ok"):
                return False  # pre-identity server: can't verify
            ident = resp.get("result") or {}
            if int(ident.get("port", -1)) != self._addr[1]:
                return False
            bind = str(ident.get("host", ""))
            if bind in ("0.0.0.0", "::"):
                return True  # wildcard bind answers every local address
            loop = {"127.0.0.1", "localhost", "::1"}
            # dialing 0.0.0.0 over TCP lands on loopback, so a
            # loopback-bound server is the right peer for it too
            if bind in loop and self._addr[0] in (loop | {"0.0.0.0"}):
                return True
            return bind == self._addr[0]
        except (OSError, ValueError, TypeError, framing.FramingError):
            return False

    def _ensure_conn(self):
        """Dial if needed; returns the live _Conn. Caller holds no locks."""
        with self._lock:
            conn = self._conn
            if conn is not None:
                return conn
            if faults.PLANE is not None:
                # partition/error/delay on the dial path (site kinds
                # degrade to "unreachable")
                f = faults.PLANE.fire("rpc.client.connect",
                                      endpoint=self.endpoint)
                if f is not None:
                    raise errors.ConnectError(
                        "fault: connect to %s cut" % self.endpoint)
            sock = self._try_uds()
            if sock is not None:
                transport = "uds"
            else:
                try:
                    sock = socket.create_connection(
                        self._addr, timeout=self._timeout)
                    framing.set_keepalive(sock)
                    transport = "tcp"
                except OSError as e:
                    raise errors.ConnectError(
                        "connect %s:%s failed: %s" % (*self._addr, e))
            _DIALS.inc()
            conn = _Conn(sock, transport)
            conn.reader = threading.Thread(
                target=self._read_loop, args=(conn,), daemon=True,
                name="rpc-reader-%s" % self.endpoint)
            self._conn = conn
            self.transport = transport
            conn.reader.start()
            return conn

    def _read_loop(self, conn):
        """Match response frames to pending futures by envelope id.
        Any transport failure fails EVERY call in flight — the peer is
        a stream, so one torn frame desyncs all of them.

        The reader polls for readability before touching the socket:
        the socket's timeout is owned by the SEND path (per-call
        budget), and an idle connection must not be torn down just
        because no response arrived within one call's budget. A
        timeout that fires mid-frame, by contrast, really is a dead
        peer and kills the connection like any transport error."""
        poller = select.poll()
        poller.register(conn.sock.fileno(), select.POLLIN)
        try:
            while True:
                try:
                    events = poller.poll(1000)  # ms; idle wakeup only
                    if not events:
                        continue
                    if events[0][1] & select.POLLNVAL:
                        raise ConnectionError("connection closed")
                    resp = framing.read_frame(conn.sock)
                except (OSError, ConnectionError, ValueError,
                        framing.FramingError) as e:
                    self._kill_conn(conn, errors.ConnectError(
                        "rpc to %s failed: %s" % (self.endpoint, e)))
                    return
                with conn.plock:
                    fut = conn.pending.pop(resp.get("id"), None)
                if fut is None:
                    continue  # response for a call that already timed out
                if resp.get("ok"):
                    fut._resolve(value=resp.get("result"))
                else:
                    err = resp.get("error", {})
                    fut._resolve(error=errors.deserialize_error(
                        err.get("name", "RpcError"), err.get("detail", "")))
        finally:
            # the reader owns the fd's lifetime: closing it anywhere
            # else would race this thread's poll() against fd-number
            # reuse (kill only shuts the connection down)
            try:
                conn.sock.close()
            except OSError:
                pass

    def _kill_conn(self, conn, exc):
        """Tear down ``conn`` and fail everything pending on it with
        ``exc``. Idempotent; callable from any thread (reader, a timed
        -out caller, close())."""
        if conn is None:
            return
        with self._lock:
            if self._conn is conn:
                self._conn = None
        with conn.plock:
            if conn.dead:
                return
            conn.dead = True
            pending = list(conn.pending.values())
            conn.pending.clear()
        try:
            # shutdown, NOT close: the reader thread polls this fd and
            # closes it on exit; closing here would race fd reuse
            conn.sock.shutdown(socket.SHUT_RDWR)  # wakes a blocked reader
        except OSError:
            pass
        for fut in pending:
            fut._resolve(error=exc)

    def close(self):
        self._kill_conn(self._conn,
                        errors.ConnectError("client for %s closed"
                                            % self.endpoint))

    # -- the call surface --------------------------------------------------

    def call_async(self, method, *args, timeout=None, deadline=None,
                   **kwargs):
        """Send ``method`` without waiting; returns an :class:`RpcFuture`.

        Many calls may be in flight on one connection; responses are
        matched by id, so completion order is whatever the server
        chooses. The request carries ``"pl": 1`` (pipelined) so a
        feature-aware server may dispatch it to its worker pool and
        answer out of order; a strict request-reply server just answers
        in order — both are correct for this client.
        """
        return self._send(method, args, kwargs, timeout, deadline,
                          pipelined=True)

    def server_features(self):
        """The peer's advertised feature set (empty for pre-pipelining
        servers, which lack the ``__features__`` method). Cached on the
        client — the trace-header gate consults the cache on every
        send, and a pool retire discards the whole client anyway."""
        if self._features is not None:
            return self._features
        try:
            feats = tuple(self.call("__features__"))
        except errors.RpcError:
            feats = ()
        self._features = feats
        return feats

    def _trace_header(self, span, method):
        """The ``[trace_id, span_id]`` header for ``span`` — but only
        once the peer negotiated ``obs.trace`` (probed lazily, once per
        client). A legacy peer never sees the key: byte-compatible
        fallback, same negotiation pattern as rpc.pipeline. Internal
        dunder methods never probe (the probe itself is one)."""
        if span is None:
            return None
        feats = self._features
        if feats is None:
            if method.startswith("__"):
                return None
            try:
                feats = self.server_features()
            except errors.EdlError:
                self._features = feats = ()
        if "obs.trace" not in feats:
            return None
        return [span.trace_id, span.span_id]

    def _send(self, method, args, kwargs, timeout, deadline,
              pipelined, wrote=None):
        # span + header resolved BEFORE taking the write lock: the
        # first traced call may probe __features__, a full nested call
        span = obs_trace.begin_span("rpc.client/%s" % method,
                                    kind="client",
                                    tags={"endpoint": self.endpoint})
        header = self._trace_header(span, method)
        try:
            conn = self._ensure_conn()
            budget = timeout or self._timeout
            if deadline is not None:
                budget = deadline.remaining(cap=budget)
                if budget is not None and budget <= 0:
                    raise errors.DeadlineExceededError(
                        "rpc %s to %s: no budget left"
                        % (method, self.endpoint))
            with conn.wlock:
                if faults.PLANE is not None:
                    f = faults.PLANE.fire("rpc.client.call",
                                          endpoint=self.endpoint,
                                          method=method)
                    if f is not None:
                        # a dropped request manifests to the caller as
                        # a timed-out connection
                        self._kill_conn(conn, errors.ConnectError(
                            "rpc %s to %s failed: fault: request dropped"
                            % (method, self.endpoint)))
                        raise errors.ConnectError(
                            "rpc %s to %s failed: fault: request dropped"
                            % (method, self.endpoint))
                call_id = next(self._ids)
                req = {"id": call_id, "method": method,
                       "args": list(args), "kwargs": kwargs}
                if pipelined:
                    req["pl"] = 1
                if header is not None:
                    req["tr"] = header
                fut = RpcFuture(self, conn, method, budget)
                fut._span = span
                with conn.plock:
                    if conn.dead:
                        raise errors.ConnectError(
                            "rpc %s to %s failed: connection died"
                            % (method, self.endpoint))
                    # registered BEFORE the write: the response can
                    # arrive the instant the last request byte hits the
                    # wire
                    conn.pending[call_id] = fut
                _INFLIGHT.inc()
                fut._counted = True
                try:
                    conn.sock.settimeout(budget)
                    framing.write_frame(conn.sock, req)
                    if wrote is not None:
                        wrote[0] = True
                except (OSError, ConnectionError,
                        framing.FramingError) as e:
                    self._kill_conn(conn, errors.ConnectError(
                        "rpc %s to %s failed: %s"
                        % (method, self.endpoint, e)))
                    raise errors.ConnectError(
                        "rpc %s to %s failed: %s"
                        % (method, self.endpoint, e))
        except Exception:
            # a send that never reached _resolve closes its span here
            # (end_span is idempotent, so the _kill_conn path — which
            # resolves the registered future and closes the span — is
            # safe to race)
            obs_trace.end_span(span, ok=False)
            raise
        return fut

    def call(self, method, *args, timeout=None, deadline=None,
             idempotent=False, **kwargs):
        """Invoke ``method`` remotely and block for its result.

        ``deadline``: an optional robustness.policy.Deadline — the
        caller's remaining budget caps this call's socket timeout, so a
        nested call chain can never outlive its outermost budget.
        ``idempotent``: with a retry policy configured, lets this call
        be re-sent after a transport failure even though the original
        request may have reached the server.
        """
        if self._retry is None:
            return self._call_once(method, args, kwargs, timeout, deadline)
        attempt = 0
        while True:
            attempt += 1
            if deadline is not None:
                deadline.check("rpc %s to %s" % (method, self.endpoint))
            wrote = [False]
            try:
                return self._call_once(method, args, kwargs, timeout,
                                       deadline, wrote)
            except errors.ConnectError as e:
                # a request that never hit the wire is always safe to
                # retry; one that did is only safe if idempotent
                if not (idempotent or not wrote[0]):
                    raise
                if not self._retry.sleep(attempt, deadline):
                    if deadline is not None and deadline.expired():
                        raise errors.DeadlineExceededError(
                            "rpc %s to %s: deadline exceeded after %d "
                            "attempts; last error: %r"
                            % (method, self.endpoint, attempt, e)) from e
                    raise
                _RETRIES.labels(method).inc()

    def _call_once(self, method, args, kwargs, timeout, deadline,
                   wrote=None):
        # pipelined=False: a plain blocking call asks for the server's
        # strict inline dispatch (lowest latency, pre-pipelining order)
        fut = self._send(method, args, kwargs, timeout, deadline,
                         pipelined=False, wrote=wrote)
        return fut.result()


def call(endpoint, method, *args, **kwargs):
    """One-shot convenience call (opens and closes a connection)."""
    c = RpcClient(endpoint)
    try:
        return c.call(method, *args, **kwargs)
    finally:
        c.close()
