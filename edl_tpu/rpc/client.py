"""Blocking RPC client with reconnect, per-endpoint channel cache.

Reference parity: edl/utils/client.py + data_server_client.py channel cache;
errors re-raise by class name (edl/utils/exceptions.py:93-103).
"""

import itertools
import os
import socket
import threading

from edl_tpu.robustness import faults
from edl_tpu.rpc import framing
from edl_tpu.utils import errors

_LOCAL_HOSTS = None
_LOCAL_LOCK = threading.Lock()


def _local_hosts():
    """Addresses that mean "this machine" — loopback plus this host's
    own IP (a same-host peer usually advertises the real IP). Cached
    only once the real IP resolves: get_host_ip falls back to loopback
    before the network settles, and freezing that would silently
    disable the fast path for real-IP endpoints forever."""
    global _LOCAL_HOSTS
    with _LOCAL_LOCK:
        if _LOCAL_HOSTS is not None:
            return _LOCAL_HOSTS
        hosts = {"127.0.0.1", "localhost", "::1", "0.0.0.0"}
        try:
            from edl_tpu.utils.network import get_host_ip
            ip = get_host_ip()
        except Exception:  # noqa: BLE001 — fast path is optional
            ip = None
        if ip and not ip.startswith("127."):
            hosts.add(ip)
            _LOCAL_HOSTS = hosts  # resolved: safe to freeze
        return hosts


class RpcClient(object):
    def __init__(self, endpoint, timeout=60.0, retry=None):
        """``retry``: an optional robustness.policy.RetryPolicy; when
        set, calls marked ``idempotent=True`` (and any call that failed
        before its request hit the wire) reconnect and retry with
        jittered backoff instead of failing fast."""
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self.endpoint = endpoint
        self._timeout = timeout
        self._retry = retry
        self._sock = None
        self._ids = itertools.count()
        self._lock = threading.Lock()
        self.transport = None  # "uds" | "tcp" after connect

    def _try_uds(self):
        """Same-host fast path (r5: 1381 vs 997 MB/s on tensor
        frames): dial the server's conventional AF_UNIX path if it
        exists, is OURS (0600 + uid check — /tmp is world-writable,
        a squatter must not receive our payloads), and answers.
        Any failure falls back to TCP silently."""
        if os.environ.get("EDL_TPU_DISABLE_UDS") \
                or not hasattr(socket, "AF_UNIX") \
                or self._addr[0] not in _local_hosts():
            return None
        import stat as stat_mod

        from edl_tpu.rpc.server import uds_path_for_port
        path = uds_path_for_port(self._addr[1])
        s = None
        try:
            # lstat + S_ISSOCK: a symlink planted in world-writable
            # /tmp must not redirect us (stat would follow it)
            st = os.lstat(path)
            if st.st_uid != os.getuid() \
                    or not stat_mod.S_ISSOCK(st.st_mode):
                return None
            s = socket.socket(socket.AF_UNIX)
            s.settimeout(self._timeout)
            s.connect(path)
            return s
        except OSError:
            if s is not None:
                s.close()  # no fd leak on stale-file fallback
            return None

    def _connect(self):
        if self._sock is None:
            if faults.PLANE is not None:
                # partition/error/delay on the dial path (site kinds
                # degrade to "unreachable")
                f = faults.PLANE.fire("rpc.client.connect",
                                      endpoint=self.endpoint)
                if f is not None:
                    raise errors.ConnectError(
                        "fault: connect to %s cut" % self.endpoint)
            sock = self._try_uds()
            if sock is not None:
                self._sock = sock
                self.transport = "uds"
                return
            try:
                self._sock = socket.create_connection(
                    self._addr, timeout=self._timeout)
                framing.set_keepalive(self._sock)
                self.transport = "tcp"
            except OSError as e:
                self._sock = None
                raise errors.ConnectError(
                    "connect %s:%s failed: %s" % (*self._addr, e))

    def _close_locked(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def close(self):
        with self._lock:
            self._close_locked()

    def call(self, method, *args, timeout=None, deadline=None,
             idempotent=False, **kwargs):
        """Invoke ``method`` remotely; one in-flight request per client.

        ``deadline``: an optional robustness.policy.Deadline — the
        caller's remaining budget caps this call's socket timeout, so a
        nested call chain can never outlive its outermost budget.
        ``idempotent``: with a retry policy configured, lets this call
        be re-sent after a transport failure even though the original
        request may have reached the server.
        """
        if self._retry is None:
            return self._call_once(method, args, kwargs, timeout, deadline)
        attempt = 0
        while True:
            attempt += 1
            if deadline is not None:
                deadline.check("rpc %s to %s" % (method, self.endpoint))
            wrote = [False]
            try:
                return self._call_once(method, args, kwargs, timeout,
                                       deadline, wrote)
            except errors.ConnectError as e:
                # a request that never hit the wire is always safe to
                # retry; one that did is only safe if idempotent
                if not (idempotent or not wrote[0]):
                    raise
                if not self._retry.sleep(attempt, deadline):
                    if deadline is not None and deadline.expired():
                        raise errors.DeadlineExceededError(
                            "rpc %s to %s: deadline exceeded after %d "
                            "attempts; last error: %r"
                            % (method, self.endpoint, attempt, e)) from e
                    raise

    def _call_once(self, method, args, kwargs, timeout, deadline,
                   wrote=None):
        with self._lock:
            self._connect()
            if faults.PLANE is not None:
                f = faults.PLANE.fire("rpc.client.call",
                                      endpoint=self.endpoint, method=method)
                if f is not None:
                    # a dropped request manifests to the caller as a
                    # timed-out connection
                    self._close_locked()
                    raise errors.ConnectError(
                        "rpc %s to %s failed: fault: request dropped"
                        % (method, self.endpoint))
            req = {"id": next(self._ids), "method": method,
                   "args": list(args), "kwargs": kwargs}
            try:
                budget = timeout or self._timeout
                if deadline is not None:
                    budget = deadline.remaining(cap=budget)
                    if budget is not None and budget <= 0:
                        raise errors.DeadlineExceededError(
                            "rpc %s to %s: no budget left"
                            % (method, self.endpoint))
                self._sock.settimeout(budget)
                framing.write_frame(self._sock, req)
                if wrote is not None:
                    wrote[0] = True
                resp = framing.read_frame(self._sock)
            except (OSError, ConnectionError, framing.FramingError) as e:
                # already holding self._lock — must NOT re-enter close()
                self._close_locked()
                raise errors.ConnectError(
                    "rpc %s to %s failed: %s" % (method, self.endpoint, e))
            if resp.get("ok"):
                return resp.get("result")
            err = resp.get("error", {})
            raise errors.deserialize_error(
                err.get("name", "RpcError"), err.get("detail", ""))


def call(endpoint, method, *args, **kwargs):
    """One-shot convenience call (opens and closes a connection)."""
    c = RpcClient(endpoint)
    try:
        return c.call(method, *args, **kwargs)
    finally:
        c.close()
