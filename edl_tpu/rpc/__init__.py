from edl_tpu.rpc.server import RpcServer
from edl_tpu.rpc.client import RpcClient, call

__all__ = ["RpcServer", "RpcClient", "call"]
