"""Numpy arrays over the msgpack wire: tag-encode ndarrays inside pytrees.

Used by the distill plane to ship feature batches and teacher predictions
(the role paddle-serving's protobuf tensors played in the reference).
"""

import numpy as np

_TAG = "__nd__"


def encode_tree(obj):
    if isinstance(obj, np.ndarray):
        return {_TAG: True, "dtype": obj.dtype.str,
                "shape": list(obj.shape),
                "data": obj.tobytes()}
    if isinstance(obj, (np.generic,)):
        return encode_tree(np.asarray(obj))
    if isinstance(obj, dict):
        return {k: encode_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [encode_tree(v) for v in obj]
    return obj


def decode_tree(obj, copy=True):
    """``copy=False`` returns READ-ONLY views into the decoded message
    bytes (zero-copy) — right for consumers that only feed the arrays
    onward (device upload, jnp conversion); the distill teacher's feed
    path saves a full batch-size memcpy per request this way. Default
    stays copying (owned, writable arrays)."""
    if isinstance(obj, dict):
        if obj.get(_TAG):
            arr = np.frombuffer(
                obj["data"], dtype=np.dtype(obj["dtype"])
            ).reshape(obj["shape"])
            return arr.copy() if copy else arr
        return {k: decode_tree(v, copy) for k, v in obj.items()}
    if isinstance(obj, list):
        return [decode_tree(v, copy) for v in obj]
    return obj
