"""Numpy arrays over the msgpack wire: tag-encode ndarrays inside pytrees,
plus the columnar batch encoding the elastic data plane ships batches in.

Used by the distill plane to ship feature batches and teacher predictions
(the role paddle-serving's protobuf tensors played in the reference), and
by the data plane's ``get_batches`` to turn a list of records into a
handful of ndarray columns that ride the v2 tensor frames out-of-band —
one contiguous segment per column instead of one msgpack object (or one
frame segment) per record.
"""

import numpy as np

_TAG = "__nd__"


def encode_tree(obj):
    if isinstance(obj, np.ndarray):
        return {_TAG: True, "dtype": obj.dtype.str,
                "shape": list(obj.shape),
                "data": obj.tobytes()}
    if isinstance(obj, (np.generic,)):
        return encode_tree(np.asarray(obj))
    if isinstance(obj, dict):
        return {k: encode_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [encode_tree(v) for v in obj]
    return obj


def decode_tree(obj, copy=True):
    """``copy=False`` returns READ-ONLY views into the decoded message
    bytes (zero-copy) — right for consumers that only feed the arrays
    onward (device upload, jnp conversion); the distill teacher's feed
    path saves a full batch-size memcpy per request this way. Default
    stays copying (owned, writable arrays)."""
    if isinstance(obj, dict):
        if obj.get(_TAG):
            arr = np.frombuffer(
                obj["data"], dtype=np.dtype(obj["dtype"])
            ).reshape(obj["shape"])
            return arr.copy() if copy else arr
        return {k: decode_tree(v, copy) for k, v in obj.items()}
    if isinstance(obj, list):
        return [decode_tree(v, copy) for v in obj]
    return obj


# -- columnar batch encoding ------------------------------------------------
#
# pack_columns turns a HOMOGENEOUS list of records into a small dict of
# ndarray columns; unpack_columns restores the exact original records
# (types included), so the row and columnar wire formats are
# interchangeable — the negotiation can fall back per producer without
# the consumer seeing any difference. Returns None for record shapes it
# cannot represent exactly; callers then keep the row format.
#
# Column kinds:
#   nd     records are ndarrays of one dtype+shape  -> one stacked array
#   str    utf-8 bytes blob + per-record lengths
#   bytes  raw blob + per-record lengths
#   i64    python ints that fit int64               -> one int64 array
#   f64    python floats                            -> one float64 array
#   tuple / list  fixed-arity rows; one column per field

def pack_columns(records):
    """Columnar form of ``records`` (a non-empty list), or None when the
    records are heterogeneous / unsupported and the row format must be
    kept."""
    if not records:
        return None
    first = records[0]
    if isinstance(first, str):
        if not all(type(r) is str for r in records):
            return None
        blobs = [r.encode("utf-8") for r in records]
        return {"kind": "str",
                "data": np.frombuffer(b"".join(blobs), dtype=np.uint8),
                "lens": np.array([len(b) for b in blobs], "<i8")}
    if isinstance(first, bytes):
        if not all(type(r) is bytes for r in records):
            return None
        return {"kind": "bytes",
                "data": np.frombuffer(b"".join(records), dtype=np.uint8),
                "lens": np.array([len(b) for b in records], "<i8")}
    if isinstance(first, np.ndarray):
        dtype, shape = first.dtype, first.shape
        if dtype.hasobject:
            return None
        if not all(isinstance(r, np.ndarray) and r.dtype == dtype
                   and r.shape == shape for r in records):
            return None
        return {"kind": "nd", "data": np.stack(records)}
    if type(first) is int:  # bool is an int subclass: keep it row-form
        if not all(type(r) is int for r in records):
            return None
        try:
            col = np.array(records, "<i8")
        except OverflowError:
            return None
        return {"kind": "i64", "data": col}
    if type(first) is float:
        if not all(type(r) is float for r in records):
            return None
        return {"kind": "f64", "data": np.array(records, "<f8")}
    if isinstance(first, (tuple, list)):
        arity = len(first)
        seq = type(first)
        if not all(type(r) is seq and len(r) == arity for r in records):
            return None
        fields = []
        for i in range(arity):
            col = pack_columns([r[i] for r in records])
            if col is None:
                return None
            fields.append(col)
        return {"kind": "tuple" if seq is tuple else "list",
                "fields": fields, "n": len(records)}
    return None


def _col_array(data, copy):
    """Normalize a column that crossed the wire: v2 tensor frames hand
    us a real ndarray already; the v1 tagged fallback (or a msgpack
    bin) arrives as a tagged dict / raw bytes."""
    if isinstance(data, np.ndarray):
        return data
    return decode_tree(data, copy=copy)


def unpack_columns(col, copy=False):
    """The exact record list ``pack_columns`` encoded. ``copy=False``
    returns views into the received buffers for ``nd`` columns (the
    zero-copy path into device upload); blob-backed kinds (str/bytes)
    materialize per-record objects either way."""
    kind = col["kind"]
    if kind in ("tuple", "list"):
        cols = [unpack_columns(f, copy=copy) for f in col["fields"]]
        rows = zip(*cols) if cols else [() for _ in range(col["n"])]
        if kind == "tuple":
            return [tuple(r) for r in rows]
        return [list(r) for r in rows]
    data = _col_array(col["data"], copy)
    if kind == "nd":
        return [r.copy() if copy else r for r in data]
    if kind in ("str", "bytes"):
        lens = _col_array(col["lens"], copy)
        blob = data.tobytes()  # one copy for the whole column
        out, off = [], 0
        for n in lens.tolist():
            chunk = blob[off:off + n]
            out.append(chunk.decode("utf-8") if kind == "str" else chunk)
            off += n
        return out
    if kind == "i64":
        return [int(v) for v in data.tolist()]
    if kind == "f64":
        return [float(v) for v in data.tolist()]
    raise ValueError("unknown column kind %r" % kind)
