"""Numpy arrays over the msgpack wire: tag-encode ndarrays inside pytrees.

Used by the distill plane to ship feature batches and teacher predictions
(the role paddle-serving's protobuf tensors played in the reference).
"""

import numpy as np

_TAG = "__nd__"


def encode_tree(obj):
    if isinstance(obj, np.ndarray):
        return {_TAG: True, "dtype": obj.dtype.str,
                "shape": list(obj.shape),
                "data": obj.tobytes()}
    if isinstance(obj, (np.generic,)):
        return encode_tree(np.asarray(obj))
    if isinstance(obj, dict):
        return {k: encode_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [encode_tree(v) for v in obj]
    return obj


def decode_tree(obj):
    if isinstance(obj, dict):
        if obj.get(_TAG):
            return np.frombuffer(
                obj["data"], dtype=np.dtype(obj["dtype"])
            ).reshape(obj["shape"]).copy()
        return {k: decode_tree(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [decode_tree(v) for v in obj]
    return obj
