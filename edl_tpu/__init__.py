"""edl_tpu — a TPU-native elastic deep learning framework.

A ground-up JAX/XLA/pjit rebuild of the capabilities of elasticdeeplearning/edl
(reference layer map: SURVEY.md §1):

- elastic, fault-tolerant collective training: an in-tree coordination store
  (``edl_tpu.coordination``) replaces etcd; a per-host launcher daemon
  (``edl_tpu.controller``) does leader election, membership, stage-keyed
  barrier, and stop-resume elasticity;
- an in-tree JAX training runtime (``edl_tpu.runtime``) replaces Paddle Fleet:
  device meshes, pjit/shard_map train steps with XLA collectives over ICI/DCN,
  atomic versioned checkpointing, elastic State;
- an elastic distillation service plane (``edl_tpu.distill``): TPU-hosted
  teacher inference servers, service discovery and client/teacher balancing.
"""

__version__ = "0.1.0"
