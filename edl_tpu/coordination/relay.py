"""Watch relay trees: O(log N) control-plane fan-out at fleet scale.

Flat topology costs the store O(N) work per control-plane beat: every
pod long-polls ``store_wait_events`` directly, refreshes its leases
directly, and writes its own ``obs_pub/v1`` doc every tick.  This
module applies the two classic fixes on top of our revision-resumable
watch protocol — ZooKeeper-style observer fan-out for the downward
path and Astrolabe-style in-network aggregation for the upward path:

- **Downward (watch fan-out)**: each pod hosts a :class:`WatchRelay`
  that holds ONE upstream ``wait_events`` long-poll per watched prefix
  — against the store for the root relay, against its parent relay
  otherwise — and serves its children's long-polls from a local
  revision-ordered event cache.  The tree is a deterministic B-ary
  heap over the SORTED pod-id list (parent of index ``i`` is index
  ``(i - 1) // B``), so every pod derives the same depth-⌈log_B N⌉
  topology from the cluster map alone, with no negotiation round.

- **Upward (lease + obs coalescing)**: children's
  ``lease_refresh_many`` beats are folded into one upstream batch per
  coalesce window, and ``obs_pub/v1`` docs are folded into
  ``obs_agg/v1`` docs that KEEP per-pod cells (straggler/staleness
  detectors still see individual pods) — the root writes one store doc
  per tick instead of N.

Failover is lossless by construction: children attach via feature
negotiation (``coord.relay`` in ``__features__``; relays advertise
under a TTL lease in ``SERVICE_RELAY``) and fall through to the direct
store path whenever no relay answers.  Because every consumer resumes
from its OWN ``since_rev``, a relay kill can delay an event but never
lose one — the reattached child replays the gap from the grandparent
or the store.  Kill switch: ``EDL_TPU_RELAY=0`` disables hosting and
attaching entirely (the fleet reverts to flat long-polls).

Fault points: ``relay.attach`` (child side, when an attachment adopts
a relay endpoint; ctx: endpoint, pod) and ``relay.forward`` (relay
side, before a child long-poll is served; ctx: prefix, child — a
``drop`` looks like a timed-out poll, an ``error`` forces the child
through the reattach path).  See docs/fault_tolerance.md.
"""

import json
import math
import os
import threading
import time

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.robustness import faults
from edl_tpu.robustness.policy import RetryPolicy
from edl_tpu.rpc.client import RpcClient
from edl_tpu.rpc.server import FEATURES, RpcServer
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger

#: feature-negotiation token: servers that can serve relayed
#: ``relay_wait_events`` / ``relay_obs_publish`` /
#: ``relay_lease_refresh_many`` advertise it via ``__features__``
FEATURE = "coord.relay"

#: value of controller.constants.SERVICE_RELAY, inlined so coordination
#: stays below controller in the layering (guarded by a drift test)
SERVICE_RELAY = "relay"

#: branching factor B of the relay tree (heap arity)
DEFAULT_BRANCHING = int(os.environ.get("EDL_TPU_RELAY_BRANCH", "16"))

# zero-loss accounting for the relay chaos drill: the drill asserts
# reattaches happened AND no event went missing, from metrics not logs
_CHILDREN = obs_metrics.counter(
    "edl_relay_children_total", "distinct children that attached to "
    "this relay")
_FORWARDED = obs_metrics.counter(
    "edl_relay_events_forwarded_total", "events served to children "
    "from the local cache")
_REATTACHES = obs_metrics.counter(
    "edl_relay_reattaches_total", "child-side endpoint switches: a "
    "relay died (or refused) and the attachment moved to the next "
    "ancestor / the direct store path")


def enabled():
    """The kill switch: ``EDL_TPU_RELAY=0`` turns the whole subsystem
    off (no hosting, no attaching — flat direct long-polls)."""
    return os.environ.get("EDL_TPU_RELAY", "1") != "0"


# -- the deterministic tree ---------------------------------------------


def tree_parent(pod_ids, pod_id, branching=None):
    """Parent pod id of ``pod_id`` in the B-ary heap over the sorted
    pod list; None for the root (index 0). Every pod computes the same
    tree from the same cluster map — no negotiation, no tie-breaks."""
    b = int(branching or DEFAULT_BRANCHING)
    ids = sorted(pod_ids)
    i = ids.index(pod_id)
    if i == 0:
        return None
    return ids[(i - 1) // b]


def tree_ancestors(pod_ids, pod_id, branching=None):
    """Ancestor chain parent → root (the reattach candidate order)."""
    out = []
    cur = pod_id
    while True:
        cur = tree_parent(pod_ids, cur, branching)
        if cur is None:
            return out
        out.append(cur)


def tree_depth(n, branching=None):
    """⌈log_B N⌉: levels below the root for an ``n``-pod fleet."""
    b = int(branching or DEFAULT_BRANCHING)
    if n <= 1:
        return 0
    return int(math.ceil(math.log(n) / math.log(b)))


# -- child side: the attachment -----------------------------------------


class RelayAttachment(object):
    """The child half of the protocol: routes a CoordClient's
    long-polls, keepalive beats, and obs publishes through the first
    live, feature-negotiated relay in ``resolver()``'s candidate list
    (parent first, then grandparent, ... root).

    Every method returns None when no relay is usable so the caller
    falls through to its direct store path — attachment failure is
    never an error, only a topology downgrade.  The adopted endpoint
    is sticky: ``resolver()`` is only re-invoked when the current
    endpoint fails (or :meth:`invalidate` is called after a resize),
    so the steady state adds zero store reads.
    """

    def __init__(self, resolver, pod_id=None, timeout=30.0,
                 retry_bad_after=10.0):
        self._resolver = resolver
        self._pod_id = None if pod_id is None else str(pod_id)
        self._timeout = float(timeout)
        self._retry_bad_after = float(retry_bad_after)
        self._lock = threading.Lock()
        self._bad = {}        # endpoint -> monotonic mark time
        self._legacy = set()  # endpoints that lack FEATURE (permanent)
        self._current = None
        self._local = threading.local()

    # -- transport (per-thread clients: a relayed long-poll must not
    # -- serialize against keepalive beats from other threads) ---------

    def _client_for(self, endpoint):
        cache = getattr(self._local, "rpcs", None)
        if cache is None:
            cache = self._local.rpcs = {}
        rpc = cache.get(endpoint)
        if rpc is None:
            rpc = cache[endpoint] = RpcClient(endpoint,
                                              timeout=self._timeout)
        return rpc

    def _drop_client(self, endpoint):
        cache = getattr(self._local, "rpcs", None)
        rpc = cache.pop(endpoint, None) if cache else None
        if rpc is not None:
            rpc.close()

    # -- candidate management ------------------------------------------

    def current(self):
        with self._lock:
            return self._current

    def invalidate(self):
        """Drop the sticky endpoint (topology changed — e.g. a resize
        recomputed the tree); the next call re-resolves candidates."""
        with self._lock:
            self._current = None
            self._bad.clear()

    def _candidates(self):
        try:
            eps = list(self._resolver() or ())
        except Exception as e:  # noqa: BLE001 — resolver is best-effort
            logger.debug("relay resolver failed: %r", e)
            return []
        now = time.monotonic()
        with self._lock:
            out = []
            for ep in eps:
                if ep in self._legacy:
                    continue
                marked = self._bad.get(ep)
                if marked is not None \
                        and now - marked < self._retry_bad_after:
                    continue
                out.append(ep)
            return out

    def _mark_bad(self, endpoint):
        with self._lock:
            self._bad[endpoint] = time.monotonic()
            was_current = self._current == endpoint
            if was_current:
                self._current = None
        self._drop_client(endpoint)
        if was_current:
            # the switch away from a previously-adopted relay IS the
            # reattach the chaos drill counts (whether the next stop is
            # an ancestor or the direct store path)
            _REATTACHES.inc()
            logger.warning("relay %s unusable; reattaching", endpoint)

    def _negotiated(self, endpoint, rpc):
        """Feature negotiation: a registered endpoint that does not
        advertise ``coord.relay`` (a legacy peer) is permanently
        skipped — its children use the direct path."""
        try:
            feats = rpc.server_features()
        except (errors.EdlError, ConnectionError, OSError):
            return False
        if FEATURE not in feats:
            with self._lock:
                self._legacy.add(endpoint)
            return False
        return True

    def _try_endpoint(self, endpoint, adopting, method, args, timeout):
        """(served, result): one attempt against one endpoint."""
        if adopting and faults.PLANE is not None:
            try:
                faults.PLANE.fire("relay.attach", endpoint=endpoint,
                                  pod=self._pod_id or "")
            except Exception:  # noqa: BLE001 — injected attach error
                self._mark_bad(endpoint)
                return False, None
        rpc = self._client_for(endpoint)
        if adopting and not self._negotiated(endpoint, rpc):
            return False, None
        try:
            out = rpc.call(method, *args,
                           timeout=timeout or self._timeout)
        except (errors.EdlError, ConnectionError, OSError):
            self._mark_bad(endpoint)
            return False, None
        if adopting:
            with self._lock:
                self._current = endpoint
        return True, out

    def _call(self, method, *args, timeout=None):
        """One relayed call with ancestor fall-through; None means no
        relay is usable and the caller must go direct. Fast path: the
        sticky adopted endpoint, no resolver invocation; slow path
        (adoption) walks ``resolver()``'s candidates in order."""
        cur = self.current()
        if cur is not None:
            served, out = self._try_endpoint(cur, False, method, args,
                                             timeout)
            if served:
                return out
        for endpoint in self._candidates():
            if endpoint == cur:
                continue
            served, out = self._try_endpoint(endpoint, True, method,
                                             args, timeout)
            if served:
                return out
        return None

    # -- the relayed surface -------------------------------------------

    def wait_events(self, prefix, since_rev, poll_timeout):
        """Relayed long-poll; None → caller falls through direct. The
        child keeps its own ``since_rev`` cursor, so a mid-stream
        reattach resumes exactly where the dead relay left it."""
        return self._call("relay_wait_events", prefix, since_rev,
                          poll_timeout, self._pod_id,
                          timeout=float(poll_timeout) + 30.0)

    def lease_refresh_many(self, lease_ids):
        """Relayed keepalive beat ({lease_id: ok}); None → go direct."""
        pairs = self._call("relay_lease_refresh_many", list(lease_ids),
                           self._pod_id)
        if pairs is None:
            return None
        return {int(lid): bool(ok) for lid, ok in pairs}

    def obs_publish(self, service, key, value):
        """Hand an obs doc to the relay for subtree aggregation; False
        → caller writes the store directly."""
        return bool(self._call("relay_obs_publish", service, key, value,
                               self._pod_id))

    def close(self):
        cache = getattr(self._local, "rpcs", None)
        for rpc in (cache or {}).values():
            try:
                rpc.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        if cache:
            cache.clear()


# -- relay side ----------------------------------------------------------


class _Feed(object):
    """Per-prefix event cache: a rev-ordered window mirrored from the
    upstream watch.  ``floor`` is the oldest rev the cache can replay
    from; a child whose ``since_rev`` fell below it is told to reset
    (re-list) exactly like the store would."""

    __slots__ = ("prefix", "events", "floor", "rev", "waiters",
                 "last_wait", "retired")

    def __init__(self, prefix, since_rev):
        self.prefix = prefix
        self.events = []
        self.floor = since_rev
        self.rev = since_rev
        self.waiters = 0
        self.last_wait = time.monotonic()
        self.retired = False


class WatchRelay(object):
    """One pod's relay: serves children from a local event cache fed
    by ONE upstream long-poll per prefix, coalesces children's lease
    beats into one upstream batch, and folds children's obs docs into
    one ``obs_agg/v1`` doc per tick.

    ``coord``: a CoordClient for DIRECT store access (registration,
    root-level upstream, root-level agg writes).  ``parent_resolver``:
    optional override returning candidate parent endpoints; by default
    ancestors are computed from :meth:`update_tree`'s pod list and the
    ``SERVICE_RELAY`` registry.
    """

    #: events kept per prefix before the floor advances (children
    #: falling further behind re-list, same contract as the store)
    EVENT_HISTORY = 4096
    #: upstream long-poll timeout (a pump holds one of these open)
    UPSTREAM_POLL_S = 20.0
    #: cap on a child's single long-poll wait
    MAX_CHILD_WAIT_S = 60.0
    #: a feed with no waiter for this long retires its pump
    FEED_IDLE_S = 90.0
    #: min gap between upstream lease batches (the coalesce window)
    LEASE_COALESCE_S = 1.0
    #: forget child leases not refreshed through us for this long
    LEASE_FORGET_S = 120.0
    #: drop obs cells whose publisher went silent for this long (far
    #: beyond the staleness detector's threshold, so dead pods are
    #: flagged stale long before their cell disappears)
    CELL_PRUNE_S = 900.0
    #: cache ttl for the default parent-endpoint resolution (bounds
    #: registry reads from the pumps)
    RESOLVE_CACHE_S = 5.0

    def __init__(self, coord, pod_id, branching=None, host="0.0.0.0",
                 service=SERVICE_RELAY, register_ttl=10.0,
                 obs_service="metrics", obs_interval=10.0,
                 parent_resolver=None):
        self._coord = coord
        self._pod_id = str(pod_id)
        self._branching = int(branching or DEFAULT_BRANCHING)
        self._service = service
        self._register_ttl = float(register_ttl)
        self._obs_service = obs_service
        self._obs_interval = float(obs_interval)
        self._agg_key = "obs_agg_" + self._pod_id
        self._rpc = RpcServer(host=host, port=0)
        self._rpc.register("relay_wait_events", self.relay_wait_events)
        self._rpc.register("relay_obs_publish", self.relay_obs_publish)
        self._rpc.register("relay_lease_refresh_many",
                           self.relay_lease_refresh_many)
        self._rpc.register("__features__",
                           lambda: list(FEATURES) + [FEATURE])
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._feeds = {}         # prefix -> _Feed
        self._children = set()   # child ids seen (metrics only)
        self._cells = {}         # obs key -> obs_pub/v1 doc
        self._child_leases = {}  # lease_id -> last monotonic refresh
        self._lease_verdicts = {}
        self._last_lease_beat = 0.0
        self._resolved = (0.0, [])  # (monotonic, endpoints) cache
        self._pod_ids = []
        self._lease = None
        self._stop = threading.Event()
        self._flush_thread = None
        self._retry = RetryPolicy(base_delay=0.25, max_delay=2.0,
                                  multiplier=2.0, jitter=0.5)
        self._up = RelayAttachment(
            parent_resolver if parent_resolver is not None
            else self._parent_endpoints,
            pod_id=self._pod_id)

    # -- lifecycle ------------------------------------------------------

    def start(self, register=True):
        self._rpc.start()
        # cache: the advertised endpoint must stay readable after
        # stop() — kill drills and resolvers hold it as a plain string
        self._endpoint = self._rpc.endpoint
        self._flush_thread = threading.Thread(
            target=self._flush_loop, daemon=True,
            name="relay-obs-%s" % self._pod_id)
        self._flush_thread.start()
        if register:
            self._register()
        return self

    def _register(self):
        from edl_tpu.coordination import keepalive
        try:
            self._lease = self._coord.set_server_with_lease(
                self._service, self._pod_id, self.endpoint,
                self._register_ttl)
            keepalive.hub_for(self._coord).add(
                self._lease, self._register_ttl, on_lost=self._relost)
        except errors.EdlError as e:
            # advertising is best-effort: an unregistered relay simply
            # never gets children; the fleet stays on the direct path
            logger.warning("relay %s failed to register: %r",
                           self._pod_id, e)

    def _relost(self):
        if not self._stop.is_set():
            logger.warning("relay %s registration lease lost; "
                           "re-registering", self._pod_id)
            self._register()

    @property
    def endpoint(self):
        ep = getattr(self, "_endpoint", None)
        return ep if ep is not None else self._rpc.endpoint

    @property
    def port(self):
        return self._rpc.port

    def update_tree(self, pod_ids):
        """Adopt a new cluster map: recompute ancestors and drop the
        sticky upstream so the next pump iteration re-resolves."""
        with self._lock:
            self._pod_ids = sorted(pod_ids)
            self._resolved = (0.0, [])
        self._up.invalidate()

    def stop(self):
        self._stop.set()
        with self._lock:
            for feed in self._feeds.values():
                feed.retired = True
            self._feeds.clear()
            self._cond.notify_all()
        if self._flush_thread is not None:
            self._flush_thread.join(timeout=5.0)
        if self._lease is not None:
            from edl_tpu.coordination import keepalive
            keepalive.hub_for(self._coord).remove(self._lease)
            try:
                self._coord.remove_server(self._service, self._pod_id)
            except errors.EdlError:
                pass
        self._up.close()
        self._rpc.stop()

    # -- upstream resolution -------------------------------------------

    def _parent_endpoints(self):
        """Default resolver: my ancestors' advertised endpoints, parent
        first.  Registry reads are cached for RESOLVE_CACHE_S and only
        happen on the slow path (no sticky upstream)."""
        now = time.monotonic()
        with self._lock:
            at, eps = self._resolved
            if now - at < self.RESOLVE_CACHE_S:
                return list(eps)
            ids = list(self._pod_ids)
        eps = []
        if ids and self._pod_id in ids:
            try:
                reg = dict(self._coord.get_service(self._service))
            except errors.EdlError:
                reg = {}
            for anc in tree_ancestors(ids, self._pod_id,
                                      self._branching):
                ep = reg.get(anc)
                if ep and ep != self.endpoint:
                    eps.append(ep)
        with self._lock:
            self._resolved = (now, list(eps))
        return eps

    def attachment_candidates(self):
        """Candidate list for THIS pod's local clients: the pod-local
        relay first, then its ancestors — so if the local relay dies
        the clients walk the same chain the relay itself would."""
        return [self.endpoint] + self._parent_endpoints()

    def _upstream_wait(self, prefix, since_rev, timeout):
        out = self._up.wait_events(prefix, since_rev, timeout)
        if out is not None:
            return out
        return self._coord.wait_events(prefix, since_rev, timeout,
                                       relay=False)

    # -- downward: the fan-out path ------------------------------------

    def _feed_for(self, prefix, since_rev):
        with self._lock:
            feed = self._feeds.get(prefix)
            if feed is None:
                feed = self._feeds[prefix] = _Feed(prefix, since_rev)
                threading.Thread(
                    target=self._pump, args=(feed,), daemon=True,
                    name="relay-pump-%s" % self._pod_id).start()
            feed.last_wait = time.monotonic()
            return feed

    def _pump(self, feed):
        """ONE upstream long-poll per prefix — the whole point: N
        children share this single store-side (or parent-side) poll."""
        attempts = 0
        while not self._stop.is_set():
            with self._lock:
                if feed.retired:
                    return
                if feed.waiters == 0 and (time.monotonic()
                                          - feed.last_wait
                                          > self.FEED_IDLE_S):
                    feed.retired = True
                    self._feeds.pop(feed.prefix, None)
                    return
                since = feed.rev
            try:
                events, rev = self._upstream_wait(
                    feed.prefix, since, self.UPSTREAM_POLL_S)
            except (errors.EdlError, ConnectionError, OSError) as e:
                attempts += 1
                logger.debug("relay %s pump %s upstream error: %r",
                             self._pod_id, feed.prefix, e)
                self._retry.sleep(min(attempts, 6))
                continue
            attempts = 0
            with self._lock:
                if events and any(e.get("type") == "reset"
                                  for e in events):
                    # upstream lost our position: our whole cache is
                    # unverifiable — raise the floor so every child
                    # re-lists (each from the store, which is exactly
                    # what the store itself would have told them)
                    feed.events = []
                    feed.floor = rev
                    feed.rev = rev
                elif events:
                    feed.events.extend(events)
                    feed.rev = max(feed.rev, rev)
                    overflow = len(feed.events) - self.EVENT_HISTORY
                    if overflow > 0:
                        feed.floor = feed.events[overflow - 1]["rev"]
                        del feed.events[:overflow]
                else:
                    feed.rev = max(feed.rev, rev)
                self._cond.notify_all()

    def relay_wait_events(self, prefix, since_rev, timeout, child=None):
        """The child-facing mirror of ``store_wait_events``: same
        (events, rev) shape, same timeout-means-empty, same synthetic
        reset when ``since_rev`` predates the cache floor."""
        since_rev = int(since_rev)
        if faults.PLANE is not None:
            f = faults.PLANE.fire("relay.forward", prefix=prefix,
                                  child=str(child or ""))
            if f is not None and f.kind == "drop":
                # dropped delivery == timed-out poll; the child keeps
                # its cursor and polls again (no loss, only delay)
                return [], since_rev
        if child:
            with self._lock:
                if child not in self._children:
                    self._children.add(child)
                    _CHILDREN.inc()
        feed = self._feed_for(prefix, since_rev)
        deadline = time.monotonic() + min(float(timeout),
                                          self.MAX_CHILD_WAIT_S)
        with self._lock:
            feed.waiters += 1
            try:
                while True:
                    if feed.retired:
                        # relay shutting down: look like a timeout; the
                        # child's next poll reattaches elsewhere
                        return [], since_rev
                    if since_rev < feed.floor:
                        return ([{"type": "reset", "key": prefix,
                                  "value": None, "rev": feed.rev}],
                                feed.rev)
                    evs = [e for e in feed.events
                           if e["rev"] > since_rev
                           and e.get("key", "").startswith(prefix)]
                    if evs:
                        _FORWARDED.inc(len(evs))
                        return evs, max(feed.rev, since_rev)
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        # never hand back a rev below the child's own
                        # cursor: a lagging cache must not regress it
                        return [], max(feed.rev, since_rev)
                    self._cond.wait(remaining)
            finally:
                feed.waiters -= 1
                feed.last_wait = time.monotonic()

    # -- upward: lease coalescing --------------------------------------

    def _upstream_refresh(self, lease_ids):
        res = self._up.lease_refresh_many(lease_ids)
        if res is None:
            res = self._coord.lease_refresh_many(lease_ids, relay=False)
        return {int(lid): bool(ok) for lid, ok in res.items()}

    def relay_lease_refresh_many(self, lease_ids, child=None):
        """Coalesced keepalive: children's beats are merged into ONE
        upstream ``lease_refresh_many`` per LEASE_COALESCE_S window.
        An id we have no verdict for yet forces a synchronous batch
        (fresh registrations must learn their fate immediately); known
        ids between windows are answered from the cached verdicts —
        one window of staleness, well inside the ttl/3 beat slack."""
        now = time.monotonic()
        ids = [int(lid) for lid in lease_ids]
        with self._lock:
            for lid in ids:
                self._child_leases[lid] = now
            for lid in [l for l, ts in self._child_leases.items()
                        if now - ts > self.LEASE_FORGET_S]:
                del self._child_leases[lid]
                self._lease_verdicts.pop(lid, None)
            need_sync = any(lid not in self._lease_verdicts
                            for lid in ids)
            due = now - self._last_lease_beat >= self.LEASE_COALESCE_S
            batch = (sorted(self._child_leases)
                     if (need_sync or due) else None)
            if batch is not None:
                self._last_lease_beat = now
        if batch is not None:
            verdicts = self._upstream_refresh(batch)
            with self._lock:
                self._lease_verdicts.update(verdicts)
        with self._lock:
            return [[lid, bool(self._lease_verdicts.get(lid, True))]
                    for lid in ids]

    # -- upward: obs aggregation ---------------------------------------

    def relay_obs_publish(self, service, key, value, child=None):
        """Absorb one obs doc (a leaf's ``obs_pub/v1`` or a child
        relay's ``obs_agg/v1``) into the per-pod cell map; the flush
        loop folds the subtree upward."""
        try:
            doc = json.loads(value)
        except (ValueError, TypeError):
            return False
        if not isinstance(doc, dict):
            return False
        with self._lock:
            if service:
                self._obs_service = service
            if doc.get("schema") == "obs_agg/v1":
                for cell_key, cell in (doc.get("pods") or {}).items():
                    if not isinstance(cell, dict):
                        continue
                    prev = self._cells.get(cell_key)
                    if prev is None or ((cell.get("ts") or 0)
                                        >= (prev.get("ts") or 0)):
                        self._cells[cell_key] = cell
            else:
                self._cells[key] = doc
        return True

    def _flush_loop(self):
        while not self._stop.wait(self._obs_interval):
            try:
                self.flush_once()
            except Exception as e:  # noqa: BLE001 — obs is best-effort
                logger.debug("relay %s obs flush failed: %r",
                             self._pod_id, e)

    def flush_once(self):
        """Fold the subtree's cells into one ``obs_agg/v1`` doc and
        push it to the parent relay, or — at the root / with no parent
        reachable — write ONE doc to the store (the N→N/B^depth win)."""
        now = time.time()
        with self._lock:
            for k in [k for k, c in self._cells.items()
                      if now - (c.get("ts") or now) > self.CELL_PRUNE_S]:
                del self._cells[k]
            cells = dict(self._cells)
            service = self._obs_service
        if not cells:
            return None
        agg = {"schema": "obs_agg/v1", "key": self._agg_key, "ts": now,
               "relay": self._pod_id, "pods": cells}
        if self._up.obs_publish(service, self._agg_key,
                                json.dumps(agg)):
            return agg
        # root of the tree (or orphaned mid-relay): merge the per-pod
        # snapshots into a fleet rollup and write a single store doc
        from edl_tpu.obs import metrics as metrics_mod
        snaps = {k: (c.get("metrics") or {}) for k, c in cells.items()}
        agg["fleet"] = metrics_mod.merge_snapshots(snaps)
        self._coord.set_server_permanent(service, self._agg_key,
                                         json.dumps(agg))
        return agg

    # -- introspection (tests / bench) ---------------------------------

    def stats(self):
        with self._lock:
            return {"pod": self._pod_id,
                    "children": len(self._children),
                    "feeds": len(self._feeds),
                    "cells": len(self._cells),
                    "child_leases": len(self._child_leases)}
