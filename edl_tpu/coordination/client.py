"""Coordination client — the API surface the whole control plane talks to.

Mirrors the reference's EtcdClient contract (edl/discovery/etcd_client.py:
51-263): namespaced keys ``/<root>/<service>/nodes/<server>``, TTL-leased
registration, put-if-absent election, guarded transactions, and prefix watches
with add/remove diffing — but speaks to the in-tree Store over framed RPC.
"""

import re
import threading
import uuid

from edl_tpu.robustness.policy import CircuitBreaker, Deadline, RetryPolicy
from edl_tpu.rpc.client import RpcClient
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger

_LEADER_HINT = re.compile(r"leader=([^\s]+)")


def _parse_leader_hint(exc):
    """Extract the leader endpoint from a NotLeaderError detail
    (``not leader: leader=<host:port> term=<n>``); None if unknown."""
    m = _LEADER_HINT.search(str(exc))
    if m and m.group(1) not in ("?", "None"):
        return m.group(1)
    return None


class Watcher(object):
    """Background prefix watch that diffs service membership.

    Calls ``callback(added, removed, all_servers)`` where each is a dict
    server_name -> value, whenever membership/values change (reference parity:
    etcd_client.py:122-155 watch_service add/rm diffing).
    """

    def __init__(self, client, service, callback, poll_timeout=5.0):
        self._client = client
        self._service = service
        self._callback = callback
        self._poll_timeout = poll_timeout
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="coord-watch-%s" % service)
        self._thread.start()

    def _snapshot(self):
        servers, rev = self._client.get_service_with_revision(self._service)
        return dict(servers), rev

    def _run(self):
        current, rev = {}, 0
        first = True
        while not self._stop.is_set():
            try:
                if first:
                    new, rev = self._snapshot()
                    self._diff_and_fire(current, new)
                    current = new
                    first = False
                    continue
                events, new_rev = self._client.wait_events(
                    self._client.service_prefix(self._service), rev,
                    self._poll_timeout)
                if not events:
                    rev = new_rev
                    continue
                if any(e["type"] == "reset" for e in events):
                    new, rev = self._snapshot()
                else:
                    new = dict(current)
                    prefix = self._client.service_prefix(self._service)
                    for e in events:
                        name = e["key"][len(prefix):]
                        if e["type"] == "put":
                            new[name] = e["value"]
                        elif e["type"] == "delete":
                            new.pop(name, None)
                    rev = new_rev
                self._diff_and_fire(current, new)
                current = new
            except errors.EdlError as e:
                logger.warning("watch %s error: %r; re-listing", self._service,
                               e)
                first = True
                self._stop.wait(1.0)
            except Exception:
                logger.exception("watch %s callback failed", self._service)
                self._stop.wait(1.0)

    def _diff_and_fire(self, old, new):
        if self._stop.is_set():  # never fire after stop() was requested
            return
        added = {k: v for k, v in new.items()
                 if k not in old or old[k] != v}
        removed = {k: v for k, v in old.items() if k not in new}
        if added or removed:
            self._callback(added, removed, dict(new))

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=self._poll_timeout + 2)


class CoordClient(object):
    def __init__(self, endpoints, root="edl", timeout=60.0,
                 failover_grace=15.0):
        if isinstance(endpoints, str):
            endpoints = [e for e in endpoints.split(",") if e]
        self._endpoints = list(endpoints)
        if not self._endpoints:
            raise errors.ConnectError("no coordination endpoints given")
        self._root = root
        self._timeout = timeout
        # how long a call keeps retrying endpoint rotation when EVERY
        # endpoint refuses — covers both the primary-death ->
        # standby-promote window (standby.py) and a replica-set election
        # (replica.py); single-endpoint clients fail fast on ConnectError
        self._failover_grace = failover_grace
        # per-thread per-endpoint connections: a watcher's long-poll must
        # not block lease-refresh heartbeats issued from other threads
        self._local = threading.local()
        self._ep_lock = threading.Lock()
        self._leader = None        # last NotLeader redirect hint
        self._features = {}        # endpoint -> frozenset of features
        # per-endpoint breaker: a dead replica stops eating a dial
        # timeout on every single call while the set stays degraded
        self._breakers = CircuitBreaker(failure_threshold=3,
                                        reset_timeout=2.0)
        # jittered backoff between rotation rounds: desyncs the herd of
        # control-plane clients that would otherwise re-dial a dead
        # primary in lockstep every 0.5s
        self._retry = RetryPolicy(base_delay=0.25, max_delay=2.0,
                                  multiplier=2.0, jitter=0.5)
        # optional relay.RelayAttachment: long-polls, keepalive beats
        # and obs publishes ride the fan-out tree when one is attached;
        # every relayed path falls through to the direct store path the
        # moment the attachment declines (None) or no relay answers
        self._relay_att = None

    # -- relay attachment ----------------------------------------------------

    def attach_relay(self, attachment):
        """Route ``wait_events`` / ``lease_refresh_many`` /
        ``publish_obs`` through a relay tree (coordination/relay.py).
        Reads, writes, registrations and transactions stay direct —
        only the O(N)-per-beat traffic is worth relaying."""
        self._relay_att = attachment
        return attachment

    def detach_relay(self):
        att, self._relay_att = self._relay_att, None
        return att

    @property
    def relay_attachment(self):
        return self._relay_att

    # -- key namespace ------------------------------------------------------

    def service_prefix(self, service):
        return "/%s/%s/nodes/" % (self._root, service)

    def server_key(self, service, server):
        """The raw store key for a (service, server) pair — for callers
        composing guarded txns over service keys (e.g. leader stop)."""
        return self.service_prefix(service) + server

    _key = server_key

    @property
    def root(self):
        return self._root

    # -- transport ----------------------------------------------------------

    def _client_for(self, endpoint):
        """This thread's cached connection to ``endpoint`` (dialed lazily).
        Returns (client, was_cached)."""
        rpcs = getattr(self._local, "rpcs", None)
        if rpcs is None:
            rpcs = self._local.rpcs = {}
        rpc = rpcs.get(endpoint)
        if rpc is not None:
            return rpc, True
        rpc = rpcs[endpoint] = RpcClient(endpoint, timeout=self._timeout)
        return rpc, False

    def _drop_client(self, endpoint):
        rpcs = getattr(self._local, "rpcs", None)
        rpc = rpcs.pop(endpoint, None) if rpcs else None
        if rpc is not None:
            rpc.close()
        with self._ep_lock:
            self._features.pop(endpoint, None)

    def _supports(self, endpoint, rpc, feature):
        with self._ep_lock:
            feats = self._features.get(endpoint)
        if feats is None:
            feats = frozenset(rpc.server_features())
            with self._ep_lock:
                self._features[endpoint] = feats
        return feature in feats

    def _round_endpoints(self):
        """One rotation round: the last known leader first, then every
        other configured endpoint."""
        with self._ep_lock:
            leader = self._leader
            eps = list(self._endpoints)
        if leader is not None and leader in eps:
            eps.remove(leader)
            eps.insert(0, leader)
        elif leader is not None:
            # a redirect may point outside the configured list (replica
            # advertised endpoint): dial it, but keep the configured
            # set as fallback
            eps.insert(0, leader)
        return eps

    def _call(self, method, *args, **kwargs):
        deadline = kwargs.pop("deadline", None)  # caller's Deadline budget
        # idempotency key: generated once per logical op by the public
        # method and preserved across every re-dial / redirect below, so
        # a retry that straddles a failover cannot double-apply
        op_id = kwargs.pop("op_id", None)
        last = None
        grace = None
        rounds = 0
        redirects = 0
        while True:
            fast_redirect = False
            for endpoint in self._round_endpoints():
                if not self._breakers.allow(endpoint):
                    continue
                # a stale cached connection (severed by a server restart)
                # costs one attempt; the fresh reconnect deserves its own
                # — and a stale-conn error must not open the breaker
                hint = None
                for _ in range(2):
                    rpc, was_cached = self._client_for(endpoint)
                    call_kwargs = dict(kwargs)
                    try:
                        if op_id is not None and self._supports(
                                endpoint, rpc, "store.txn_dedup"):
                            call_kwargs["op_id"] = op_id
                        out = rpc.call(method, *args, deadline=deadline,
                                       **call_kwargs)
                        self._breakers.record_success(endpoint)
                        return out
                    except errors.NotLeaderError as e:
                        # the endpoint is healthy — it just isn't the
                        # leader; follow its redirect
                        self._breakers.record_success(endpoint)
                        last = e
                        hint = _parse_leader_hint(e)
                        with self._ep_lock:
                            self._leader = hint
                        break
                    except errors.ConnectError as e:
                        last = e
                        self._drop_client(endpoint)
                        with self._ep_lock:
                            if self._leader == endpoint:
                                self._leader = None
                        if not was_cached:
                            self._breakers.record_failure(endpoint)
                            break
                if hint is not None and hint != endpoint and redirects < 3:
                    # restart the round leader-first, without the backoff
                    # sleep (bounded, so a redirect ping-pong between two
                    # confused replicas degrades into the jittered path)
                    redirects += 1
                    fast_redirect = True
                    break
            if fast_redirect:
                continue
            if len(self._endpoints) < 2 and \
                    not isinstance(last, errors.NotLeaderError):
                raise last
            if last is None:
                last = errors.CircuitOpenError(
                    "all coordination endpoints circuit-open")
            # multi-endpoint deployments have a FAILOVER WINDOW: the
            # leader is gone but no successor has promoted/been elected
            # yet. Retrying rotation rounds for a bounded grace keeps
            # control-plane calls alive across the takeover instead of
            # surfacing a transient outage.
            rounds += 1
            if grace is None:
                grace = Deadline(self._failover_grace)
            budget = grace if deadline is None else grace.union(deadline)
            if not self._retry.sleep(rounds, budget):
                raise last

    # -- raw KV -------------------------------------------------------------

    def put(self, key, value, lease_id=None):
        return self._call("store_put", key, value, lease_id)

    def get_key(self, key):
        return self._call("store_get", key)

    def get_prefix_raw(self, prefix):
        """Raw (kv dicts incl. lease_id, revision) under a raw-key
        prefix — the replication primitive (standby.py)."""
        return self._call("store_get_prefix", prefix)

    def delete(self, key):
        return self._call("store_delete", key)

    def revision(self):
        return self._call("store_revision")

    def wait_events(self, prefix, since_rev, poll_timeout, relay=True):
        """Long-poll for events under ``prefix`` past ``since_rev``.

        Rides the relay tree when an attachment is present (``relay=
        False`` forces the direct store path — the relays themselves
        use it for their upstream polls so a tree can never loop).
        Because the caller keeps its own ``since_rev``, the fall-
        through mid-stream is lossless: the direct poll resumes exactly
        where the dead relay left off."""
        att = self._relay_att
        if relay and att is not None:
            out = att.wait_events(prefix, since_rev, poll_timeout)
            if out is not None:
                return out
        return self._call("store_wait_events", prefix, since_rev,
                          poll_timeout, timeout=poll_timeout + 30)

    # -- leases --------------------------------------------------------------

    def lease_grant(self, ttl):
        # idempotency key: a retry that straddles a failover must not
        # grant two leases for one logical registration
        return self._call("store_lease_grant", ttl,
                          op_id=uuid.uuid4().hex)

    def lease_refresh(self, lease_id):
        return self._call("store_lease_refresh", lease_id)

    def lease_refresh_many(self, lease_ids, relay=True):
        """Batched keepalive; returns {lease_id: ok}. Rides the relay
        tree when attached (the relay coalesces children's beats into
        one upstream batch; ``relay=False`` is the relays' own
        loop-free upstream path). Falls back to per-id refreshes
        against peers that predate the batched RPC (feature
        ``store.lease_refresh_many``)."""
        lease_ids = list(lease_ids)
        if not lease_ids:
            return {}
        att = self._relay_att
        if relay and att is not None:
            res = att.lease_refresh_many(lease_ids)
            if res is not None:
                return res
        try:
            pairs = self._call("store_lease_refresh_many", lease_ids)
            return {int(lid): bool(ok) for lid, ok in pairs}
        except errors.RpcError as e:
            if "no such method" not in str(e):
                raise
        return {lid: bool(self.lease_refresh(lid)) for lid in lease_ids}

    def lease_revoke(self, lease_id):
        return self._call("store_lease_revoke", lease_id)

    # -- service registry (reference etcd_client.py surface) -----------------

    def get_service(self, service):
        """[(server_name, value)] sorted by server name."""
        servers, _ = self.get_service_with_revision(service)
        return servers

    def get_service_with_revision(self, service):
        kvs, rev = self._call("store_get_prefix",
                              self.service_prefix(service))
        prefix = self.service_prefix(service)
        return [(kv["key"][len(prefix):], kv["value"]) for kv in kvs], rev

    def get_value(self, service, server):
        kv = self.get_key(self._key(service, server))
        return None if kv is None else kv["value"]

    def set_server_permanent(self, service, server, value):
        return self.put(self._key(service, server), value)

    def publish_obs(self, service, server, value):
        """Publish an observability doc: hand it to the relay tree for
        subtree aggregation when attached (one ``obs_agg/v1`` store
        write per subtree per tick instead of one per pod), else write
        it directly like ``set_server_permanent`` always did."""
        att = self._relay_att
        if att is not None and att.obs_publish(service, server, value):
            return True
        self.set_server_permanent(service, server, value)
        return True

    def set_server_not_exists(self, service, server, value, ttl):
        """Put-if-absent with a fresh TTL lease — the election primitive.

        Returns the lease_id on success, None if the key already exists
        (reference parity: etcd_client.py:177-197).
        """
        lease_id = self.lease_grant(ttl)
        ok, _ = self._call("store_put_if_absent", self._key(service, server),
                           value, lease_id, op_id=uuid.uuid4().hex)
        if not ok:
            self.lease_revoke(lease_id)
            return None
        return lease_id

    def set_server_with_lease(self, service, server, value, ttl):
        """Unconditional TTL-leased registration; returns lease_id."""
        lease_id = self.lease_grant(ttl)
        self.put(self._key(service, server), value, lease_id)
        return lease_id

    def refresh_server(self, service, server, lease_id):
        """Refresh the lease keeping a registration alive.

        Raises LeaseExpiredError if the lease (and hence the registration)
        has already expired — the caller must re-register or die.
        """
        if not self.lease_refresh(lease_id):
            raise errors.LeaseExpiredError(
                "lease %s for %s/%s expired" % (lease_id, service, server))

    def remove_server(self, service, server):
        return self.delete(self._key(service, server))

    def watch_service(self, service, callback, poll_timeout=5.0):
        return Watcher(self, service, callback, poll_timeout=poll_timeout)

    # -- transactions ---------------------------------------------------------

    def txn(self, compares, on_success, on_failure=()):
        return self._call("store_txn", list(compares), list(on_success),
                          list(on_failure), op_id=uuid.uuid4().hex)

    def put_if_leader(self, leader_service, leader_server, leader_value,
                      puts):
        """Commit ``puts`` [(key, value)] iff the leader key still holds
        ``leader_value`` — the guarded-transaction idiom of the reference
        (cluster_generator.py:223-250, state.py:186-200)."""
        ok, _ = self.txn(
            [(self._key(leader_service, leader_server), "value_eq",
              leader_value)],
            [("put", k, v) for k, v in puts])
        return ok

    # -- maintenance ----------------------------------------------------------

    def clean_root(self):
        """Delete every key under this client's root (test isolation;
        reference parity: constants.clean_etcd)."""
        return self._call("store_delete_prefix", "/%s/" % self._root)
