"""Locate/build/launch the native (C++) coordination store server.

native/store_server.cc implements the identical wire protocol and store
semantics as the Python StoreServer; CoordClient works against either. The
native binary is the production deployment (one static binary per cluster,
replacing the external etcd of the reference — SURVEY.md §2.6).
"""

import os
import subprocess
import time

from edl_tpu.utils.logger import logger
from edl_tpu.utils.network import find_free_port, is_server_alive

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
NATIVE_DIR = os.path.join(_REPO, "native")
BINARY = os.path.join(NATIVE_DIR, "build", "edl_tpu_store")


def ensure_binary():
    """Return the binary path, (re)building via make — a no-op when the
    build is already up to date; serialized across processes (see
    edl_tpu.utils.buildlock)."""
    from edl_tpu.utils.buildlock import locked_make
    locked_make(NATIVE_DIR, "build/edl_tpu_store",
                what="native store server")
    return BINARY


class NativeStoreServer(object):
    """Run the C++ store as a subprocess; context-manager friendly."""

    def __init__(self, host="127.0.0.1", port=0, data_dir=None):
        self._host = host
        self._port = port or find_free_port()
        self._data_dir = data_dir
        self._proc = None

    def start(self, wait_s=10):
        binary = ensure_binary()
        cmd = [binary, "--host", self._host, "--port", str(self._port)]
        if self._data_dir:
            os.makedirs(self._data_dir, exist_ok=True)
            cmd += ["--data-dir", self._data_dir]
        self._proc = subprocess.Popen(
            cmd, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            if is_server_alive(self.endpoint, timeout=0.5):
                return self
            if self._proc.poll() is not None:
                raise RuntimeError("native store exited with %d"
                                   % self._proc.returncode)
            time.sleep(0.05)
        raise RuntimeError("native store did not come up on %s"
                           % self.endpoint)

    @property
    def endpoint(self):
        return "%s:%d" % (self._host, self._port)

    def stop(self):
        if self._proc is not None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
            self._proc = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
