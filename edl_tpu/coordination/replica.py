"""Quorum-replicated coordination store (raft-lite).

Turns the single-process :class:`~edl_tpu.coordination.store.Store` into a
3-replica replicated state machine:

* a durable **replication log** (:class:`ReplLog`) layered on the same
  JSON-lines record format as the Store WAL (one fsynced line per append,
  torn-tail truncation on replay, full-rewrite compaction);
* a **leader** holding a store-internal lease-based term appends every
  mutating op (put / delete / txn / lease grant / revoke — coalesced
  keepalives stay OFF the log), streams ``repl.append`` entries to
  followers over the pipelined RPC plane, and acks the client only after
  a quorum has fsynced; the commit index advances monotonically and
  followers apply strictly in order, so failover never loses an
  acknowledged write and never resurrects an unacknowledged one;
* **leader election**: randomized-timeout candidacy with term fencing
  (persisted term + vote), a no-op entry asserted on election so the new
  leader can commit, and the raft commit rule (only entries from the
  current term advance the commit index by counting);
* **linearizable reads from followers** via read-index confirmation: the
  follower asks the leader for a confirmed commit index and serves the
  read only once its applied index has caught up;
* **snapshot install** for lagging or wiped replicas, reusing the Store
  snapshot/rewrite machinery (``snapshot_state``/``install_snapshot``).

The module is dependency-free beyond the in-tree rpc plane and is exercised
hermetically by ``tests/test_replication.py`` and the ``store_bench --micro``
failover arc.  The witness/standby pair in ``standby.py`` remains as the
1-replica fallback for deployments that cannot afford three processes.
"""

from __future__ import annotations

import argparse
import base64
import json
import logging
import os
import random
import threading
import time
from collections import OrderedDict

from ..obs import events as obs_events
from ..obs import metrics as obs_metrics
from ..robustness import faults
from ..robustness.policy import Deadline
from ..rpc.pool import ClientPool
from ..rpc.server import RpcServer
from ..utils import errors
from .store import Store

log = logging.getLogger("edl_tpu.coordination.replica")

_PROPOSE_MS = obs_metrics.histogram(
    "edl_repl_propose_ms", "leader propose -> quorum-applied latency",
    labels=("kind",))
_APPLIED_INDEX = obs_metrics.gauge(
    "edl_repl_applied_index", "last log index applied to the local "
    "state machine")
_ELECTIONS = obs_metrics.counter(
    "edl_repl_elections_total", "elections this replica won")

# Dedicated ClientPool channel so replication traffic (appends, votes,
# snapshots) never queues behind client-facing store calls.
REPL_CHANNEL = "repl"

# Election timeouts (seconds).  Heartbeat period is min/5.  Tests override
# with much smaller values; production default targets sub-second failover.
ELECTION_TIMEOUT = (0.75, 1.5)

# How many applied entries the log may trail the snapshot by before the
# leader/follower compacts its own log.
COMPACT_THRESHOLD = 2048

# Replicated dedup table size (client op_id -> result).
DEDUP_CAP = 4096

# Per-index local result cache (leader-side, for acking proposers).
RESULT_CAP = 1024


def _enc(obj):
    """JSON-encode helper: bytes -> {"__b64__": ...} recursively."""
    if isinstance(obj, bytes):
        return {"__b64__": base64.b64encode(obj).decode("ascii")}
    if isinstance(obj, (list, tuple)):
        return [_enc(x) for x in obj]
    if isinstance(obj, dict):
        return {k: _enc(v) for k, v in obj.items()}
    return obj


def _dec(obj):
    if isinstance(obj, dict):
        if set(obj) == {"__b64__"}:
            return base64.b64decode(obj["__b64__"])
        return {k: _dec(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_dec(x) for x in obj]
    return obj


class ReplLog:
    """Durable replication log: JSON lines, one fsync per append batch.

    Uses the same record style as the Store WAL (self-describing JSON
    objects, newline-delimited, torn trailing record tolerated and
    truncated on replay).  The log may begin after a snapshot: records

        {"op": "snap", "index": i, "term": t, "state": {...}}
        {"op": "ent", "index": i, "term": t, "kind": ..., "args": [...]}

    ``base_index``/``base_term`` describe the entry immediately before
    ``entries[0]`` (the snapshot point, or 0/0 for an empty prefix).
    """

    def __init__(self, path=None):
        self.path = path
        self.base_index = 0
        self.base_term = 0
        self.snapshot = None          # store snapshot dict at base_index
        self.entries = []             # list of {"index","term","kind","args"}
        self._f = None
        if path:
            self._replay()
            self._open()

    # -- durability ----------------------------------------------------

    def _open(self):
        self._f = open(self.path, "ab")

    def _replay(self):
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            raw = f.read()
        lines = raw.split(b"\n")
        offset = 0
        torn_at = None
        for i, line in enumerate(lines):
            if not line.strip():
                offset += len(line) + 1
                continue
            try:
                rec = json.loads(line.decode("utf-8"))
                op = rec["op"]
                if op == "snap":
                    self.base_index = int(rec["index"])
                    self.base_term = int(rec["term"])
                    self.snapshot = _dec(rec["state"])
                    self.entries = []
                elif op == "ent":
                    ent = {"index": int(rec["index"]),
                           "term": int(rec["term"]),
                           "kind": rec["kind"],
                           "args": _dec(rec.get("args") or [])}
                    # a rewritten suffix after truncate_from may overlap
                    while self.entries and \
                            self.entries[-1]["index"] >= ent["index"]:
                        self.entries.pop()
                    self.entries.append(ent)
                else:
                    raise ValueError("unknown op %r" % (op,))
            except (ValueError, KeyError, TypeError) as e:
                if i >= len(lines) - 2:
                    log.warning("repl log %s: torn trailing record "
                                "(%s); truncating", self.path, e)
                else:
                    log.error("repl log %s: corrupt record at byte %d "
                              "(%s); discarding it and all later records",
                              self.path, offset, e)
                torn_at = offset
                break
            offset += len(line) + 1
        if torn_at is not None:
            with open(self.path, "rb+") as f:
                f.truncate(torn_at)
                f.flush()
                os.fsync(f.fileno())

    def _write(self, recs, fsync=True):
        if self._f is None:
            return
        buf = b"".join(
            json.dumps(r, separators=(",", ":")).encode("utf-8") + b"\n"
            for r in recs)
        self._f.write(buf)
        self._f.flush()
        if fsync:
            os.fsync(self._f.fileno())

    # -- index math ----------------------------------------------------

    @property
    def last_index(self):
        return self.entries[-1]["index"] if self.entries else self.base_index

    @property
    def last_term(self):
        return self.entries[-1]["term"] if self.entries else self.base_term

    def term_at(self, index):
        """Term of entry at ``index``; None if compacted away/unknown."""
        if index == self.base_index:
            return self.base_term
        ent = self.get(index)
        return None if ent is None else ent["term"]

    def get(self, index):
        i = index - self.base_index - 1
        if 0 <= i < len(self.entries):
            return self.entries[i]
        return None

    def slice_from(self, index):
        """Entries with index >= ``index`` (must not be compacted)."""
        i = index - self.base_index - 1
        return self.entries[max(i, 0):]

    # -- mutation ------------------------------------------------------

    def append(self, ents, fsync=True):
        self.entries.extend(ents)
        self._write([{"op": "ent", "index": e["index"], "term": e["term"],
                      "kind": e["kind"], "args": _enc(e["args"])}
                     for e in ents], fsync=fsync)

    def truncate_from(self, index):
        """Drop entries with index >= ``index`` (conflict resolution).

        Rewrites the on-disk log so the divergent suffix cannot
        resurrect on restart.
        """
        i = index - self.base_index - 1
        if i < 0:
            i = 0
        if i >= len(self.entries):
            return
        self.entries = self.entries[:i]
        self._rewrite()

    def compact(self, index, term, snapshot):
        """Install ``snapshot`` at (index, term), dropping covered entries."""
        kept = [e for e in self.entries if e["index"] > index]
        self.base_index = index
        self.base_term = term
        self.snapshot = snapshot
        self.entries = kept
        self._rewrite()

    def reset(self, index, term, snapshot):
        """Wholesale replace with a snapshot (install from leader)."""
        self.base_index = index
        self.base_term = term
        self.snapshot = snapshot
        self.entries = []
        self._rewrite()

    def _rewrite(self):
        if not self.path:
            return
        if self._f is not None:
            self._f.close()
            self._f = None
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            recs = []
            if self.snapshot is not None or self.base_index:
                recs.append({"op": "snap", "index": self.base_index,
                             "term": self.base_term,
                             "state": _enc(self.snapshot)})
            recs.extend({"op": "ent", "index": e["index"],
                         "term": e["term"], "kind": e["kind"],
                         "args": _enc(e["args"])} for e in self.entries)
            for r in recs:
                f.write(json.dumps(r, separators=(",", ":"))
                        .encode("utf-8") + b"\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        d = os.open(os.path.dirname(os.path.abspath(self.path)),
                    os.O_RDONLY)
        try:
            os.fsync(d)
        finally:
            os.close(d)
        self._open()

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None


class ReplMeta:
    """Persistent per-replica metadata: current term + vote (fsynced on
    every change, as raft requires) and the commit index (lazily persisted
    — safe because commit is recomputed from quorum state on recovery)."""

    def __init__(self, path=None):
        self.path = path
        self.term = 0
        self.voted_for = None
        self.commit = 0
        if path and os.path.exists(path):
            try:
                with open(path, "r") as f:
                    d = json.load(f)
                self.term = int(d.get("term", 0))
                self.voted_for = d.get("voted_for")
                self.commit = int(d.get("commit", 0))
            except (ValueError, KeyError, TypeError):
                log.warning("repl meta %s unreadable; starting fresh",
                            path)

    def save(self, fsync=True):
        if not self.path:
            return
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"term": self.term, "voted_for": self.voted_for,
                       "commit": self.commit}, f)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        os.replace(tmp, self.path)


# Features a replica advertises on top of the rpc-plane set.
FEATURES = ("store.repl", "store.txn_dedup", "store.lease_refresh_many")


class ReplicatedStoreServer(object):
    """One replica of the quorum-replicated coordination store.

    ``endpoint`` is this replica's advertised ``host:port`` and must
    appear in ``peers`` (the full, odd-sized replica set).  All replicas
    run the same code; roles (follower / candidate / leader) emerge from
    the election protocol.
    """

    def __init__(self, endpoint, peers, data_dir=None, host=None,
                 election_timeout=ELECTION_TIMEOUT, quorum_timeout=5.0,
                 heartbeat=None):
        if endpoint not in peers:
            raise ValueError("endpoint %s not in replica set %r"
                             % (endpoint, peers))
        if len(peers) % 2 == 0:
            raise ValueError("replica set size must be odd, got %d"
                             % len(peers))
        self.endpoint = endpoint
        self.replica_set = list(peers)
        self.peers = [p for p in peers if p != endpoint]
        self.quorum = len(peers) // 2 + 1
        self._et = tuple(election_timeout)
        self._hb = heartbeat if heartbeat is not None else self._et[0] / 5.0
        self._quorum_timeout = quorum_timeout

        log_path = meta_path = None
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            log_path = os.path.join(data_dir, "repl.log")
            meta_path = os.path.join(data_dir, "repl.meta")
        # Replicated state machine: revisions are seeded at 0 and leases
        # never expire locally — every replica applies the identical
        # entry sequence, so every replica holds the identical store.
        self.store = Store(wal_path=None, expire_leases=False, seed_rev=0)
        self.log = ReplLog(log_path)
        self.meta = ReplMeta(meta_path)

        self._mu = threading.RLock()
        self._apply_cond = threading.Condition(self._mu)
        self._prop_lock = threading.Lock()   # serializes proposes
        self._repl_lock = threading.Lock()   # serializes replicate rounds
        self._stop = threading.Event()
        self._thread = None

        self._role = "follower"
        self._leader = None
        self._applied = self.log.base_index
        self._dedup = OrderedDict()   # op_id -> [result], replicated
        self._results = {}            # index -> [result], leader-local acks
        self._match = {}
        self._next = {}
        self._lease_hint = 1
        self._quorum_ok_at = 0.0
        self._reset_timer()

        # recovery: snapshot, then the committed prefix of the log; the
        # uncommitted tail stays on disk and lives or dies by the
        # current leader's log-matching checks.
        if self.log.snapshot is not None:
            self._install_state(self.log.snapshot)
            self._applied = self.log.base_index
        self.meta.commit = max(self.log.base_index,
                               min(self.meta.commit, self.log.last_index))
        with self._mu:
            self._apply_upto_locked(self.meta.commit)
        # any watcher holding a pre-restart revision must re-list
        self.store.seed_revision_above(self.store.revision())

        bind_host = host or endpoint.rsplit(":", 1)[0]
        port = int(endpoint.rsplit(":", 1)[1])
        self._rpc = RpcServer(host=bind_host, port=port)
        self._pool = ClientPool(timeout=max(2.0, self._et[0] * 2.0))
        from ..rpc import server as rpc_server
        self._rpc.register(
            "__features__",
            lambda: list(rpc_server.FEATURES) + list(FEATURES))
        for name in ("put", "put_if_absent", "get", "get_prefix",
                     "delete", "delete_prefix", "txn", "wait_events",
                     "lease_grant", "lease_refresh", "lease_refresh_many",
                     "lease_revoke", "revision"):
            self._rpc.register("store_" + name,
                               getattr(self, "store_" + name))
        self._rpc.register("repl_append", self.repl_append)
        self._rpc.register("repl_vote", self.repl_vote)
        self._rpc.register("repl_snapshot", self.repl_snapshot)
        self._rpc.register("repl_read_index", self.repl_read_index)
        self._rpc.register("repl_status", self.repl_status)
        self._rpc.register("repl_log", self.repl_log_dump)

    # -- lifecycle -----------------------------------------------------

    def start(self):
        self._rpc.start()
        self._thread = threading.Thread(
            target=self._ticker, name="repl-ticker", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        with self._apply_cond:
            self._apply_cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._rpc.stop()
        self._pool.close()
        self.store.close()
        self.log.close()

    @property
    def port(self):
        return self._rpc.port

    # -- small helpers -------------------------------------------------

    def _reset_timer(self):
        self._heard = time.monotonic()
        self._deadline = self._heard + random.uniform(*self._et)

    def _not_leader(self):
        leader = self._leader or "?"
        return errors.NotLeaderError(
            "not leader: leader=%s term=%d" % (leader, self.meta.term))

    def _fire(self, point, **ctx):
        """Fire a store.repl.* fault point; a returned site-handled
        fault (drop) makes the message vanish as a ConnectError."""
        if faults.PLANE is not None:
            f = faults.PLANE.fire(point, **ctx)
            if f is not None:
                raise errors.ConnectError(
                    "fault: %s at %s" % (f.kind, point))

    def _install_state(self, snap):
        self.store.install_snapshot(snap["store"])
        self._dedup = OrderedDict(
            (k, v) for k, v in (snap.get("dedup") or []))

    def _step_down(self, term):
        # caller holds _mu
        if term > self.meta.term:
            self.meta.term = term
            self.meta.voted_for = None
            self.meta.save()
        if self._role == "leader":
            log.warning("replica %s: stepping down at term %d",
                        self.endpoint, term)
            obs_events.emit("store.stepdown", endpoint=self.endpoint,
                            term=term)
            self._leader = None
        self._role = "follower"
        self._reset_timer()
        self._apply_cond.notify_all()   # wake blocked proposers/readers

    # -- state machine apply -------------------------------------------

    def _apply_upto_locked(self, commit):
        """Apply log entries up to ``commit`` (caller holds _mu, or is
        the single-threaded recovery path)."""
        while self._applied < commit:
            idx = self._applied + 1
            ent = self.log.get(idx)
            if ent is None:
                break
            if faults.PLANE is not None:
                faults.PLANE.fire("store.repl.apply", index=idx,
                                  kind=ent["kind"])
            res = self._apply_one(ent)
            self._applied = idx
            op_id = ent.get("op_id")
            if op_id is not None:
                self._dedup[op_id] = [res]
                while len(self._dedup) > DEDUP_CAP:
                    self._dedup.popitem(last=False)
            self._results[idx] = [res]
            if len(self._results) > RESULT_CAP:
                drop = len(self._results) - RESULT_CAP
                for k in sorted(self._results)[:drop]:
                    self._results.pop(k, None)
        _APPLIED_INDEX.set(self._applied)
        self._apply_cond.notify_all()

    def _apply_one(self, ent):
        op_id = ent.get("op_id")
        if op_id is not None and op_id in self._dedup:
            # the same client op was logged twice (a retry straddling a
            # failover): apply once, replay the first result
            return self._dedup[op_id][0]
        kind = ent["kind"]
        a = ent["args"]
        s = self.store
        if kind == "noop":
            return None
        if kind == "put":
            return s.put(a[0], a[1], a[2])
        if kind == "put_if_absent":
            ok, rev = s.put_if_absent(a[0], a[1], a[2])
            return [ok, rev]
        if kind == "delete":
            return s.delete(a[0])
        if kind == "delete_prefix":
            return s.delete_prefix(a[0])
        if kind == "txn":
            ok, rev = s.txn(a[0], a[1], a[2])
            return [ok, rev]
        if kind == "lease_grant":
            return s.lease_grant(a[0], lease_id=a[1])
        if kind == "lease_revoke":
            return s.lease_revoke(a[0])
        if kind == "lease_expire":
            for lid in a[0]:
                s.lease_revoke(lid)
            return None
        log.error("unknown log entry kind %r at index %d",
                  kind, ent["index"])
        return None

    # -- leader: propose + replicate -----------------------------------

    def _propose(self, kind, args, op_id=None, wait=True):
        self._fire("store.repl.propose", kind=kind)
        t0 = time.monotonic()
        with self._prop_lock:
            with self._mu:
                if op_id is not None and op_id in self._dedup:
                    return self._dedup[op_id][0]
                if self._role != "leader":
                    raise self._not_leader()
                term = self.meta.term
                idx = self.log.last_index + 1
                ent = {"index": idx, "term": term, "kind": kind,
                       "args": args}
                if op_id is not None:
                    ent["op_id"] = op_id
                self.log.append([ent])          # local fsync
                self._match[self.endpoint] = idx
            self._replicate_round()
        if not wait:
            return None
        dl = Deadline(self._quorum_timeout)
        with self._apply_cond:
            while self._applied < idx:
                if self.meta.term != term or self._role != "leader":
                    raise self._not_leader()
                if self._stop.is_set():
                    raise errors.StopError("replica stopping")
                if dl.expired():
                    raise errors.DeadlineExceededError(
                        "no quorum for %s within %.1fs"
                        % (kind, self._quorum_timeout))
                self._apply_cond.wait(min(0.1, max(dl.remaining(), 0.01)))
            res = self._results.pop(idx, None)
        _PROPOSE_MS.labels(kind).observe((time.monotonic() - t0) * 1e3)
        if res is not None:
            return res[0]
        if op_id is not None:
            with self._mu:
                cached = self._dedup.get(op_id)
            if cached is not None:
                return cached[0]
        return None

    def _replicate_round(self):
        """One append fan-out: ships pending entries (or an empty
        heartbeat) to every peer, advances match/next and the commit
        index on quorum. Doubles as the heartbeat."""
        with self._repl_lock:
            with self._mu:
                if self._role != "leader":
                    return
                term = self.meta.term
                commit = self.meta.commit
                plan = {}
                for p in self.peers:
                    nxt = self._next.get(p, self.log.last_index + 1)
                    prev = nxt - 1
                    pterm = self.log.term_at(prev)
                    if pterm is None:
                        plan[p] = None          # compacted away: snapshot
                        continue
                    ents = [dict(e) for e in self.log.slice_from(nxt)]
                    plan[p] = (prev, pterm, ents)
            futs = {}
            sent = {}
            for p, spec in plan.items():
                if spec is None:
                    self._send_snapshot(p, term)
                    continue
                prev, pterm, ents = spec
                sent[p] = prev + len(ents)
                try:
                    futs[p] = self._pool.call_async(
                        p, "repl_append", term, self.endpoint, prev,
                        pterm, ents, commit, channel=REPL_CHANNEL)
                except errors.EdlError:
                    self._pool.retire(p, channel=REPL_CHANNEL)
            acks = 1                            # self, already fsynced
            for p, fut in futs.items():
                try:
                    r = fut.result(timeout=max(0.5, self._hb * 4))
                except errors.EdlError:
                    self._pool.retire(p, channel=REPL_CHANNEL)
                    continue
                with self._mu:
                    if int(r.get("term", 0)) > self.meta.term:
                        self._step_down(int(r["term"]))
                        return
                    if r.get("ok"):
                        self._match[p] = int(r["match"])
                        self._next[p] = self._match[p] + 1
                        acks += 1
                    elif r.get("need_snap"):
                        self._next[p] = 0       # forces snapshot next round
                    else:
                        self._next[p] = max(1, int(r.get("hint", 1)))
            with self._mu:
                if self._role != "leader" or self.meta.term != term:
                    return
                if acks >= self.quorum:
                    self._quorum_ok_at = time.monotonic()
                matched = sorted(self._match.get(ep, 0)
                                 for ep in self.replica_set)
                cand = matched[len(self.replica_set) - self.quorum]
                if cand > self.meta.commit and \
                        self.log.term_at(cand) == term:
                    self.meta.commit = cand
                    self.meta.save(fsync=False)
                self._apply_upto_locked(self.meta.commit)

    def _send_snapshot(self, peer, term):
        with self._mu:
            if self._role != "leader" or self.meta.term != term:
                return
            idx = self._applied
            sterm = self.log.term_at(idx)
            state = {"store": self.store.snapshot_state(),
                     "dedup": [[k, v] for k, v in self._dedup.items()]}
        if sterm is None:
            return
        log.warning("replica %s: installing snapshot@%d on %s",
                    self.endpoint, idx, peer)
        try:
            r = self._pool.call(peer, "repl_snapshot", term,
                                self.endpoint, idx, sterm, state,
                                channel=REPL_CHANNEL)
        except errors.EdlError:
            self._pool.retire(peer, channel=REPL_CHANNEL)
            return
        with self._mu:
            if int(r.get("term", 0)) > self.meta.term:
                self._step_down(int(r["term"]))
                return
            if r.get("ok"):
                self._match[peer] = idx
                self._next[peer] = idx + 1

    # -- election ------------------------------------------------------

    def _campaign(self):
        with self._mu:
            self._role = "candidate"
            self._leader = None
            self.meta.term += 1
            self.meta.voted_for = self.endpoint
            self.meta.save()
            term = self.meta.term
            li, lt = self.log.last_index, self.log.last_term
            self._reset_timer()
        log.info("replica %s: campaigning in term %d", self.endpoint,
                 term)
        futs = {}
        for p in self.peers:
            try:
                futs[p] = self._pool.call_async(
                    p, "repl_vote", term, self.endpoint, li, lt,
                    channel=REPL_CHANNEL)
            except errors.EdlError:
                self._pool.retire(p, channel=REPL_CHANNEL)
        votes = 1
        for p, fut in futs.items():
            try:
                r = fut.result(timeout=max(0.5, self._et[0]))
            except errors.EdlError:
                self._pool.retire(p, channel=REPL_CHANNEL)
                continue
            with self._mu:
                if int(r.get("term", 0)) > self.meta.term:
                    self._step_down(int(r["term"]))
                    return
            if r.get("granted"):
                votes += 1
        became = False
        with self._mu:
            if self._role == "candidate" and self.meta.term == term \
                    and votes >= self.quorum:
                self._become_leader_locked(term)
                became = True
        if became:
            self._replicate_round()

    def _become_leader_locked(self, term):
        log.warning("replica %s: elected leader for term %d "
                    "(commit=%d applied=%d last=%d)", self.endpoint,
                    term, self.meta.commit, self._applied,
                    self.log.last_index)
        self._role = "leader"
        self._leader = self.endpoint
        _ELECTIONS.inc()
        obs_events.emit("store.leader_elected", endpoint=self.endpoint,
                        term=term, commit=self.meta.commit,
                        applied=self._applied)
        nxt = self.log.last_index + 1
        self._next = {p: nxt for p in self.peers}
        self._match = {p: 0 for p in self.peers}
        self._quorum_ok_at = 0.0
        # lease-id hint: stay above every granted id, including grants
        # the previous leader logged but we have not applied yet
        hint = self.store.snapshot_state()["next_lease"]
        for e in self.log.entries:
            if e["kind"] == "lease_grant":
                hint = max(hint, int(e["args"][1]) + 1)
        self._lease_hint = hint
        # assert leadership with a no-op so this term can commit, then
        # give every lease one full ttl of grace before expiry
        self.log.append([{"index": nxt, "term": term, "kind": "noop",
                          "args": []}])
        self._match[self.endpoint] = nxt
        self.store.rearm_leases()

    # -- ticker --------------------------------------------------------

    def _ticker(self):
        while not self._stop.is_set():
            try:
                self._tick()
            except errors.EdlError as e:
                log.warning("replica %s: tick error: %s", self.endpoint,
                            e)
            except Exception:
                log.exception("replica %s: tick failed", self.endpoint)
            self._stop.wait(self._hb)

    def _tick(self):
        with self._mu:
            role = self._role
            overdue = time.monotonic() >= self._deadline
        if role == "leader":
            self._housekeeping()
            self._replicate_round()
        elif overdue:
            self._campaign()
        self._maybe_compact()

    def _housekeeping(self):
        # only the leader turns expired leases into logged revokes, so
        # every replica applies identical deletions in identical order
        dead = self.store.expired_leases()
        if dead:
            try:
                self._propose("lease_expire", [dead], wait=False)
            except errors.EdlError as e:
                log.warning("replica %s: lease expiry propose failed: "
                            "%s", self.endpoint, e)

    def _maybe_compact(self):
        with self._mu:
            if self._applied - self.log.base_index <= COMPACT_THRESHOLD:
                return
            t = self.log.term_at(self._applied)
            if t is None:
                return
            snap = {"store": self.store.snapshot_state(),
                    "dedup": [[k, v] for k, v in self._dedup.items()]}
            self.log.compact(self._applied, t, snap)

    # -- replication RPC surface (replica <-> replica) -----------------

    def repl_append(self, term, leader, prev_index, prev_term, entries,
                    commit):
        self._fire("store.repl.append", term=term, leader=leader,
                   n=len(entries))
        term, prev_index, prev_term = \
            int(term), int(prev_index), int(prev_term)
        with self._mu:
            if term < self.meta.term:
                return {"ok": False, "term": self.meta.term}
            if term > self.meta.term or self._role != "follower":
                self._step_down(term)
            self._leader = leader
            self._reset_timer()
            if prev_index > self.log.last_index:
                return {"ok": False, "term": self.meta.term,
                        "hint": self.log.last_index + 1}
            lterm = self.log.term_at(prev_index)
            if lterm is None:
                # prev predates our snapshot: ask for a fresh install
                return {"ok": False, "term": self.meta.term,
                        "need_snap": True,
                        "hint": self.log.base_index + 1}
            if lterm != prev_term:
                self.log.truncate_from(prev_index)
                return {"ok": False, "term": self.meta.term,
                        "hint": prev_index}
            new = [e for e in entries
                   if int(e["index"]) > self.log.last_index]
            for e in entries:
                i = int(e["index"])
                if i <= self.log.last_index:
                    have = self.log.get(i)
                    if have is not None and have["term"] != e["term"]:
                        self.log.truncate_from(i)
                        new = [x for x in entries
                               if int(x["index"]) >= i]
                        break
            if new:
                self.log.append(new)            # one fsync for the batch
            match = prev_index + len(entries)
            newc = min(int(commit), match)
            if newc > self.meta.commit:
                self.meta.commit = newc
                self.meta.save(fsync=False)
            self._apply_upto_locked(self.meta.commit)
            return {"ok": True, "term": self.meta.term, "match": match}

    def repl_vote(self, term, candidate, last_index, last_term):
        self._fire("store.repl.vote", term=term, candidate=candidate)
        term, last_index, last_term = \
            int(term), int(last_index), int(last_term)
        with self._mu:
            if term < self.meta.term:
                return {"granted": False, "term": self.meta.term}
            if term > self.meta.term:
                self._step_down(term)
                self._leader = None
            up_to_date = (last_term, last_index) >= \
                (self.log.last_term, self.log.last_index)
            if up_to_date and self.meta.voted_for in (None, candidate):
                self.meta.voted_for = candidate
                self.meta.save()
                self._reset_timer()
                return {"granted": True, "term": self.meta.term}
            return {"granted": False, "term": self.meta.term}

    def repl_snapshot(self, term, leader, index, snap_term, state):
        self._fire("store.repl.snapshot", term=term, index=index)
        term, index, snap_term = int(term), int(index), int(snap_term)
        with self._mu:
            if term < self.meta.term:
                return {"ok": False, "term": self.meta.term}
            if term > self.meta.term or self._role != "follower":
                self._step_down(term)
            self._leader = leader
            self._reset_timer()
            if index <= self._applied:
                return {"ok": True, "term": self.meta.term}
            self._install_state(state)
            self.log.reset(index, snap_term, state)
            self._applied = index
            self.meta.commit = max(self.meta.commit, index)
            self.meta.save()
            self._apply_cond.notify_all()
            return {"ok": True, "term": self.meta.term}

    def repl_read_index(self):
        """Leader-only: a commit index guaranteed current at call time.

        Cheap within the leader lease (a fresh quorum round-trip was
        seen under election_timeout_min * 0.8 ago); otherwise forces a
        heartbeat round to re-confirm leadership before answering.
        """
        lease = self._et[0] * 0.8
        with self._mu:
            if self._role != "leader":
                raise self._not_leader()
            if time.monotonic() - self._quorum_ok_at < lease:
                return {"index": self.meta.commit}
        self._replicate_round()
        with self._mu:
            if self._role != "leader" or \
                    time.monotonic() - self._quorum_ok_at >= lease:
                raise self._not_leader()
            return {"index": self.meta.commit}

    def repl_status(self):
        with self._mu:
            return {"endpoint": self.endpoint, "role": self._role,
                    "term": self.meta.term, "leader": self._leader,
                    "commit": self.meta.commit, "applied": self._applied,
                    "last_index": self.log.last_index,
                    "base_index": self.log.base_index}

    def repl_log_dump(self, since=0):
        """Committed entries after ``since`` — the raw material for the
        linearizability check in tests and store_bench."""
        with self._mu:
            ents = [dict(e) for e in self.log.entries
                    if int(since) < e["index"] <= self.meta.commit]
            return {"base_index": self.log.base_index,
                    "commit": self.meta.commit, "entries": ents}

    # -- client-facing store surface -----------------------------------

    def _linearize(self):
        """Read-index protocol: block until this replica has applied at
        least the cluster commit index observed at call time."""
        with self._mu:
            role = self._role
            leader = self._leader
        if role == "leader":
            idx = self.repl_read_index()["index"]
        else:
            if not leader or leader == self.endpoint:
                raise self._not_leader()
            try:
                idx = self._pool.call(
                    leader, "repl_read_index",
                    channel=REPL_CHANNEL)["index"]
            except errors.NotLeaderError:
                raise
            except errors.EdlError:
                with self._mu:
                    self._leader = None
                raise errors.NotLeaderError(
                    "not leader: leader=? term=%d" % self.meta.term)
        dl = Deadline(self._quorum_timeout)
        with self._apply_cond:
            while self._applied < idx:
                if dl.expired():
                    raise errors.DeadlineExceededError(
                        "read-index %d not applied (at %d)"
                        % (idx, self._applied))
                self._apply_cond.wait(min(0.1, max(dl.remaining(),
                                                   0.01)))

    def store_put(self, key, value, lease_id=None, op_id=None):
        return self._propose("put", [key, value, lease_id], op_id=op_id)

    def store_put_if_absent(self, key, value, lease_id=None, op_id=None):
        return self._propose("put_if_absent", [key, value, lease_id],
                             op_id=op_id)

    def store_delete(self, key, op_id=None):
        return self._propose("delete", [key], op_id=op_id)

    def store_delete_prefix(self, prefix, op_id=None):
        return self._propose("delete_prefix", [prefix], op_id=op_id)

    def store_txn(self, compares, on_success, on_failure=(), op_id=None):
        return self._propose(
            "txn", [list(compares), list(on_success), list(on_failure)],
            op_id=op_id)

    def store_lease_grant(self, ttl, op_id=None):
        # the leader assigns the lease id at propose time so every
        # replica's lease table stays identical
        with self._mu:
            if self._role != "leader":
                raise self._not_leader()
            lid = self._lease_hint
            self._lease_hint = lid + 1
        return self._propose("lease_grant", [float(ttl), lid],
                             op_id=op_id)

    def store_lease_revoke(self, lease_id, op_id=None):
        return self._propose("lease_revoke", [int(lease_id)],
                             op_id=op_id)

    def store_lease_refresh(self, lease_id):
        # keepalives stay OFF the log: only the leader tracks deadlines,
        # and expiry reaches followers as a logged lease_expire
        with self._mu:
            if self._role != "leader":
                raise self._not_leader()
        return self.store.lease_refresh(lease_id)

    def store_lease_refresh_many(self, lease_ids):
        with self._mu:
            if self._role != "leader":
                raise self._not_leader()
        return self.store.lease_refresh_many(lease_ids)

    def store_get(self, key):
        self._linearize()
        return self.store.get(key)

    def store_get_prefix(self, prefix):
        self._linearize()
        return self.store.get_prefix(prefix)

    def store_revision(self):
        self._linearize()
        return self.store.revision()

    def store_wait_events(self, prefix, since_rev, timeout):
        # watches are served locally on any replica: a lagging follower
        # just delivers events a beat later, and a watcher whose rev
        # predates this replica's floor gets a reset and re-lists
        return self.store.wait_events(prefix, since_rev, timeout)


def start_local_replica_set(n=3, data_dir=None, host="127.0.0.1",
                            election_timeout=(0.3, 0.6), **kw):
    """Spin up an in-process n-replica set on free ports (tests/bench)."""
    from ..utils.network import find_free_ports
    ports = find_free_ports(n)
    eps = ["%s:%d" % (host, p) for p in ports]
    reps = []
    for i, ep in enumerate(eps):
        dd = os.path.join(data_dir, "r%d" % i) if data_dir else None
        reps.append(ReplicatedStoreServer(
            ep, eps, data_dir=dd,
            election_timeout=election_timeout, **kw).start())
    return reps


def wait_for_leader(replicas, timeout=10.0):
    """Block until exactly one live replica leads; returns it."""
    dl = Deadline(timeout)
    tick = threading.Event()
    while True:
        for r in replicas:
            with r._mu:
                if r._role == "leader" and not r._stop.is_set():
                    return r
        if dl.expired():
            raise errors.DeadlineExceededError(
                "no leader elected within %.1fs" % timeout)
        tick.wait(0.02)


def main(argv=None):
    ap = argparse.ArgumentParser(
        "edl_tpu replicated coordination store replica")
    ap.add_argument("--endpoint", required=True,
                    help="advertised host:port of THIS replica")
    ap.add_argument("--peers", required=True,
                    help="comma-separated replica set "
                         "(all endpoints, including this one)")
    ap.add_argument("--data_dir", default=None,
                    help="directory for the replication log + meta")
    ap.add_argument("--host", default=None,
                    help="bind host (default: host from --endpoint)")
    ap.add_argument("--election_min", type=float,
                    default=ELECTION_TIMEOUT[0])
    ap.add_argument("--election_max", type=float,
                    default=ELECTION_TIMEOUT[1])
    args = ap.parse_args(argv)
    import signal
    server = ReplicatedStoreServer(
        args.endpoint, [p for p in args.peers.split(",") if p],
        data_dir=args.data_dir, host=args.host,
        election_timeout=(args.election_min, args.election_max)).start()
    log.info("replica %s serving (peers=%s)", args.endpoint, args.peers)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
